#!/bin/bash
# Retry TPU contact until the single-client tunnel comes back, then run the
# full Mosaic-compile probe (tools/tpu_probe.py) once and exit 0.
cd /root/repo
for i in $(seq 1 40); do
  echo "attempt $i: $(date -u +%H:%M:%S)" >> tpu_watch.log
  timeout 900 python -u tools/tpu_probe.py > tpu_probe.out 2> tpu_probe.err
  if grep -q '"on_tpu": true' tpu_probe.out 2>/dev/null; then
    echo "TPU UP at $(date -u +%H:%M:%S)" >> tpu_watch.log
    exit 0
  fi
  sleep 240
done
echo "gave up $(date -u +%H:%M:%S)" >> tpu_watch.log
exit 1
