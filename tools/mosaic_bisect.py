"""Dev harness: compile candidate kernel fragments under Mosaic to locate
unsupported ops. Run on the real chip:  python tools/mosaic_bisect.py
"""
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, T, K = 256, 512, 8
_NEG_BIG = -(2**31) + 1


def run_case(name, body):
    def kernel(x_ref, o_ref):
        o_ref[:] = body(x_ref[:])

    t0 = time.perf_counter()
    try:
        x = jnp.arange(S * T, dtype=jnp.float32).reshape(S, T) % 37.0
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((S, K), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(x)
        out.block_until_ready()
        print(json.dumps({name: "ok", "s": round(time.perf_counter() - t0, 1)}),
              flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({name: f"{type(e).__name__}: {e}"[:300],
                          "s": round(time.perf_counter() - t0, 1)}), flush=True)


def case_min(d2):
    m = jnp.min(d2, axis=1)
    return jnp.broadcast_to(m[:, None], (S, K))


def case_i32_row_bcast_s64(d2):
    # minimal repro of the round-5 probe crash (tpu_compile_helper exit 1
    # on `vector.broadcast vector<1x128xi32> -> vector<64x128xi32>`): an
    # i32 [1, 128] row broadcast to 64 sublanes and sliced. The production
    # kernels no longer contain this op class (fold_tile_into_candidates
    # records lane positions instead of broadcasting an id row); this case
    # documents/confirms the trigger in isolation
    ids = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    idb = jnp.broadcast_to(ids, (64, T))
    blk = jax.lax.slice_in_dim(idb, 0, 128, axis=1)          # [64, 128]
    v = jnp.max(blk, axis=1).astype(jnp.float32)             # [64]
    return jnp.broadcast_to(jnp.max(v)[None, None], (S, K)) + d2[:, :K] * 0.0


def case_lane_extract(d2):
    lane = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
    m = jnp.min(d2, axis=1)
    # lsk: allow[float-eq] min-lane extraction repro: m IS an element of d2,
    is_min = d2 == m[:, None]  # so bitwise equality is exact by construction
    ml = jnp.min(jnp.where(is_min, lane, T), axis=1)
    sel = is_min & (lane == ml[:, None])
    mid = jnp.max(jnp.where(sel, lane, _NEG_BIG), axis=1)
    return jnp.broadcast_to(mid[:, None].astype(jnp.float32), (S, K))


def case_roll_concat(d2):
    cd2 = d2[:, :K]
    roll = jnp.concatenate([cd2[:, :1], cd2[:, :-1]], axis=1)
    return roll


def case_insert(d2):
    cd2 = d2[:, :K]
    m = jnp.min(d2, axis=1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (S, K), 1)
    pos = jnp.sum((cd2 <= m[:, None]).astype(jnp.int32), axis=1)
    roll = jnp.concatenate([cd2[:, :1], cd2[:, :-1]], axis=1)
    ins = jnp.where(cols < pos[:, None], cd2,
                    jnp.where(cols == pos[:, None], m[:, None], roll))
    return ins


def case_while(d2):
    def cond(c):
        return c[0]

    def body(c):
        _, d2, cd2 = c
        m = jnp.min(d2, axis=1)
        improved = m < cd2[:, -1]
        # lsk: allow[float-eq] m is jnp.min(d2): equality is exact by construction
        d2 = jnp.where((d2 == m[:, None]) & improved[:, None], jnp.inf, d2)
        cd2 = jnp.where(improved[:, None], jnp.minimum(cd2, m[:, None]), cd2)
        go = jnp.any(jnp.min(d2, axis=1) < cd2[:, -1])
        return go, d2, cd2

    cd2 = d2[:, :K] + 100.0
    go0 = jnp.any(jnp.min(d2, axis=1) < cd2[:, -1])
    _, _, cd2 = jax.lax.while_loop(cond, body, (go0, d2, cd2))
    return cd2


def case_full_fold(d2):
    from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
        fold_tile_into_candidates,
    )
    cd2 = jnp.full((S, K), jnp.inf, jnp.float32)
    cidx = jnp.full((S, K), -1, jnp.int32)
    cd2, cidx = fold_tile_into_candidates(d2, 0, cd2, cidx)
    return cd2


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    for nm, fn in [("min", case_min), ("lane_extract", case_lane_extract),
                   ("roll_concat", case_roll_concat), ("insert", case_insert),
                   ("while", case_while), ("full_fold", case_full_fold),
                   ("i32_row_bcast_s64", case_i32_row_bcast_s64)]:
        run_case(nm, fn)
