"""TPU engine/tile tuning sweep — run on a real chip to pick defaults.

One measurement per (engine, n, knobs) combination, each in its OWN
subprocess so a Mosaic compile failure or tunnel hang costs only that cell
(the axon tunnel is single-client: never run two of these concurrently).

The sweep is the crossed grid the reference effectively hand-tuned for its
launch geometry (1024-wide blocks, unorderedDataVariant.cu:199-203):
bucket_size x LSK_CHUNK_LANES x k, at a mid size that compiles fast, then a
confirmation pass of the best cells at the full 1M config. Every cell
records pair_evals (the pair budget the bucket size buys) and vector-MFU
next to qps, and exactly recomputes 16 sampled outputs — a cell only
reports a number for a CORRECT result.

    python tools/tpu_tune.py             # crossed sweep + 1M confirms
    python tools/tpu_tune.py --quick     # k=8 sweep only, no confirms

Env: TUNE_N (sweep size, default 500k), TUNE_N_K100 (default 250k),
TUNE_TIMEOUT_S (per cell, default 600), TUNE_CONFIRM_N (default 1M).
Use the results to reset KnnConfig defaults (docs/TUNING.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mpi_cuda_largescaleknn_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_cache)

# Children inherit the env: repeated-geometry cells skip XLA compile.
enable_persistent_cache()

# report lives at the repo root regardless of invocation cwd (the --cells
# merge must find the checkpointed report it protects)
REPORT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tpu_tune_report.json")

_CHILD = r"""
import json, sys, time
import numpy as np

spec = json.loads(sys.argv[1])
import jax

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

n, k = spec["n"], spec["k"]
pts = np.random.default_rng(7).random((n, 3)).astype(np.float32)
cfg = KnnConfig(k=k, engine=spec["engine"],
                bucket_size=spec.get("bucket_size", 512),
                point_group=spec.get("point_group", 1),
                query_tile=spec.get("query_tile", 2048),
                point_tile=spec.get("point_tile", 2048))
model = UnorderedKNN(cfg, mesh=get_mesh(1))
t0 = time.perf_counter()
out = model.run(pts)
compile_s = time.perf_counter() - t0
best, ring_s = float("inf"), None
for _ in range(2):
    model.timers.phases.clear()
    t0 = time.perf_counter()
    out = model.run(pts)
    dt = time.perf_counter() - t0
    if dt < best:
        best = dt
        ring_s = model.timers.report().get("ring", {}).get("seconds")
assert np.all(np.isfinite(out))
from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
verify_sample(pts, out, k, 16)
from mpi_cuda_largescaleknn_tpu.obs.cost import cost_report
devs = jax.devices()
cr = cost_report((model.last_stats or {}).get("pair_evals", 0),
                 ring_s or best, devs[0].platform,
                 getattr(devs[0], "device_kind", None))
print("RESULT " + json.dumps({
    **spec, "platform": devs[0].platform,
    "compile_s": round(compile_s, 2), "seconds": round(best, 4),
    "device_seconds": ring_s, "qps": round(n / best, 1),
    "pair_evals_per_query": round(cr["pair_evals"] / n, 1), **cr}),
    flush=True)
"""

BUCKETS = (128, 256, 512)
LANES = ("1024", "2048", "4096")


def _cells(quick: bool):
    n8 = int(os.environ.get("TUNE_N", 500_000))
    n100 = int(os.environ.get("TUNE_N_K100", 250_000))
    cells = []
    # the crossed grid, k=8 (headline config's k)
    for b in BUCKETS:
        for lanes in LANES:
            cells.append({"engine": "pallas_tiled", "n": n8, "k": 8,
                          "bucket_size": b, "env": {"LSK_CHUNK_LANES": lanes}})
    # decoupled prune/tile geometry: fine query buckets, coarse point side
    # (escapes the bucket-size diagonal — docs/TUNING.md point_group row).
    # pair_budget_report.json (CPU-measured, platform-independent): at an
    # equal 512-lane tile, 64/G8 scores ~3x fewer pairs than 512/G1
    # 64/G1 is the measured pair-budget winner (2,215 pairs/query) but its
    # 64-lane tiles pad to 128 (2x lane waste); 64/G2 hits T=128 exactly —
    # both compete with the wider-tile cells only the chip can rank
    for b, g in ((128, 4), (128, 8), (64, 1), (64, 2), (64, 4), (64, 8),
                 (64, 16), (256, 2)):
        cells.append({"engine": "pallas_tiled", "n": n8, "k": 8,
                      "bucket_size": b, "point_group": g,
                      "env": {"LSK_CHUNK_LANES": "2048"}})
    # engine sanity rows at the sweep size
    cells.append({"engine": "tiled", "n": n8, "k": 8, "bucket_size": 512})
    cells.append({"engine": "pallas", "n": min(n8, 200_000), "k": 8,
                  "query_tile": 256, "point_tile": 2048})
    if quick:
        return cells
    # k=100 regime (the reference's canonical k, README.md:30-33): the fold
    # pays up to k+1 extract-min passes per cold chunk, so the best cell can
    # differ from k=8's — cross bucket_size, keep the lane midpoint fixed
    for b in BUCKETS:
        cells.append({"engine": "pallas_tiled", "n": n100, "k": 100,
                      "bucket_size": b, "env": {"LSK_CHUNK_LANES": "2048"}})
    cells.append({"engine": "pallas_tiled", "n": n100, "k": 100,
                  "bucket_size": 64, "point_group": 8,
                  "env": {"LSK_CHUNK_LANES": "2048"}})
    cells.append({"engine": "tiled", "n": n100, "k": 100, "bucket_size": 512})
    return cells


def _run_cell(spec, results):
    """Run one cell and checkpoint the report: a tunnel outage mid-sweep
    must not lose the cells already measured."""
    env = dict(os.environ)
    # spec["env"] stays in the spec (and the RESULT line) so cells that
    # differ only by env knobs remain distinguishable in the report
    env.update(spec.get("env", {}))
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", _CHILD, json.dumps(spec)],
            timeout=float(os.environ.get("TUNE_TIMEOUT_S", 600)),
            capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired:
        print(json.dumps({**spec, "error": "timeout"}), flush=True)
        return
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith("RESULT ")), None)
    if r.returncode != 0 or line is None:
        print(json.dumps({**spec,
                          "error": (r.stderr or "no output")[-400:]}),
              flush=True)
    else:
        results.append(json.loads(line[len("RESULT "):]))
        print(json.dumps(results[-1]), flush=True)
    with open(REPORT_PATH, "w") as f:
        json.dump(results, f, indent=1)


def main() -> int:
    quick = "--quick" in sys.argv
    if "--cells" in sys.argv:
        # targeted re-runs (e.g. cells a tunnel outage killed mid-sweep):
        # JSON file of spec dicts; successful rows merge into the existing
        # checkpointed report instead of restarting the whole grid
        idx = sys.argv.index("--cells") + 1
        if idx >= len(sys.argv):
            sys.stderr.write("usage: tpu_tune.py --cells <specs.json>\n")
            return 2
        with open(sys.argv[idx]) as f:
            specs = json.load(f)

        def _key(row):
            # identity of a measurement cell = its full spec (qps etc.
            # are results, not identity)
            return json.dumps(
                {kk: row.get(kk) for kk in
                 ("engine", "n", "k", "bucket_size", "point_group",
                  "query_tile", "point_tile", "env", "confirm")},
                sort_keys=True)

        rerun = {_key(s) for s in specs}
        prior_rows = {}
        try:
            with open(REPORT_PATH) as f:
                loaded = [r for r in json.load(f) if "qps" in r]
            # stale rows being re-measured leave the live list, but stay
            # at hand: a failed re-run must NOT delete a checkpointed
            # measurement an outage makes unrepeatable
            prior_rows = {_key(r): r for r in loaded}
            results = [r for r in loaded if _key(r) not in rerun]
        except (OSError, ValueError):
            results = []
        for spec in specs:
            n_before = len(results)
            _run_cell(spec, results)
            if len(results) == n_before and _key(spec) in prior_rows:
                results.append(prior_rows[_key(spec)])
                with open(REPORT_PATH, "w") as f:
                    json.dump(results, f, indent=1)
        return 0
    results = []
    for spec in _cells(quick):
        _run_cell(spec, results)

    if not quick:
        # confirm the best measured cells at the full headline size
        confirm_n = int(os.environ.get("TUNE_CONFIRM_N", 1_000_000))
        for k in (8, 100):
            swept = [r for r in results
                     if r.get("k") == k and r.get("engine") == "pallas_tiled"
                     and "qps" in r]
            for r in sorted(swept, key=lambda r: -r["qps"])[:2]:
                spec = {kk: r[kk] for kk in
                        ("engine", "k", "bucket_size", "point_group", "env")
                        if kk in r}
                _run_cell({**spec, "n": confirm_n, "confirm": True}, results)
    return 0


if __name__ == "__main__":
    sys.exit(main())
