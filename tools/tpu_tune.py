"""TPU engine/tile tuning sweep — run on a real chip to pick defaults.

One measurement per (engine, n, knobs) combination, each in its OWN
subprocess so a Mosaic compile failure or tunnel hang costs only that cell
(the axon tunnel is single-client: never run two of these concurrently).

    python tools/tpu_tune.py             # sweep, prints one JSON line/cell
    python tools/tpu_tune.py --quick     # smaller sweep

Use the results to set KnnConfig defaults and the bench engine.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_CHILD = r"""
import json, sys, time
import numpy as np

spec = json.loads(sys.argv[1])
import jax

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

n, k = spec["n"], spec["k"]
pts = np.random.default_rng(7).random((n, 3)).astype(np.float32)
cfg = KnnConfig(k=k, engine=spec["engine"],
                bucket_size=spec.get("bucket_size", 512),
                query_tile=spec.get("query_tile", 2048),
                point_tile=spec.get("point_tile", 2048))
model = UnorderedKNN(cfg, mesh=get_mesh(1))
t0 = time.perf_counter()
out = model.run(pts)
compile_s = time.perf_counter() - t0
best = float("inf")
for _ in range(2):
    t0 = time.perf_counter()
    out = model.run(pts)
    best = min(best, time.perf_counter() - t0)
assert np.all(np.isfinite(out))
print("RESULT " + json.dumps({
    **spec, "platform": jax.devices()[0].platform,
    "compile_s": round(compile_s, 2), "seconds": round(best, 4),
    "qps": round(n / best, 1)}), flush=True)
"""


def main() -> int:
    quick = "--quick" in sys.argv
    sizes = [100_000] if quick else [100_000, 1_000_000]
    cells = []
    for n in sizes:
        for engine, knobs in [
            ("pallas_tiled", {"bucket_size": 256}),
            ("pallas_tiled", {"bucket_size": 512}),
            ("pallas_tiled", {"bucket_size": 512,
                              "env": {"LSK_CHUNK_LANES": "1024"}}),
            ("pallas_tiled", {"bucket_size": 512,
                              "env": {"LSK_CHUNK_LANES": "4096"}}),
            ("pallas_tiled", {"bucket_size": 1024}),
            ("tiled", {"bucket_size": 512}),
            ("tiled", {"bucket_size": 1024}),
            ("pallas", {"query_tile": 256, "point_tile": 2048}),
            ("bruteforce", {}),
        ]:
            if engine == "bruteforce" and n > 200_000:
                continue  # O(N^2): hopeless at 1M
            cells.append({"engine": engine, "n": n, "k": 8, **knobs})
    # the k=100 regime (BASELINE configs #2-#4): merge cost scales with k
    cells.append({"engine": "pallas_tiled", "n": sizes[0], "k": 100,
                  "bucket_size": 512})
    cells.append({"engine": "tiled", "n": sizes[0], "k": 100,
                  "bucket_size": 512})

    results = []
    for spec in cells:
        env = dict(os.environ)
        # spec["env"] stays in the spec (and the RESULT line) so cells that
        # differ only by env knobs remain distinguishable in the report
        env.update(spec.get("env", {}))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _CHILD, json.dumps(spec)],
                timeout=float(os.environ.get("TUNE_TIMEOUT_S", 600)),
                capture_output=True, text=True, env=env)
        except subprocess.TimeoutExpired:
            print(json.dumps({**spec, "error": "timeout"}), flush=True)
            continue
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("RESULT ")), None)
        if r.returncode != 0 or line is None:
            print(json.dumps({**spec,
                              "error": (r.stderr or "no output")[-400:]}),
                  flush=True)
        else:
            results.append(json.loads(line[len("RESULT "):]))
            print(json.dumps(results[-1]), flush=True)
    with open("tpu_tune_report.json", "w") as f:
        json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
