#!/usr/bin/env python3
"""Load generator for the kNN serving layer (stdlib only, importable).

Closed loop (default): N workers fire back-to-back requests — measures the
server's saturated throughput and the latency it costs. Open loop: requests
fire on a fixed-rate schedule regardless of completions — measures latency
at a target offered load, the regime where queueing (and admission's 429
shedding) actually shows. Both report q/s, rows/s and p50/p95/p99 from the
same obs/timers.py LatencyHistogram the server exports on /metrics, so
client-side and server-side percentiles line up bucket-for-bucket.

Workloads (``--workload``): ``uniform`` draws every query independently in
[0, scale)^3 — spatially incoherent traffic, the radius prune's worst case.
``clustered`` draws ``--blobs`` Gaussian blob centers from the same box
(``--scale`` stands in for the index bounding box — match it to the data)
and each REQUEST samples one blob with ``--blob-sigma`` spread: the
one-user-one-region pattern the serving engine's Morton-sorted multi-bucket
traversal exists to exploit (``serve_smoke.py --locality-bench`` drives
both and compares tile counts). ``sweep`` drifts a blob window along the
box diagonal over ``--sweep-period`` seconds: the hot region MOVES, so a
tiered slab index (serve/slabpool.py) churns through real
eviction/readmission cycles — clustered/uniform never evict once warm
(``serve_smoke.py --streaming-bench`` drives it).

    python tools/loadgen.py --url http://127.0.0.1:8080 --duration 10 \
        --concurrency 8 --batch 16 [--qps 500] [--workload clustered] \
        [--neighbors] [--out rep.json]
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import threading
import time
import urllib.request
from urllib.parse import urlparse

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run as a file

from mpi_cuda_largescaleknn_tpu.obs.timers import LatencyHistogram  # noqa: E402


class _Client:
    """One worker's persistent HTTP/1.1 connection to the server.

    The server speaks keep-alive; reusing one socket per worker drops the
    per-request TCP connect AND the per-connection handler thread the
    threading server would otherwise spawn — so the measurement (and any
    real client) pays for kNN, not connection churn. Any transport error
    tears the socket down and the next request reconnects.
    """

    def __init__(self, url: str, timeout_s: float):
        p = urlparse(url if "//" in url else "//" + url)
        self._https = p.scheme == "https"
        self._host = p.hostname or "127.0.0.1"
        self._port = p.port or (443 if self._https else 80)
        #: URL path prefix, kept so a reverse-proxied server
        #: (http://host/prefix -> /prefix/knn) still routes
        self._prefix = p.path.rstrip("/")
        self._timeout_s = timeout_s
        self._conn: http.client.HTTPConnection | None = None

    def _request(self, path: str, body: bytes, ctype: str):
        if self._conn is None:
            conn_cls = (http.client.HTTPSConnection if self._https
                        else http.client.HTTPConnection)
            self._conn = conn_cls(
                self._host, self._port, timeout=self._timeout_s)
        path = self._prefix + path
        try:
            self._conn.request("POST", path, body=body,
                               headers={"Content-Type": ctype})
            resp = self._conn.getresponse()
            payload = resp.read()  # must drain to reuse the socket
            return resp.status, payload, resp.headers
        except Exception:
            self.close()  # poisoned socket: reconnect on the next request
            raise

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def post_batch(self, queries: np.ndarray, neighbors: bool,
                   binary: bool, recall: float | None = None,
                   tenant: str | None = None):
        """-> (status, degraded, retry_after_s|None, tier|None).

        ``degraded`` is the server's HOST-LOSS exactness flag for a 200
        (the pod front end's degraded partial answers under
        --on-host-loss degrade) — a recall-SLO approximate answer is NOT
        degraded: it carries a plan (``recall_plan`` in JSON,
        ``X-Knn-Recall-Plan`` in binary) and lands in ``tier`` instead.
        ``recall`` attaches the request's recall-SLO target (JSON body
        key; query string for binary — the octet codec's only option
        channel); ``tier`` then reports the server's resolution:
        ``{"exact": bool, "recall_estimated": float|None, "plan":
        str|None}``. ``retry_after_s`` echoes a Retry-After header so the
        load loop can honor 503/429 backpressure instead of hammering a
        draining pod. ``tenant`` routes the request to a multi-index
        server's ``/v1/<tenant>/knn`` namespace (docs/SERVING.md
        'Multi-index tenancy'); None keeps the legacy ``/knn`` path."""
        tier = None
        knn_path = f"/v1/{tenant}/knn" if tenant else "/knn"
        if binary:
            # raw f32 xyz triples in, raw f32 distances out — the server's
            # octet-stream format. Skips both sides' JSON encode/decode, so
            # the client measures the engine, not the text codec (neighbors
            # ride the query string; only the JSON response carries them)
            opts = [o for o in (
                "neighbors=1" if neighbors else "",
                f"recall={recall:g}" if recall is not None else "") if o]
            status, payload, headers = self._request(
                knn_path + ("?" + "&".join(opts) if opts else ""),
                np.ascontiguousarray(queries, np.float32).tobytes(),
                "application/octet-stream")
            degraded = False
            if status == 200:
                np.frombuffer(payload, np.float32)
                plan = headers.get("X-Knn-Recall-Plan")
                degraded = (headers.get("X-Knn-Exact") == "0"
                            and plan is None)
                if recall is not None:
                    est = headers.get("X-Knn-Recall-Estimated")
                    tier = {"exact": headers.get("X-Knn-Exact") != "0",
                            "recall_estimated": (float(est)
                                                 if est is not None
                                                 else None),
                            "plan": plan}
        else:
            body = {"queries": queries.tolist(), "neighbors": neighbors}
            if recall is not None:
                body["recall"] = recall
            status, payload, headers = self._request(
                knn_path, json.dumps(body).encode(), "application/json")
            obj = json.loads(payload.decode())
            degraded = (status == 200 and obj.get("exact") is False
                        and "recall_plan" not in obj)
            if status == 200 and recall is not None:
                tier = {"exact": obj.get("exact") is not False,
                        "recall_estimated": obj.get("recall_estimated"),
                        "plan": obj.get("recall_plan")}
        ra = headers.get("Retry-After")
        try:
            retry_after_s = float(ra) if ra is not None else None
        except ValueError:
            retry_after_s = None
        return status, degraded, retry_after_s, tier


def _server_pipeline_stats(url: str, timeout_s: float) -> dict | None:
    """Scrape /stats and project the pipeline-occupancy view the report
    embeds: configured depth, in-flight occupancy, dispatch stalls, mean
    batch width. None (JSON null) when the server has no /stats."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/stats",
                                    timeout=timeout_s) as r:
            stats = json.loads(r.read().decode())
    except Exception:  # noqa: BLE001 - stats are optional decoration
        return None
    b = stats.get("batcher", {})
    return {
        "pipeline_depth": b.get("pipeline_depth"),
        "pipelined": b.get("pipelined"),
        "inflight_batches": b.get("inflight_batches"),
        "inflight_rows": b.get("inflight_rows"),
        "dispatch_stalls": b.get("dispatch_stalls"),
        "dispatch_stall_seconds": b.get("dispatch_stall_seconds"),
        "batches": b.get("batches"),
        "mean_batch_rows": b.get("mean_batch_rows"),
        "engine": stats.get("engine", {}).get("engine"),
        "compile_count": stats.get("engine", {}).get("compile_count"),
        # merge placement + cumulative fetch accounting: serve_smoke's
        # host-vs-device comparison derives bytes-per-row from these
        "merge": stats.get("engine", {}).get("merge"),
        "fetch_bytes": stats.get("engine", {}).get("fetch_bytes"),
        "result_rows": stats.get("engine", {}).get("result_rows"),
        # query-locality surface: bucketing config + tile-skip counters
        # (tile-row units) — the locality bench's primary signal
        "query_buckets": stats.get("engine", {}).get("query_buckets"),
        "sort_queries": stats.get("engine", {}).get("sort_queries"),
        "tiles_executed": stats.get("engine", {}).get("tiles_executed"),
        "tiles_skipped": stats.get("engine", {}).get("tiles_skipped"),
        # shard-local routing surface (pod front ends with
        # --routing bounds): routed-row share per host — clustered traffic
        # skews it toward the hosts owning the hot regions — plus the
        # escalation rate and mean hosts visited per query, so one loadgen
        # run shows clustered-vs-uniform routing behavior end to end
        **_routing_projection(stats),
        # wire-codec surface (PR 17): which codec each endpoint
        # negotiated and the observed exchange bytes-per-row — the
        # compression ratio as measured by the server, not the bench
        **_wire_projection(stats),
        # certified query-cache surface (serve/qcache.py): how much
        # device work the exact-hit / dedup / radius-seeding tiers
        # actually removed, per the server's own counters
        **_qcache_projection(stats),
    }


def _qcache_projection(stats: dict) -> dict:
    """Hit/seed/dedup rates from the server's qcache block. Hit rate is
    over lookups (hits + misses); seed rate is the fraction of MISSED
    rows that still got a certified radius seed — the triangle-inequality
    tier's coverage of the revisit stream. An old server (or one launched
    with --qcache-rows 0) has no block and projects nothing."""
    qc = stats.get("qcache")
    if not qc:
        return {}
    lookups = qc.get("hits", 0) + qc.get("misses", 0)
    return {
        "qcache_hits": qc.get("hits"),
        "qcache_misses": qc.get("misses"),
        "qcache_hit_rate": (round(qc.get("hits", 0) / lookups, 4)
                            if lookups else None),
        "qcache_seeds": qc.get("seeds"),
        "qcache_seed_rate": (round(qc.get("seeds", 0) / qc["misses"], 4)
                             if qc.get("misses") else None),
        "qcache_dedup_rows": qc.get("dedup_rows"),
        "qcache_evictions": qc.get("evictions"),
        "qcache_size_rows": qc.get("size_rows"),
    }


def _wire_projection(stats: dict) -> dict:
    """Codec-in-use + bytes-per-row per (path, codec). Reads a pod front
    end's fan-out table (``fanout.wire``: mode, per-url negotiation,
    traffic) or a single host's root ``wire_traffic`` block; an old
    server has neither and projects nothing."""
    out: dict = {}
    fan = stats.get("fanout", {}).get("wire")
    if fan:
        out["wire_mode"] = fan.get("mode")
        out["wire_negotiated"] = fan.get("negotiated")
        traffic = fan.get("traffic")
    else:
        traffic = stats.get("wire_traffic")
    if traffic:
        out["wire_bytes_per_row"] = {
            f"{path}:{codec}": cell.get("bytes_per_row")
            for path, codecs in traffic.items()
            for codec, cell in codecs.items()
            if "bytes_per_row" in cell}
    return out


def _routing_projection(stats: dict) -> dict:
    routing = stats.get("fanout", {}).get("routing")
    if not routing:
        return {}
    rr = routing.get("routed_rows", {})
    total = sum(rr.values())
    rows_served = stats.get("batcher", {}).get("rows_served", 0)
    return {
        "routing_mode": routing.get("mode"),
        "routing_escalations": routing.get("escalations"),
        "routing_escalation_rate": (
            round(routing.get("escalations", 0) / rows_served, 4)
            if rows_served else None),
        "routed_rows": rr,
        "routed_row_share": {u: round(v / total, 4) for u, v in rr.items()}
        if total else {},
        "hosts_per_query_mean": routing.get("hosts_per_query_mean"),
    }


def run_load(url: str, *, duration_s: float = 5.0, concurrency: int = 4,
             batch: int = 8, qps: float = 0.0, neighbors: bool = False,
             timeout_s: float = 10.0, seed: int = 0,
             scale: float = 1.0, server_stats: bool = False,
             binary: bool = False, workload: str = "uniform",
             blobs: int = 16, blob_sigma: float = 0.02,
             sweep_period_s: float = 2.0,
             hosts: list[str] | None = None,
             retry_after_cap_s: float = 1.0,
             recall: float | None = None,
             tenants: list[str] | None = None,
             tenant_skew: float = 0.0,
             dup_frac: float = 0.0,
             revisit_sigma: float = 0.0) -> dict:
    """Drive the server; returns the JSON-able report (also the test API).

    ``qps > 0`` switches to open loop: the request schedule is fixed at
    ``qps`` requests/s, spread over the workers; a worker that falls behind
    skips ahead (lost sends are counted) rather than silently compressing
    the offered load. ``server_stats`` appends a post-run /stats scrape of
    the server's pipeline occupancy (depth, stalls, mean batch width) so
    one artifact carries both sides of a throughput run.

    ``workload="clustered"`` draws each request's queries from one of
    ``blobs`` Gaussian blobs (centers uniform in the [0, scale)^3 box,
    per-axis sigma ``blob_sigma * scale``, samples clipped to the box);
    concurrent workers hit different blobs, so a coalesced server batch
    mixes a few tight clusters — the locality pattern the engine's Morton
    admission separates back out.

    ``hosts`` switches to round-robin multi-endpoint mode: each worker
    holds one persistent connection per endpoint and rotates requests
    across them (front-end-BYPASS — point it at independent replica
    servers, NOT at one pod's slice servers, whose /shard_knn protocol is
    collective). The report then carries per-endpoint p50/p95/p99 AND
    per-endpoint availability / degraded_rate next to the aggregate —
    under a rolling host kill the aggregate can look healthy while one
    endpoint serves every degraded answer; the per-endpoint split is how
    the replica bench reads which host actually absorbed the loss.

    ``retry_after_cap_s`` caps how long a closed-loop worker honors a
    server's Retry-After on 503/429 (default 1.0 s): a chaos/replica
    bench must not park its workers past the measurement window, while a
    patient production client can raise it to the server's real drain
    horizon.

    ``workload="sweep"`` drives a WINDOW of blob centers drifting along
    the index box's main diagonal over ``sweep_period_s`` (wrapping):
    each request samples a blob around the current window position, so
    the hot slab set MOVES through the index — the churn pattern that
    forces a tiered slab pool (serve/slabpool.py) through real
    eviction/readmission cycles, where clustered/uniform streams never
    evict again once warm.

    ``dup_frac``/``revisit_sigma`` shape the stream for the certified
    query cache (serve/qcache.py): every FRESH batch enters a bounded
    shared pool of issued batches. With probability ``dup_frac`` a
    request replays a pooled batch byte-identically — the exact-hit and
    in-flight-dedup tiers' traffic. With ``revisit_sigma > 0`` three
    quarters of the remaining requests re-ask a pooled batch jittered
    by a per-row Gaussian of sigma ``revisit_sigma * scale`` (the last
    quarter stays fresh draws so the pool keeps churning) —
    near-duplicates the triangle-inequality radius-seeding tier
    certifies. The report's ``server`` scrape then projects the cache's
    own hit/seed/dedup rates next to the measured q/s (docs/SERVING.md
    "Query cache & radius seeding").

    ``tenants`` switches to multi-index mode against a tenanted server
    (serve/tenancy.py): each request picks a tenant name and posts to
    ``/v1/<tenant>/knn``. ``tenant_skew`` is the zipf exponent ``a`` of
    the pick distribution — weight of rank-i tenant is 1/(i+1)^a, so
    rank 0 is the hot tenant and the tail goes cold as ``a`` grows
    (0 = uniform). The report then carries a per-tenant
    availability/p50/p99 split plus a hot/cold rollup (hot = rank 0,
    cold = everything else aggregated) — the read the tenancy bench
    uses to bound a cold tenant's p99 under one shared byte budget.
    """
    if workload not in ("uniform", "clustered", "sweep"):
        raise ValueError(f"unknown workload '{workload}'")
    endpoints = list(hosts) if hosts else [url]
    # blob centers are seed-deterministic and shared by all workers; each
    # request picks a blob, so the stream is a mixture of tight clusters.
    # Query draws use a PER-WORKER Generator (numpy Generators are not
    # thread-safe — concurrent draws from a shared one can corrupt state)
    centers = np.random.default_rng(seed).random((max(1, blobs), 3)) * scale
    t_start = time.monotonic()
    hist = LatencyHistogram()
    ep_hists = {u: LatencyHistogram() for u in endpoints}
    lock = threading.Lock()
    counts = {"ok": 0, "degraded": 0, "overload": 0, "deadline": 0,
              "unavailable": 0, "http_error": 0,
              "net_error": 0, "rows_ok": 0, "sched_skipped": 0,
              "approx": 0}
    status_counts: dict[str, int] = {}
    #: recall-SLO accounting: per-plan approx counts and the server's
    #: claimed recall_estimated distribution over the approx 200s
    recall_plan_counts: dict[str, int] = {}
    recall_est_counts: dict[str, int] = {}
    ep_counts = {u: {"requests": 0, "ok": 0, "errors": 0, "degraded": 0,
                     "rejected": 0}
                 for u in endpoints}
    # multi-index mode: zipf pick weights — rank-i tenant draws
    # 1/(i+1)^tenant_skew of the traffic (skew 0 = uniform), so rank 0
    # is the hot tenant and the tail goes cold as the exponent grows
    tenant_names = list(tenants) if tenants else []
    if len(set(tenant_names)) != len(tenant_names):
        raise ValueError("duplicate tenant names")
    tenant_weights = None
    if tenant_names:
        w = np.array([1.0 / (i + 1) ** tenant_skew
                      for i in range(len(tenant_names))])
        tenant_weights = w / w.sum()
    tenant_hists = {t: LatencyHistogram() for t in tenant_names}
    tenant_counts = {t: {"requests": 0, "ok": 0, "rejected": 0,
                         "net_errors": 0}
                     for t in tenant_names}
    hc_hists = {"hot": LatencyHistogram(), "cold": LatencyHistogram()}
    # query-reuse pool (serve/qcache.py workloads): fresh batches are
    # remembered here so --dup-frac can replay one byte-identically and
    # --revisit can re-ask one jittered; bounded, random-replacement so
    # long runs keep mixing recent and old anchors
    issued_pool: list[np.ndarray] = []
    issued_cap = 64
    stop_at = time.monotonic() + duration_s

    def account(endpoint: str, status: int, dt: float, rows: int,
                degraded: bool = False, tier: dict | None = None,
                tenant: str | None = None):
        hist.record(dt)
        ep_hists[endpoint].record(dt)
        if tenant is not None:
            tenant_hists[tenant].record(dt)
            hc_hists["hot" if tenant == tenant_names[0]
                     else "cold"].record(dt)
        with lock:
            if tenant is not None:
                tenant_counts[tenant]["requests"] += 1
                if status == 200:
                    tenant_counts[tenant]["ok"] += 1
                else:
                    tenant_counts[tenant]["rejected"] += 1
            ep_counts[endpoint]["requests"] += 1
            status_counts[str(status)] = status_counts.get(str(status), 0) + 1
            if status == 200:
                counts["ok"] += 1
                counts["rows_ok"] += rows
                ep_counts[endpoint]["ok"] += 1
                if degraded:
                    counts["degraded"] += 1
                    ep_counts[endpoint]["degraded"] += 1
                if tier is not None and not tier["exact"]:
                    counts["approx"] += 1
                    plan = tier.get("plan") or "?"
                    recall_plan_counts[plan] = (
                        recall_plan_counts.get(plan, 0) + 1)
                    est = tier.get("recall_estimated")
                    if est is not None:
                        key = f"{est:g}"
                        recall_est_counts[key] = (
                            recall_est_counts.get(key, 0) + 1)
            elif status == 429:
                counts["overload"] += 1
            elif status == 503:
                counts["unavailable"] += 1
            elif status == 504:
                counts["deadline"] += 1
            else:
                counts["http_error"] += 1
            if status != 200:
                ep_counts[endpoint]["rejected"] += 1

    def one_request(pick_client, rng: np.random.Generator):
        """Fire one request; returns a Retry-After backoff (seconds) the
        caller should honor, or None."""
        q = None
        if dup_frac > 0 or revisit_sigma > 0:
            with lock:
                prev = (issued_pool[int(rng.integers(len(issued_pool)))]
                        if issued_pool else None)
            if prev is not None:
                u = rng.random()
                if u < dup_frac:
                    # byte-identical replay: the exact-hit tier (and,
                    # under enough concurrency, the in-flight dedup tier)
                    q = prev
                elif revisit_sigma > 0 and u < dup_frac + 0.75 * (
                        1.0 - dup_frac):
                    # near-duplicate revisit: the radius-seeding tier
                    q = np.clip(
                        prev + rng.normal(0.0, revisit_sigma * scale,
                                          prev.shape),
                        0.0, scale).astype(np.float32)
        if q is None:
            if workload == "clustered":
                c = centers[rng.integers(len(centers))]
                q = np.clip(
                    c + rng.normal(0.0, blob_sigma * scale, (batch, 3)),
                    0.0, scale).astype(np.float32)
            elif workload == "sweep":
                # drifting window: position along the box diagonal is a
                # pure function of elapsed time, so the hot slab set moves
                # through the index at a controlled rate
                # (eviction/readmission churn)
                frac = ((time.monotonic() - t_start) / sweep_period_s) % 1.0
                c = np.full(3, frac * scale)
                q = np.clip(
                    c + rng.normal(0.0, blob_sigma * scale, (batch, 3)),
                    0.0, scale).astype(np.float32)
            else:
                q = (rng.random((batch, 3)) * scale).astype(np.float32)
            if dup_frac > 0 or revisit_sigma > 0:
                # only FRESH batches enter the reuse pool: replays and
                # revisits anchor to originals, never to each other
                with lock:
                    if len(issued_pool) < issued_cap:
                        issued_pool.append(q)
                    else:
                        issued_pool[int(rng.integers(issued_cap))] = q
        tenant = None
        if tenant_names:
            tenant = tenant_names[int(rng.choice(len(tenant_names),
                                                 p=tenant_weights))]
        endpoint, client = pick_client()
        t0 = time.perf_counter()
        try:
            status, degraded, retry_after, tier = client.post_batch(
                q, neighbors, binary, recall=recall, tenant=tenant)
            account(endpoint, status, time.perf_counter() - t0,
                    batch if status == 200 else 0, degraded, tier,
                    tenant=tenant)
            if status in (429, 503) and retry_after:
                # honor the server's backpressure, capped by the
                # --retry-after-cap knob (an outage must not park workers
                # past the measurement window)
                return min(retry_after, retry_after_cap_s)
        except Exception:  # noqa: BLE001 - connection refused/reset, timeout
            with lock:
                counts["net_error"] += 1
                ep_counts[endpoint]["requests"] += 1
                ep_counts[endpoint]["errors"] += 1
                if tenant is not None:
                    tenant_counts[tenant]["requests"] += 1
                    tenant_counts[tenant]["net_errors"] += 1
        return None

    def make_picker(wid: int):
        """One persistent connection per endpoint per worker; round-robin
        rotation offset by worker id so concurrent workers spread load."""
        clients = {u: _Client(u, timeout_s) for u in endpoints}
        state = {"i": wid}

        def pick():
            u = endpoints[state["i"] % len(endpoints)]
            state["i"] += 1
            return u, clients[u]

        def close_all():
            for c in clients.values():
                c.close()

        return pick, close_all

    def closed_worker(wid: int):
        pick, close_all = make_picker(wid)
        wrng = np.random.default_rng((seed, wid))
        try:
            while time.monotonic() < stop_at:
                backoff = one_request(pick, wrng)
                if backoff:
                    time.sleep(min(backoff, max(0.0,
                                                stop_at - time.monotonic())))
        finally:
            close_all()

    def open_worker(wid: int):
        # worker wid owns schedule slots wid, wid+W, wid+2W, ...
        pick, close_all = make_picker(wid)
        wrng = np.random.default_rng((seed, wid))
        interval = concurrency / qps
        next_t = time.monotonic() + (wid / qps)
        try:
            while next_t < stop_at:
                now = time.monotonic()
                if now < next_t:
                    time.sleep(next_t - now)
                elif now - next_t > interval:
                    # behind by a full slot: drop it, keep the offered rate
                    # honest
                    missed = int((now - next_t) / interval)
                    next_t += missed * interval
                    with lock:
                        counts["sched_skipped"] += missed
                    continue
                one_request(pick, wrng)
                next_t += interval
        finally:
            close_all()

    t_start = time.monotonic()
    workers = [threading.Thread(
        target=(open_worker if qps > 0 else closed_worker),
        args=(i,), daemon=True)
        for i in range(concurrency)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=duration_s + timeout_s + 30)
    elapsed = time.monotonic() - t_start
    # open loop: a sparse schedule (fractional offered q/s) can finish
    # its last slot well before the window closes — rates divide by the
    # offered window, not the early-exit wall, or a one-request run at
    # 0.1 q/s reports whatever its single latency happened to be
    if qps > 0:
        elapsed = max(elapsed, float(duration_s))

    total = sum(counts[c] for c in
                ("ok", "overload", "deadline", "unavailable", "http_error"))
    attempted = total + counts["net_error"]
    lat = hist.report()

    def _pct_ms(rep, p):
        return None if rep[p] is None else round(rep[p] * 1e3, 3)

    per_endpoint = None
    if hosts:
        per_endpoint = {}
        for u in endpoints:
            rep = ep_hists[u].report()
            c = ep_counts[u]
            per_endpoint[u] = {
                **c,
                "qps": round(c["requests"] / elapsed, 2),
                # per-endpoint availability/degraded split: under a
                # rolling kill the aggregate hides WHICH endpoint
                # absorbed the loss — this is how the replica bench
                # reads it (requests includes net errors, like the
                # aggregate's attempted denominator)
                "availability": (round(c["ok"] / c["requests"], 4)
                                 if c["requests"] else None),
                "degraded_rate": (round(c["degraded"] / c["ok"], 4)
                                  if c["ok"] else None),
                "p50_ms": _pct_ms(rep, "p50"),
                "p95_ms": _pct_ms(rep, "p95"),
                "p99_ms": _pct_ms(rep, "p99"),
            }
    tenancy = None
    if tenant_names:
        per_tenant = {}
        for i, t in enumerate(tenant_names):
            rep = tenant_hists[t].report()
            c = tenant_counts[t]
            per_tenant[t] = {
                **c,
                "rank": i,
                "share": (round(c["requests"] / attempted, 4)
                          if attempted else None),
                "availability": (round(c["ok"] / c["requests"], 4)
                                 if c["requests"] else None),
                "p50_ms": _pct_ms(rep, "p50"),
                "p95_ms": _pct_ms(rep, "p95"),
                "p99_ms": _pct_ms(rep, "p99"),
            }

        def _roll(names, h):
            req = sum(tenant_counts[t]["requests"] for t in names)
            ok = sum(tenant_counts[t]["ok"] for t in names)
            rep = h.report()
            return {"tenants": list(names), "requests": req, "ok": ok,
                    "availability": round(ok / req, 4) if req else None,
                    "p50_ms": _pct_ms(rep, "p50"),
                    "p99_ms": _pct_ms(rep, "p99")}

        # hot = the zipf rank-0 tenant, cold = everything else pooled:
        # the tenancy bench's primary read for "does a cold tenant still
        # answer inside its p99 bound under one shared byte budget"
        tenancy = {
            "tenants": len(tenant_names),
            "zipf_a": tenant_skew,
            "per_tenant": per_tenant,
            "hot_cold": {
                "hot": _roll(tenant_names[:1], hc_hists["hot"]),
                "cold": _roll(tenant_names[1:], hc_hists["cold"]),
            },
        }
    return {
        **({"server": ({u: _server_pipeline_stats(u, timeout_s)
                        for u in endpoints} if hosts
                       else _server_pipeline_stats(url, timeout_s))}
           if server_stats else {}),
        "mode": "open" if qps > 0 else "closed",
        **({"endpoint_mode": "round_robin",
            "per_endpoint": per_endpoint} if hosts else {}),
        "workload": workload,
        **({"blobs": blobs, "blob_sigma": blob_sigma}
           if workload == "clustered" else {}),
        **({"blob_sigma": blob_sigma, "sweep_period_s": sweep_period_s}
           if workload == "sweep" else {}),
        **({"dup_frac": dup_frac, "revisit_sigma": revisit_sigma}
           if (dup_frac > 0 or revisit_sigma > 0) else {}),
        "url": url, "duration_s": round(elapsed, 3),
        "concurrency": concurrency, "batch": batch, "binary": binary,
        "offered_qps": qps if qps > 0 else None,
        "requests": total, "qps": round(total / elapsed, 2),
        "rows_per_s": round(counts["rows_ok"] / elapsed, 2),
        **counts,
        # availability surface (the chaos bench's primary read): fraction
        # of ATTEMPTED requests answered 200 (degraded 200s included —
        # they are answers, flagged), the status-code breakdown, and the
        # degraded share of the 200s
        "status_counts": dict(sorted(status_counts.items())),
        "availability": (round(counts["ok"] / attempted, 4)
                         if attempted else None),
        "error_rate": (round((attempted - counts["ok"]) / attempted, 4)
                       if attempted else None),
        "degraded_rate": (round(counts["degraded"] / counts["ok"], 4)
                          if counts["ok"] else None),
        # recall-SLO surface (only when a target was offered): the
        # approx-tier share of the 200s, the q/s split by served tier,
        # and the server's claimed recall_estimated / plan distributions
        **({"recall": {
            "target": recall,
            "approx_requests": counts["approx"],
            "exact_requests": counts["ok"] - counts["approx"],
            "approx_share": (round(counts["approx"] / counts["ok"], 4)
                             if counts["ok"] else None),
            "qps_approx": round(counts["approx"] / elapsed, 2),
            "qps_exact": round(
                (counts["ok"] - counts["approx"]) / elapsed, 2),
            "plan_counts": dict(sorted(recall_plan_counts.items())),
            "recall_estimated_counts": dict(
                sorted(recall_est_counts.items())),
        }} if recall is not None else {}),
        # multi-index surface (only when --tenants was asked): per-tenant
        # availability/latency split + the hot/cold rollup
        **({"tenancy": tenancy} if tenancy is not None else {}),
        "latency_seconds": lat,
        # None (JSON null) when nothing was measured — e.g. server down,
        # every request a net_error — keeping the report strict JSON
        "p50_ms": None if lat["p50"] is None else round(lat["p50"] * 1e3, 3),
        "p95_ms": None if lat["p95"] is None else round(lat["p95"] * 1e3, 3),
        "p99_ms": None if lat["p99"] is None else round(lat["p99"] * 1e3, 3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="http://127.0.0.1:8080")
    ap.add_argument("--hosts", default=None,
                    help="comma-separated endpoint URLs: round-robin "
                         "front-end-bypass mode with per-endpoint "
                         "p50/p95/p99 (point at independent replica "
                         "servers; for a pod, --url the front end instead)")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8,
                    help="queries per request")
    ap.add_argument("--qps", type=float, default=0.0,
                    help=">0: open loop at this offered request rate")
    ap.add_argument("--neighbors", action="store_true")
    ap.add_argument("--binary", action="store_true",
                    help="octet-stream bodies (raw f32), not JSON")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="query box [0, scale)^3 (match the index bbox)")
    ap.add_argument("--workload", choices=("uniform", "clustered", "sweep"),
                    default="uniform",
                    help="uniform: every query independent in the box; "
                         "clustered: each request samples one Gaussian "
                         "blob (query locality); sweep: a blob window "
                         "drifting along the box diagonal (tiered-slab "
                         "eviction/readmission churn)")
    ap.add_argument("--blobs", type=int, default=16,
                    help="clustered: number of blob centers in the box")
    ap.add_argument("--blob-sigma", type=float, default=0.02,
                    help="clustered/sweep: per-axis blob sigma as a "
                         "fraction of --scale")
    ap.add_argument("--sweep-period", type=float, default=2.0,
                    help="sweep: seconds per full diagonal traversal "
                         "(wrapping)")
    ap.add_argument("--dup-frac", type=float, default=0.0,
                    help="fraction of requests replaying a previously "
                         "issued batch byte-identically — the certified "
                         "query cache's exact-hit / in-flight-dedup "
                         "traffic (docs/SERVING.md 'Query cache & radius "
                         "seeding')")
    ap.add_argument("--revisit", type=float, default=0.0, metavar="SIGMA",
                    help=">0: most non-duplicate requests re-ask a "
                         "previously issued batch jittered by a per-row "
                         "Gaussian of sigma SIGMA*scale — the "
                         "near-duplicate stream the cache's "
                         "triangle-inequality radius seeding certifies")
    ap.add_argument("--recall", type=float, default=None,
                    help="attach this recall-SLO target to every request "
                         "(JSON body key / binary query string); the "
                         "report then splits q/s by served tier and "
                         "carries the plan + recall_estimated "
                         "distributions (docs/SERVING.md 'Recall-SLO "
                         "tier')")
    ap.add_argument("--tenants", type=int, default=0,
                    help=">0: multi-index mode — spread requests over N "
                         "tenant namespaces /v1/<t>/knn of one tenanted "
                         "server (names t0..t{N-1} unless --tenant-names); "
                         "the report gains per-tenant and hot/cold "
                         "availability/p50/p99 splits")
    ap.add_argument("--tenant-names", default=None,
                    help="comma-separated tenant names (overrides the "
                         "t0..tN default; list order = zipf rank order, "
                         "first name is the hot tenant)")
    ap.add_argument("--tenant-skew", default="zipf:0",
                    help="traffic skew across tenants as 'zipf:a': rank-i "
                         "tenant draws weight 1/(i+1)^a (zipf:0 uniform; "
                         "zipf:1.6 one hot tenant and a cold tail)")
    ap.add_argument("--retry-after-cap", type=float, default=1.0,
                    help="max seconds a closed-loop worker honors a "
                         "Retry-After on 503/429 (default 1.0; raise for "
                         "patient-client drills)")
    ap.add_argument("--server-stats", action="store_true",
                    help="embed a post-run /stats pipeline-occupancy scrape")
    ap.add_argument("--out", default=None, help="write JSON report here")
    a = ap.parse_args(argv)

    hosts = ([h for h in a.hosts.split(",") if h] if a.hosts else None)
    if a.tenant_names:
        tenant_names = [t for t in a.tenant_names.split(",") if t]
    elif a.tenants > 0:
        tenant_names = [f"t{i}" for i in range(a.tenants)]
    else:
        tenant_names = None
    if not a.tenant_skew.startswith("zipf:"):
        ap.error("--tenant-skew must look like 'zipf:a' (e.g. zipf:1.6)")
    try:
        tenant_skew = float(a.tenant_skew.partition(":")[2])
    except ValueError:
        ap.error("--tenant-skew must look like 'zipf:a' (e.g. zipf:1.6)")
    report = run_load(a.url, duration_s=a.duration, concurrency=a.concurrency,
                      batch=a.batch, qps=a.qps, neighbors=a.neighbors,
                      timeout_s=a.timeout, seed=a.seed, scale=a.scale,
                      server_stats=a.server_stats, binary=a.binary,
                      workload=a.workload, blobs=a.blobs,
                      blob_sigma=a.blob_sigma,
                      sweep_period_s=a.sweep_period, hosts=hosts,
                      retry_after_cap_s=a.retry_after_cap,
                      recall=a.recall, tenants=tenant_names,
                      tenant_skew=tenant_skew, dup_frac=a.dup_frac,
                      revisit_sigma=a.revisit)
    text = json.dumps(report, indent=2)
    print(text)
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
