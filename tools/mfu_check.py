"""Cross-check the MFU estimate's timing denominator.

obs/cost.py divides executed distance FLOPs by the ring phase's wall time.
This tool validates that denominator on the current backend by timing the
same work two independent ways:

1. fused driver: one jit call, ring-phase wall time (what bench.py reports);
2. stepwise driver: per-round ``block_until_ready`` deltas summed — free of
   the fused loop's single-dispatch structure.

It reports both, their ratio, and the cost_report each implies. A ratio
near 1 means the phase timer is measuring device time, not dispatch
artifacts; a large gap would mean the MFU number inherits timing error.

    python tools/mfu_check.py [n] [k]
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    import jax
    import numpy as np

    from mpi_cuda_largescaleknn_tpu.obs.cost import cost_report
    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_stepwise
    from mpi_cuda_largescaleknn_tpu.models.sharding import (
        pad_and_flatten,
        slab_bounds,
    )

    dev = jax.devices()[0]
    platform, kind = dev.platform, getattr(dev, "device_kind", None)
    pts = np.random.default_rng(7).random((n, 3)).astype(np.float32)
    mesh = get_mesh(1)

    # 1) fused driver, phase-timer wall time (bench.py's denominator)
    model = UnorderedKNN(KnnConfig(k=k), mesh=mesh)
    model.run(pts)  # compile
    model.timers.phases.clear()
    t0 = time.perf_counter()
    model.run(pts)
    fused_wall = time.perf_counter() - t0
    fused_ring = model.timers.report()["ring"]["seconds"]
    pair_evals = (model.last_stats or {}).get("pair_evals", 0)

    # 2) stepwise driver: block_until_ready-bounded, best of 3
    bounds = slab_bounds(n, 1)
    flat, ids, _, _ = pad_and_flatten([pts[b:e] for b, e in bounds],
                                      id_bases=[b for b, _ in bounds])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ring_knn_stepwise(flat, ids, k, mesh)
        best = min(best, time.perf_counter() - t0)

    out = {
        "n": n, "k": k, "platform": platform, "device_kind": kind,
        "fused_ring_phase_s": round(fused_ring, 4),
        "fused_total_wall_s": round(fused_wall, 4),
        "stepwise_best_s": round(best, 4),
        "ratio_stepwise_over_fused_phase": round(best / fused_ring, 3),
        "cost_via_fused_phase": cost_report(pair_evals, fused_ring,
                                            platform, kind),
        "cost_via_stepwise": cost_report(pair_evals, best, platform, kind),
    }
    print(json.dumps(out))
    with open("mfu_check.json", "w") as f:
        json.dump(out, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
