#!/usr/bin/env python3
"""Offline recall calibration for the recall-SLO tier (serve/recall.py).

Measures each candidate plan's ACTUAL recall by oracle sampling against
the exact engine — the same index, the same AOT programs the server runs,
with the plan's knobs engaged — across the three serving workload shapes
(uniform / clustered / sweep, mirroring tools/loadgen.py's generators).
Each plan's calibrated claim is the MINIMUM measured recall over the
workloads minus a safety ``--margin``: the policy may only promise what
its worst calibrated workload delivered, with slack for workload drift.

The output JSON is a ready-to-serve policy table
(``{"plans": [...]}``, the ``RecallPolicy.from_file`` format — point
``tpuknn-serve --recall-policy`` at it), plus the full measured matrix so
the calibration is auditable. ``serve_smoke.py --recall-bench`` re-runs
the same measurement end to end over HTTP and gates the claims in CI.

    python tools/recall_harness.py --points 16384 --k 16 \
        --queries 512 --margin 0.02 --out recall_policy.json

``--grid`` additionally sweeps a visit_frac x prune_shrink grid beyond
the built-in plan table — for exploring new operating points before
promoting them into a served policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run as a file


def _setup_cpu_fixture() -> None:
    """Default to the CPU backend (the calibration is about CANDIDATE
    SETS, not wall time — any backend measures the same recall); a real
    TPU run just sets JAX_PLATFORMS itself."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


import numpy as np  # noqa: E402


def workload_queries(workload: str, n_queries: int, seed: int,
                     scale: float = 1.0, blobs: int = 8,
                     blob_sigma: float = 0.02) -> np.ndarray:
    """Seed-deterministic query sets in the three serving shapes
    (tools/loadgen.py's generators, minus the time axis): ``uniform``
    draws independently in the box; ``clustered`` mixes tight Gaussian
    blobs; ``sweep`` places blob windows along the box diagonal — the
    drifting-hot-region shape, frozen at four window positions.

    The per-workload stream is seeded with crc32(workload), NOT hash():
    str hash is salted per process (PYTHONHASHSEED), which would make
    every calibration run measure a different query set — calibration
    and the CI bench must be byte-reproducible."""
    rng = np.random.default_rng((seed, zlib.crc32(workload.encode())))
    if workload == "uniform":
        return (rng.random((n_queries, 3)) * scale).astype(np.float32)
    if workload == "clustered":
        centers = rng.random((blobs, 3)) * scale
        picks = rng.integers(blobs, size=n_queries)
        q = centers[picks] + rng.normal(0.0, blob_sigma * scale,
                                        (n_queries, 3))
        return np.clip(q, 0.0, scale).astype(np.float32)
    if workload == "sweep":
        fracs = np.array([0.125, 0.375, 0.625, 0.875])
        centers = np.repeat(fracs, 3).reshape(len(fracs), 3) * scale
        picks = rng.integers(len(fracs), size=n_queries)
        q = centers[picks] + rng.normal(0.0, blob_sigma * scale,
                                        (n_queries, 3))
        return np.clip(q, 0.0, scale).astype(np.float32)
    raise ValueError(f"unknown workload '{workload}'")


def candidate_plans(grid: bool):
    """The built-in plan table's knob vectors, plus (``--grid``) a
    visit_frac x prune_shrink exploration sweep."""
    from mpi_cuda_largescaleknn_tpu.serve.recall import (
        DEFAULT_PLANS,
        RecallPlan,
    )

    plans = list(DEFAULT_PLANS)
    if grid:
        have = {p.program_key() for p in plans}
        for vf in (0.05, 0.15, 0.35, 0.65):
            for ps in (0.3, 0.6, 0.85):
                p = RecallPlan(name=f"grid-v{vf:g}-p{ps:g}",
                               skip_rescore=True, prune_shrink=ps,
                               visit_frac=vf, route_slack=0.2,
                               stream_skip_cold=True,
                               recall_estimated=0.5)
                if p.program_key() not in have:
                    plans.append(p)
    return plans


def calibrate(*, n_points: int = 16384, k: int = 16, n_queries: int = 512,
              bucket_size: int = 64, max_batch: int = 256,
              margin: float = 0.02, seed: int = 0, grid: bool = False,
              workloads=("uniform", "clustered", "sweep")) -> dict:
    """Build the exact engine once, run every candidate plan's program
    over every workload's query set, and emit the calibrated policy."""
    _setup_cpu_fixture()
    from dataclasses import replace

    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.recall import measured_recall

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    engine = ResidentKnnEngine(points, k, mesh=get_mesh(1), engine="tiled",
                               bucket_size=bucket_size, max_batch=max_batch,
                               min_batch=16)

    def run(q, plan=None):
        """Engine pass in max_batch chunks -> stacked [n, k] ids."""
        outs = [np.asarray(engine.query(q[i:i + max_batch], plan=plan)[1])
                for i in range(0, len(q), max_batch)]
        return np.concatenate(outs, axis=0)

    queries = {wl: workload_queries(wl, n_queries, seed)
               for wl in workloads}
    # one exact pass per workload — the oracle every plan is scored
    # against (the engine's exact path is itself oracle-exact; tier-1
    # proves that elsewhere)
    exact_idx = {wl: run(q) for wl, q in queries.items()}

    plans = candidate_plans(grid)
    measured: dict[str, dict[str, float]] = {}
    calibrated = []
    for plan in plans:
        per_wl = {}
        for wl, q in queries.items():
            approx_idx = run(q, plan=plan)
            per_wl[wl] = round(measured_recall(approx_idx, exact_idx[wl]),
                               6)
        measured[plan.name] = per_wl
        worst = min(per_wl.values())
        est = max(0.01, round(worst - margin, 4))
        calibrated.append(replace(plan, recall_estimated=est,
                                  recall_target=1.0))
    calibrated.sort(key=lambda p: p.recall_estimated)
    return {
        "kind": "recall_harness",
        "fixture": {"n_points": n_points, "k": k, "n_queries": n_queries,
                    "bucket_size": bucket_size, "max_batch": max_batch,
                    "seed": seed, "margin": margin,
                    "workloads": list(workloads),
                    "engine": engine.engine_name},
        "measured": measured,
        "plans": [p.to_json() for p in calibrated],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--points", type=int, default=16384)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--queries", type=int, default=512,
                    help="oracle sample size per workload shape")
    ap.add_argument("--bucket-size", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--margin", type=float, default=0.02,
                    help="claimed recall = worst measured workload minus "
                         "this safety margin")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grid", action="store_true",
                    help="also sweep a visit_frac x prune_shrink grid "
                         "beyond the built-in plan table")
    ap.add_argument("--workloads", default="uniform,clustered,sweep",
                    help="comma-separated workload shapes to calibrate on")
    ap.add_argument("--out", default=None,
                    help="write the policy JSON here (the "
                         "--recall-policy / RecallPolicy.from_file format)")
    a = ap.parse_args(argv)

    report = calibrate(
        n_points=a.points, k=a.k, n_queries=a.queries,
        bucket_size=a.bucket_size, max_batch=a.max_batch,
        margin=a.margin, seed=a.seed, grid=a.grid,
        workloads=tuple(w for w in a.workloads.split(",") if w))
    text = json.dumps(report, indent=2)
    print(text)
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
