#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim, so CI and humans run the
# exact same gate. Exits with pytest's status; prints DOTS_PASSED for the
# no-worse-than-seed comparison.
cd "$(dirname "$0")/.." || exit 1

# Static-analysis gate (BLOCKING): lock discipline (guarded_by proofs +
# lock-order cycles), determinism/parity rules, and the AOT-contract diff
# against docs/aot_contract.json — tools/lskcheck.py, rule catalog in
# docs/ANALYSIS.md. Any unwaived finding or contract drift fails the
# build; the machine-readable report lands in ANALYSIS.json (CI artifact).
timeout -k 10 300 python tools/lskcheck.py --json ANALYSIS.json
lskrc=$?

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# Serving bench trajectory (ROADMAP): loadgen q/s + p50/p95/p99 at pipeline
# depth 1 vs 2 -> BENCH_serve.json, next to the batch BENCH_r*.json series.
# Runs regardless of the pytest rc (the suite carries known pallas-API-drift
# failures on the container's jax pin — see ROADMAP), but only reachable
# when the test step completed rather than timing out (timeout exits 124).
# Oracle-exactness is the only gate in its exit code; throughput numbers on
# shared CI boxes are trajectory data, not a pass/fail bar. SERVE_BENCH=0
# skips (e.g. when iterating on an unrelated subsystem).
if [ "${SERVE_BENCH:-1}" != "0" ] && [ "$rc" -ne 124 ]; then
  # --locality-bench adds the clustered-vs-uniform query-locality section
  # (locality_compare): Morton admission + multi-bucket traversal vs the
  # single-bucket baseline, gated on oracle-exactness like the rest.
  # --multihost-bench adds the pod-serving section (multihost_compare):
  # 2 simulated host processes over one global mesh + the fan-out front
  # end vs a single-process twin — deterministic fetched-bytes-per-pod
  # ratio (~hosts x below per-host fetch), oracle-exact gated
  # --kernel-bench adds the distance-kernel section (kernel_compare):
  # elementwise VPU vs MXU matmul-form scoring at D in {3, 8, 64},
  # gated on MXU-vs-VPU bitwise exactness; speedups are trajectory data
  # --routing-bench adds the shard-local routing section
  # (routing_compare): the 2-host pod at --routing bounds vs --routing
  # off on clustered + uniform workloads — gated on the probe batch
  # being bitwise identical between the two (tie ids included) and
  # oracle-exact; multihost_compare additionally gates on its
  # qps_ratio_pod_vs_single regression floor
  # --chaos-bench adds the fault-tolerance section (chaos_compare): one
  # routed host killed mid-load via a deterministic fault-injected
  # outage — gated on availability under single-host loss (degrade mode
  # keeps answering, flagged exact:false) AND post-rejoin bitwise parity
  # --replica-bench adds the replication/handoff section
  # (replica_compare): a rolling single-host kill across an R=2 routed
  # pod with a warm standby — gated on ZERO exact:false responses,
  # availability >= 0.999, and the post-handoff probe being bitwise
  # identical to the never-failed answers (the adopted slab proves
  # itself); q/s at R=2 vs R=1 is the trajectory number
  # --streaming-bench adds the tiered-slab section (streaming_compare):
  # the sweep workload churning a slab pool at index size 4x the device
  # budget — gated on bitwise probe parity vs a fully-resident engine
  # (cold AND post-churn) plus a stream-stall-fraction ceiling (the
  # bounds-driven prefetcher must hide promotions under compute)
  # --recall-bench adds the recall-SLO tier section (recall_compare):
  # every requested recall target measured against the exact engine's
  # ids per workload shape over a clustered index — gated on measured
  # recall >= the requested target on every workload, approx-tier q/s
  # >= 3x exact on clustered (engine tier), the no-recall default path
  # staying bitwise identical through the live server, and the
  # exact:false / X-Knn-* / stats / metrics response contract
  # --wire-bench adds the quantized-wire section (wire_compare): the
  # q16 candidate exchange + x32 survivor re-fetch vs the f32 wire on
  # routed/replicated/streaming/mixed-codec pods — gated on bitwise
  # probe parity per pod, exchange bytes-per-row <= 0.45x f32, and the
  # d16 slab handoff being lossless with a paced-transfer seconds
  # ratio <= 0.6x f32
  # --tenancy-bench adds the multi-index tenancy section
  # (tenancy_compare): N tenants under zipf-skewed traffic sharing ONE
  # device byte budget vs N isolated single-tenant servers at equal
  # total memory — gated on aggregate goodput >= 1.3x isolated, cold
  # tenant p99 bounded, per-tenant bitwise parity vs the isolated
  # twins, and compile count staying flat across tenants
  # --cache-bench adds the certified query-cache section
  # (cache_compare): a revisit-heavy stream (exact replays + jittered
  # revisits) at a cache-enabled server vs a cache-off twin over one
  # shared engine — gated on revisit q/s >= 1.5x the twin,
  # seeded-vs-unseeded bitwise parity, hit-path responses
  # byte-identical, and compile count staying flat under seeded traffic
  timeout -k 10 4500 python tools/serve_smoke.py --duration 2 --trials 3 \
      --locality-bench --multihost-bench --kernel-bench --routing-bench \
      --chaos-bench --replica-bench --streaming-bench --recall-bench \
      --wire-bench --tenancy-bench --cache-bench \
      --out BENCH_serve.json >/dev/null || { brc=$?; [ "$rc" -eq 0 ] && rc=$brc; }
fi
# the lskcheck gate blocks even when the tests pass (and never masks a
# test failure — the first nonzero status wins)
[ "$rc" -eq 0 ] && rc=$lskrc
exit $rc
