"""Pair-budget measurement — the arithmetic that gates baseline parity.

docs/TUNING.md's work-budget table shows parity needs BOTH a high-MFU
kernel AND a small pair budget (pairs scored per query). The budget is
pure prune geometry — a function of (bucket_size, point_group, k) and the
data distribution, independent of the platform executing it — so it is
measured here exactly, on the CPU fixture, with the XLA twin's executed
tile counts (chunk-granular: what a dense engine really pays). The
wall-clock columns of tpu_tune.py say which geometry runs fastest ON
CHIP; this report says how much work each geometry does at all.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/pair_budget.py

Writes pair_budget_report.json; one JSON line per cell. PB_N overrides the
measurement size (default 250k — pairs/query is near size-invariant for
uniform data at fixed bucket geometry, see the n-sweep rows).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
# anchor the report beside the repo root wherever the script is invoked
# from — the committed artifact TUNING.md cites must not silently land in
# some other cwd (PB_OUT overrides for scratch runs)
_REPORT = os.environ.get("PB_OUT",
                         os.path.join(_ROOT, "pair_budget_report.json"))


def measure(n, k, bucket_size, point_group):
    import jax.numpy as jnp

    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    pts = np.random.default_rng(7).random((n, 3)).astype(np.float32)
    cfg = KnnConfig(k=k, engine="tiled", bucket_size=bucket_size,
                    point_group=point_group)
    model = UnorderedKNN(cfg, mesh=get_mesh(1))
    out = model.run(pts)
    assert np.all(np.isfinite(out))
    st = model.last_stats or {}
    pe = int(st.get("pair_evals", 0))
    return {"n": n, "k": k, "bucket_size": bucket_size,
            "point_group": point_group,
            "pair_evals": pe,
            "pairs_per_query": round(pe / n, 1),
            "tiles": int(st.get("tiles", 0))}


def main() -> int:
    n = int(os.environ.get("PB_N", 250_000))
    cells = []
    for k in (8, 100):
        for b, g in ((512, 1), (256, 1), (128, 1), (64, 1),
                     (128, 4), (128, 8), (64, 2), (64, 4), (64, 8),
                     (64, 16), (256, 2)):
            cells.append((n, k, b, g))
    # size-invariance check rows (k=8, best-guess geometry)
    for nn in (62_500, 125_000, 500_000):
        cells.append((nn, 8, 128, 4))

    results = []
    for cell in cells:
        try:
            r = measure(*cell)
        except Exception as e:  # a failed cell must not lose the report
            r = {"n": cell[0], "k": cell[1], "bucket_size": cell[2],
                 "point_group": cell[3],
                 "error": f"{type(e).__name__}: {e}"[:300]}
        results.append(r)
        print(json.dumps(r), flush=True)
        with open(_REPORT, "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
