"""One-shot TPU health + Mosaic-compile probe.

Single process (the axon tunnel is single-client): times first device
contact, runs a matmul sanity check, then compiles + runs BOTH Pallas
kernels with ``interpret=False`` at small aligned sizes. Prints one JSON
line per stage so a hang is attributable, and a final ``PROBE`` summary.

    python -u tools/tpu_probe.py 2>probe.err >probe.out
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# `python tools/tpu_probe.py` puts tools/ (not the repo root) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_largescaleknn_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_cache)

enable_persistent_cache()  # before the first jax import (stages import jax)

REPORT = {}


def stage(name):
    def deco(fn):
        t0 = time.perf_counter()
        try:
            out = fn()
            REPORT[name] = {"ok": True, "seconds": round(time.perf_counter() - t0, 1),
                            **(out or {})}
        except Exception as e:  # noqa: BLE001
            REPORT[name] = {"ok": False,
                            "seconds": round(time.perf_counter() - t0, 1),
                            "error": f"{type(e).__name__}: {e}"[:800]}
            traceback.print_exc()
        print("STAGE " + json.dumps({name: REPORT[name]}), flush=True)
        return REPORT[name]["ok"]
    return deco


def main():
    import numpy as np

    t0 = time.perf_counter()
    import jax

    @stage("contact")
    def _contact():
        d = jax.devices()
        return {"platform": d[0].platform, "n_devices": len(d),
                "device": str(d[0]),
                "import_plus_devices_s": round(time.perf_counter() - t0, 1)}

    on_tpu = REPORT["contact"].get("ok") and \
        REPORT["contact"].get("platform") not in (None, "cpu")

    @stage("matmul")
    def _matmul():
        import jax.numpy as jnp
        x = jnp.ones((1024, 1024), jnp.float32)
        y = (x @ x).block_until_ready()
        t1 = time.perf_counter()
        for _ in range(10):
            y = (y @ x) / 1024.0
        y.block_until_ready()
        return {"ten_matmuls_s": round(time.perf_counter() - t1, 4),
                "check": float(y[0, 0])}

    @stage("pallas_bf")
    def _bf():
        from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
            knn_update_pallas,
        )
        from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
        rng = np.random.default_rng(0)
        q = rng.random((1024, 3)).astype(np.float32)
        p = rng.random((4096, 3)).astype(np.float32)
        st = init_candidates(1024, 8)
        t1 = time.perf_counter()
        out = knn_update_pallas(st, q, p, query_tile=256, point_tile=2048,
                                interpret=not on_tpu)
        out.dist2.block_until_ready()
        compile_s = time.perf_counter() - t1
        # correctness vs brute force on the first 4 queries
        d2 = ((q[:4, None, :] - p[None, :, :]) ** 2).sum(-1)
        ref = np.sort(d2, axis=1)[:, :8]
        got = np.asarray(out.dist2[:4])
        assert np.allclose(np.sort(got, axis=1), ref, rtol=1e-5, atol=1e-6), \
            (got, ref)
        t2 = time.perf_counter()
        out = knn_update_pallas(st, q, p, query_tile=256, point_tile=2048,
                                interpret=not on_tpu)
        out.dist2.block_until_ready()
        return {"compile_s": round(compile_s, 2),
                "steady_s": round(time.perf_counter() - t2, 4)}

    @stage("pallas_tiled")
    def _tiled():
        from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
        from mpi_cuda_largescaleknn_tpu.ops.partition import partition_points
        from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
            knn_update_tiled_pallas,
        )
        from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
        rng = np.random.default_rng(1)
        pts = rng.random((8192, 3)).astype(np.float32)
        q = partition_points(pts, bucket_size=256)
        st = init_candidates(q.num_buckets * q.bucket_size, 8)
        t1 = time.perf_counter()
        out = knn_update_tiled_pallas(st, q, q, interpret=not on_tpu)
        out.dist2.block_until_ready()
        compile_s = time.perf_counter() - t1
        ref = knn_update_tiled(st, q, q)
        assert np.allclose(np.asarray(out.dist2), np.asarray(ref.dist2),
                           rtol=1e-5, atol=1e-6)
        t2 = time.perf_counter()
        out = knn_update_tiled_pallas(st, q, q, interpret=not on_tpu)
        out.dist2.block_until_ready()
        return {"compile_s": round(compile_s, 2),
                "steady_s": round(time.perf_counter() - t2, 4)}

    @stage("pallas_warm_group")
    def _warm_group():
        # the round-5 kernel additions in one compile: per-visit mask
        # (concat of broadcast bools), skip_self SMEM scalar, self_group
        # mapping, [1,1,2] visits/passes output — all must Mosaic-lower
        from mpi_cuda_largescaleknn_tpu.ops.partition import (
            coarsen_buckets,
            partition_points,
        )
        from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
            knn_update_tiled_pallas,
        )
        from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
        from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self
        import jax.numpy as jnp
        rng = np.random.default_rng(2)
        pts = rng.random((8192, 3)).astype(np.float32)
        out = {}
        for k in (8, 100):
            q = partition_points(pts, bucket_size=64)
            pc = coarsen_buckets(q, 8)           # T = 512 lanes
            cold = init_candidates(q.num_buckets * q.bucket_size, k)
            t1 = time.perf_counter()
            ref, vis_c, pas_c = knn_update_tiled_pallas(
                cold, q, pc, with_stats="full", interpret=not on_tpu)
            vis_c.block_until_ready()
            compile_s = time.perf_counter() - t1
            warm0 = warm_start_self(pc, k)
            got, vis_w, pas_w = knn_update_tiled_pallas(
                warm0, q, pc, skip_self=jnp.int32(1), self_group=8,
                with_stats="full", interpret=not on_tpu)
            # exactness: warm+skip must equal the cold traversal
            real = np.asarray(q.ids).reshape(-1) >= 0
            assert np.array_equal(np.asarray(got.dist2)[real],
                                  np.asarray(ref.dist2)[real])
            out[f"k{k}"] = {
                "compile_s": round(compile_s, 2),
                "fold_passes_cold": int(pas_c),
                "fold_passes_warm": int(pas_w),
                "visits_cold": int(vis_c), "visits_warm": int(vis_w)}
        return out

    REPORT["on_tpu"] = bool(on_tpu)
    print("PROBE " + json.dumps(REPORT), flush=True)


if __name__ == "__main__":
    main()
