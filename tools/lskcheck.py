#!/usr/bin/env python
"""lskcheck — the repo's static-analysis gate (blocking in tier-1 CI).

Runs three pass families over the package + tools (see docs/ANALYSIS.md):

  lock discipline   guarded_by("_lock") attribute proofs + a lock-
                    acquisition-order graph (deadlock cycles)
  determinism       wall-clock / unseeded RNG / float == on distances /
                    unstable sorts / dict-order folds / swallowed errors
  AOT contract      jax.eval_shape trace of every engine shape-bucket
                    program diffed against docs/aot_contract.json

Exit status is 0 iff there are ZERO unwaived findings and no contract
drift. Suppressions must be auditable: `# lsk: allow[rule] reason`.

Usage:
  python tools/lskcheck.py                      # full gate
  python tools/lskcheck.py --no-aot             # fast AST-only run
  python tools/lskcheck.py --json ANALYSIS.json # machine-readable report
  python tools/lskcheck.py --write-aot-golden   # adopt AOT drift
  python tools/lskcheck.py --list-rules
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# CPU pinning must precede the first jax import (the AOT pass builds
# fixture engines on a 2-device host-platform mesh; the accelerator
# tunnel must never be dialed from a lint gate) — same hardening as
# tests/conftest.py
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()


def main(argv: list[str] | None = None) -> int:
    from mpi_cuda_largescaleknn_tpu.analysis.findings import RULES
    from mpi_cuda_largescaleknn_tpu.analysis.runner import (
        DEFAULT_ROOTS,
        run_repo,
    )

    ap = argparse.ArgumentParser(
        prog="lskcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="files/directories to analyze (repo-relative; "
                         f"default: {' '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable report (the CI "
                         "ANALYSIS.json artifact)")
    ap.add_argument("--no-aot", action="store_true",
                    help="skip the AOT-contract trace (AST passes only; "
                         "no jax import)")
    ap.add_argument("--write-aot-golden", action="store_true",
                    help="regenerate docs/aot_contract.json from the "
                         "traced programs instead of diffing")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only the summary line, no per-finding output")
    args = ap.parse_args(argv)

    if args.no_aot and args.write_aot_golden:
        ap.error("--write-aot-golden requires the AOT trace; "
                 "drop --no-aot")

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule:16s} {desc}")
        return 0

    report = run_repo(roots=tuple(args.roots), base=_REPO,
                      aot=not args.no_aot,
                      aot_update=args.write_aot_golden)
    if args.json:
        report.dump_json(args.json)

    if not args.quiet:
        for f in sorted(report.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
    s = report.summary()
    waived = s["waived"]
    print(f"lskcheck: {s['files_checked']} files, "
          f"{report.aot_programs} AOT programs, "
          f"{s['findings']} finding(s), {waived} waived"
          + (f" — per-rule {s['per_rule']}" if s["per_rule"] else "")
          + (" — OK" if s["ok"] else " — FAIL"))
    if args.write_aot_golden:
        print("wrote docs/aot_contract.json")
    return 0 if s["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
