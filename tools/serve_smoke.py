#!/usr/bin/env python3
"""Serving bench smoke: loadgen q/s + p50/p95/p99 at pipeline depth 1 vs 2.

Boots the full serving stack in-process on a CPU fixture (default: one
virtual device, single-threaded Eigen, tiled engine — one core per
in-flight program, see _setup_cpu_fixture; --devices 8 matches the tests'
mesh instead), drives it with tools/loadgen.py closed-loop at each
requested pipeline depth, and writes a BENCH-series JSON so serving
throughput regressions are caught like batch ones (the ROADMAP "serving
bench trajectory" item). One resident engine backs every depth — the shape
buckets compile once, so the depths differ only in the batcher's
dispatch/complete overlap, which is the thing being measured.

Each depth's run also posts a fixed probe batch and checks it against the
brute-force numpy oracle, so the report can assert "pipelined results are
oracle-exact" next to the throughput numbers it claims for them.

    python tools/serve_smoke.py --duration 3 --out BENCH_serve.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run as a file

def _setup_cpu_fixture(devices: int) -> None:
    """Pin the process to the CPU backend with ``devices`` virtual devices.

    Must run before the first jax import (run_smoke imports jax lazily).
    Single-threaded Eigen makes one in-flight program cost one core, so
    pipeline depth maps 1:1 onto compute occupancy: at the default
    ``devices=1`` a depth-1 server computes on one core while the host
    side (merge, demux, HTTP) runs beside it, and depth 2 fills the
    remaining core with the next batch's traversal — the measurable analogue
    of keeping a TPU's program queue full. ``devices=8`` matches the test
    fixture's mesh instead (R-way merge exercised, but 8 device threads
    thrash the small CI boxes' 2 cores — noisy trials).
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] != "cpu":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={devices}"
    if devices == 1 and "xla_cpu_multi_thread_eigen" not in flags:
        # one device -> one core per in-flight program; multi-device meshes
        # keep Eigen multi-threaded so one program spans the cores the way
        # one traversal spans a pod's chips
        flags += " --xla_cpu_multi_thread_eigen=false"
    os.environ["XLA_FLAGS"] = flags.strip()


import numpy as np  # noqa: E402


def _run_loadgen(base_url, *, duration_s, concurrency, batch, seed) -> dict:
    """Drive tools/loadgen.py as a SUBPROCESS: the client's request work
    must not share this interpreter's GIL with the server's handler,
    batcher, and merge threads, or the measurement throttles the thing it
    measures. ``--binary`` for the same reason: raw f32 bodies keep the
    codec out of the way on both sides, so the run measures the engine
    pipeline, not JSON."""
    loadgen = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "loadgen.py")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out_path = f.name
    try:
        subprocess.run(
            [sys.executable, loadgen, "--url", base_url,
             "--duration", str(duration_s), "--concurrency", str(concurrency),
             "--batch", str(batch), "--seed", str(seed), "--server-stats",
             "--binary", "--out", out_path],
            check=True, stdout=subprocess.DEVNULL, timeout=duration_s + 120)
        with open(out_path) as f:
            return json.load(f)
    finally:
        os.unlink(out_path)


def _probe_oracle_exact(base_url, points, k, seed) -> bool:
    """POST a fixed batch through the live (possibly pipelined) server and
    compare against brute force — <=2 ulp, the tests' engine-vs-numpy bar
    (tests/oracle.py is the one ground-truth implementation)."""
    from tests.oracle import kth_nn_dist

    rng = np.random.default_rng(seed)
    q = rng.random((64, 3)).astype(np.float32)
    body = json.dumps({"queries": q.tolist()}).encode()
    req = urllib.request.Request(
        base_url + "/knn", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        got = np.asarray(json.loads(resp.read())["dists"], np.float32)
    want = kth_nn_dist(q, points, k)
    return bool(np.allclose(got, want, rtol=5e-7, atol=1e-37))


def run_smoke(*, n_points=8192, k=16, depths=(1, 2), duration_s=3.0,
              concurrency=8, batch=64, max_batch=128, max_delay_s=0.008,
              trials=3, devices=1, seed=0) -> dict:
    _setup_cpu_fixture(devices)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    engine = ResidentKnnEngine(points, k, mesh=get_mesh(devices),
                               engine="tiled", bucket_size=64,
                               max_batch=max_batch, min_batch=16)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    def one_trial(depth, trial):
        srv = build_server(engine, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=depth)
        srv.ready = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            exact = _probe_oracle_exact(base, points, k, seed)
            rep = _run_loadgen(base, duration_s=duration_s,
                               concurrency=concurrency, batch=batch,
                               seed=seed + trial)
            rep["oracle_exact"] = exact
            return rep
        finally:
            srv.close()

    # throwaway warmup pass: the first load the process serves runs cold
    # (page cache, JIT-internal caches, thread spin-up) and lands on
    # whichever depth goes first — burn that on a run nobody scores
    one_trial(depths[0], trials)

    # interleave trials (1, 2, 1, 2, ...) and take per-depth MEDIAN q/s:
    # on a small shared box one run's noise (CPU steal, page cache) easily
    # exceeds the effect; interleaving spreads it evenly across depths
    runs: dict[str, list[dict]] = {str(d): [] for d in depths}
    for trial in range(trials):
        for depth in depths:
            runs[str(depth)].append(one_trial(depth, trial))

    per_depth: dict[str, dict] = {}
    for key, reps in runs.items():
        med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
        per_depth[key] = {
            **med,
            "qps_trials": [r["qps"] for r in reps],
            "oracle_exact": all(r["oracle_exact"] for r in reps),
        }

    out = {
        "kind": "serve_smoke",
        "n_points": n_points, "k": k, "devices": devices,
        "engine": engine.engine_name,
        "compile_count": engine.compile_count, "warmup_s": round(warmup_s, 3),
        "duration_s": duration_s, "concurrency": concurrency, "batch": batch,
        "trials": trials, "per_depth": per_depth,
    }
    d1, d2 = per_depth.get("1"), per_depth.get("2")
    if d1 and d2 and d1["qps"]:
        out["qps_speedup_depth2_vs_1"] = round(d2["qps"] / d1["qps"], 3)
        if d1["p99_ms"] and d2["p99_ms"]:
            out["p99_ratio_depth2_vs_1"] = round(
                d2["p99_ms"] / d1["p99_ms"], 3)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--points", type=int, default=8192)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--depths", default="1,2",
                    help="comma-separated pipeline depths to bench")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds of closed-loop load per depth")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--trials", type=int, default=3,
                    help="interleaved trials per depth; median q/s reported")
    ap.add_argument("--devices", type=int, default=1,
                    help="virtual CPU devices / index shards")
    ap.add_argument("--max-delay-ms", type=float, default=8.0,
                    help="batcher flush deadline (docs/TUNING.md)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="write JSON report here")
    a = ap.parse_args(argv)

    report = run_smoke(n_points=a.points, k=a.k,
                       depths=tuple(int(d) for d in a.depths.split(",")),
                       duration_s=a.duration, concurrency=a.concurrency,
                       batch=a.batch, trials=a.trials, devices=a.devices,
                       max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
    text = json.dumps(report, indent=2)
    print(text)
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
    ok = all(r.get("oracle_exact") for r in report["per_depth"].values())
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
