#!/usr/bin/env python3
"""Serving bench smoke: loadgen q/s + p50/p95/p99 at pipeline depth 1 vs 2,
plus merge=host vs merge=device at depth 2, plus (``--locality-bench``) the
query-locality comparison — clustered vs uniform workloads at
query_buckets 1 vs auto, gated on deterministic tile-skip accounting
(``locality_compare`` in BENCH_serve.json), plus (``--multihost-bench``)
the pod-serving comparison — 2 simulated host processes over one global
mesh + the fan-out front end vs a single-process server of the same
config, gated on oracle-exactness AND a q/s regression floor, with the
deterministic fetched-bytes-per-pod ratio as the headline
(``multihost_compare``), plus (``--routing-bench``) the shard-local
routing comparison — the same 2-host pod at ``--routing bounds`` vs
``--routing off`` on clustered and uniform workloads, gated on the probe
batch being BITWISE identical between the two (tie ids included) and
oracle-exact (``routing_compare``), plus (``--replica-bench``) the
replication/handoff drill — a rolling single-host kill across an R=2
routed pod with a warm standby, gated on ZERO ``exact: false``
responses, availability >= 0.999, and post-handoff bitwise probe parity
(``replica_compare``), plus (``--streaming-bench``) the tiered-slab
streaming drill — the sweep workload churning a slab pool at index size
4x the device budget, gated on BITWISE probe parity vs a fully-resident
engine (cold and post-churn) and a stream-stall-fraction ceiling
(``streaming_compare``), plus (``--recall-bench``) the recall-SLO tier
drill — every requested recall target measured against the exact
engine's ids on the uniform/clustered/sweep workload shapes over a
clustered index, gated on measured recall >= the requested target per
workload, approx-tier q/s >= 3x exact on clustered (engine tier), the
no-recall default path staying BITWISE identical through the live
server, and the exact:false / X-Knn-* / stats / metrics response
contract (``recall_compare``), plus (``--tenancy-bench``) the
multi-index tenancy drill — N zipf-skewed tenants behind ONE shared
device byte budget vs N isolated single-tenant servers at equal total
memory, gated on an aggregate q/s floor, per-tenant bitwise probe
parity vs the isolated twins, a flat warmup compile count, and a
cold-tenant p99 ceiling (``tenancy_compare``), plus (``--cache-bench``)
the certified query-cache drill — a revisit-heavy stream (exact replays
+ jittered revisits) at a cache-enabled server vs a cache-off twin over
one shared engine, gated on revisit q/s >= 1.5x the twin,
seeded-vs-unseeded BITWISE parity, hit-path byte identity, and a flat
compile count under seeded traffic (``cache_compare``;
tools/ci_tier1.sh passes all flags).

Boots the full serving stack in-process on a CPU fixture (default: one
virtual device, single-threaded Eigen, tiled engine — one core per
in-flight program, see _setup_cpu_fixture; --devices 8 matches the tests'
mesh instead), drives it with tools/loadgen.py closed-loop at each
requested pipeline depth, and writes a BENCH-series JSON so serving
throughput regressions are caught like batch ones (the ROADMAP "serving
bench trajectory" item). One resident engine backs every depth — the shape
buckets compile once, so the depths differ only in the batcher's
dispatch/complete overlap, which is the thing being measured.

The merge comparison runs in a SUBPROCESS (--merge-bench) because it needs
a multi-device mesh — the R-way cross-shard merge does not exist at R=1 —
and the virtual device count is fixed per process at first jax import. It
boots one engine per merge placement on the same points and reports q/s,
p99, and the engine's cumulative fetch-bytes accounting: the device merge
must fetch >= R x fewer result bytes per row (deterministic — it fetches
one final [Q, k] instead of R partial [Q, k] pairs) at q/s no worse than
parity (noisy on shared boxes; trajectory data, not a gate).

Each run also posts a fixed probe batch and checks it against the
brute-force numpy oracle, so the report can assert "results are
oracle-exact" next to the throughput numbers it claims for them.

    python tools/serve_smoke.py --duration 3 --out BENCH_serve.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run as a file

def _setup_cpu_fixture(devices: int) -> None:
    """Pin the process to the CPU backend with ``devices`` virtual devices.

    Must run before the first jax import (run_smoke imports jax lazily).
    Single-threaded Eigen makes one in-flight program cost one core, so
    pipeline depth maps 1:1 onto compute occupancy: at the default
    ``devices=1`` a depth-1 server computes on one core while the host
    side (merge, demux, HTTP) runs beside it, and depth 2 fills the
    remaining core with the next batch's traversal — the measurable analogue
    of keeping a TPU's program queue full. ``devices=8`` matches the test
    fixture's mesh instead (R-way merge exercised, but 8 device threads
    thrash the small CI boxes' 2 cores — noisy trials).
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] != "cpu":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={devices}"
    if devices == 1 and "xla_cpu_multi_thread_eigen" not in flags:
        # one device -> one core per in-flight program; multi-device meshes
        # keep Eigen multi-threaded so one program spans the cores the way
        # one traversal spans a pod's chips
        flags += " --xla_cpu_multi_thread_eigen=false"
    os.environ["XLA_FLAGS"] = flags.strip()


import numpy as np  # noqa: E402


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pod_env() -> dict:
    """Env for child serve_main processes: they pin their own device
    counts, so this process's fixture flags must not leak in."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
        and "xla_cpu_multi_thread_eigen" not in f).strip()
    return env


def _run_loadgen(base_url, *, duration_s, concurrency, batch, seed,
                 workload="uniform", blobs=8, blob_sigma=0.02,
                 sweep_period=None, recall=None, tenants=None,
                 tenant_skew=None, qps=None, dup_frac=None,
                 revisit=None) -> dict:
    """Drive tools/loadgen.py as a SUBPROCESS: the client's request work
    must not share this interpreter's GIL with the server's handler,
    batcher, and merge threads, or the measurement throttles the thing it
    measures. ``--binary`` for the same reason: raw f32 bodies keep the
    codec out of the way on both sides, so the run measures the engine
    pipeline, not JSON."""
    loadgen = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "loadgen.py")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out_path = f.name
    try:
        subprocess.run(
            [sys.executable, loadgen, "--url", base_url,
             "--duration", str(duration_s), "--concurrency", str(concurrency),
             "--batch", str(batch), "--seed", str(seed), "--server-stats",
             "--binary", "--workload", workload, "--blobs", str(blobs),
             "--blob-sigma", str(blob_sigma)]
            + (["--sweep-period", str(sweep_period)]
               if sweep_period else [])
            + (["--recall", str(recall)] if recall is not None else [])
            + (["--tenant-names", ",".join(tenants),
                "--tenant-skew", f"zipf:{tenant_skew or 0:g}"]
               if tenants else [])
            + (["--qps", str(qps)] if qps else [])
            + (["--dup-frac", str(dup_frac)]
               if dup_frac is not None else [])
            + (["--revisit", str(revisit)] if revisit is not None else [])
            + ["--out", out_path],
            check=True, stdout=subprocess.DEVNULL, timeout=duration_s + 120)
        with open(out_path) as f:
            return json.load(f)
    finally:
        os.unlink(out_path)


def _probe_oracle_exact(base_url, points, k, seed) -> bool:
    """POST a fixed batch through the live (possibly pipelined) server and
    compare against brute force — <=2 ulp, the tests' engine-vs-numpy bar
    (tests/oracle.py is the one ground-truth implementation)."""
    from tests.oracle import kth_nn_dist

    rng = np.random.default_rng(seed)
    q = rng.random((64, 3)).astype(np.float32)
    body = json.dumps({"queries": q.tolist()}).encode()
    req = urllib.request.Request(
        base_url + "/knn", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        got = np.asarray(json.loads(resp.read())["dists"], np.float32)
    want = kth_nn_dist(q, points, k)
    return bool(np.allclose(got, want, rtol=5e-7, atol=1e-37))


def run_smoke(*, n_points=8192, k=16, depths=(1, 2), duration_s=3.0,
              concurrency=8, batch=64, max_batch=128, max_delay_s=0.008,
              trials=3, devices=1, seed=0) -> dict:
    _setup_cpu_fixture(devices)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    engine = ResidentKnnEngine(points, k, mesh=get_mesh(devices),
                               engine="tiled", bucket_size=64,
                               max_batch=max_batch, min_batch=16)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    def one_trial(depth, trial):
        srv = build_server(engine, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=depth)
        srv.ready = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            exact = _probe_oracle_exact(base, points, k, seed)
            rep = _run_loadgen(base, duration_s=duration_s,
                               concurrency=concurrency, batch=batch,
                               seed=seed + trial)
            rep["oracle_exact"] = exact
            return rep
        finally:
            srv.close()

    # throwaway warmup pass: the first load the process serves runs cold
    # (page cache, JIT-internal caches, thread spin-up) and lands on
    # whichever depth goes first — burn that on a run nobody scores
    one_trial(depths[0], trials)

    # interleave trials (1, 2, 1, 2, ...) and take per-depth MEDIAN q/s:
    # on a small shared box one run's noise (CPU steal, page cache) easily
    # exceeds the effect; interleaving spreads it evenly across depths
    runs: dict[str, list[dict]] = {str(d): [] for d in depths}
    for trial in range(trials):
        for depth in depths:
            runs[str(depth)].append(one_trial(depth, trial))

    per_depth: dict[str, dict] = {}
    for key, reps in runs.items():
        med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
        per_depth[key] = {
            **med,
            "qps_trials": [r["qps"] for r in reps],
            "oracle_exact": all(r["oracle_exact"] for r in reps),
        }

    out = {
        "kind": "serve_smoke",
        "n_points": n_points, "k": k, "devices": devices,
        "engine": engine.engine_name, "merge": engine.merge_mode,
        "compile_count": engine.compile_count, "warmup_s": round(warmup_s, 3),
        "duration_s": duration_s, "concurrency": concurrency, "batch": batch,
        "trials": trials, "per_depth": per_depth,
    }
    d1, d2 = per_depth.get("1"), per_depth.get("2")
    if d1 and d2 and d1["qps"]:
        out["qps_speedup_depth2_vs_1"] = round(d2["qps"] / d1["qps"], 3)
        if d1["p99_ms"] and d2["p99_ms"]:
            out["p99_ratio_depth2_vs_1"] = round(
                d2["p99_ms"] / d1["p99_ms"], 3)
    return out


def run_merge_bench(*, n_points=8192, k=16, devices=4, duration_s=2.0,
                    concurrency=8, batch=64, max_batch=128,
                    max_delay_s=0.008, trials=2, seed=0) -> dict:
    """merge=host vs merge=device on an R-device mesh at pipeline depth 2.

    One engine per placement (the AOT buckets are distinct programs), same
    points, interleaved loadgen trials, median q/s. ``fetch_bytes_per_row``
    comes from the engine's own counters — the headline
    ``fetch_ratio_host_vs_device`` is deterministic arithmetic, not a
    timing, and must be >= devices (the R x claim of the device merge).
    """
    _setup_cpu_fixture(devices)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    mesh = get_mesh(devices)
    engines = {}
    for mode in ("host", "device"):
        engines[mode] = ResidentKnnEngine(
            points, k, mesh=mesh, engine="tiled", bucket_size=64,
            max_batch=max_batch, min_batch=16, merge=mode)
        engines[mode].warmup()

    def one_trial(mode, trial):
        eng = engines[mode]
        srv = build_server(eng, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=2)
        srv.ready = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            exact = _probe_oracle_exact(base, points, k, seed)
            rep = _run_loadgen(base, duration_s=duration_s,
                               concurrency=concurrency, batch=batch,
                               seed=seed + trial)
            rep["oracle_exact"] = exact
            return rep
        finally:
            srv.close()

    one_trial("host", trials)  # cold-start burn (see run_smoke)
    runs = {m: [] for m in ("host", "device")}
    for trial in range(trials):
        for mode in ("host", "device"):
            runs[mode].append(one_trial(mode, trial))

    per_merge = {}
    for mode, reps in runs.items():
        med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
        st = engines[mode].stats()
        rows = max(1, st["result_rows"])
        per_merge[mode] = {
            "qps": med["qps"], "p99_ms": med["p99_ms"],
            "qps_trials": [r["qps"] for r in reps],
            "oracle_exact": all(r["oracle_exact"] for r in reps),
            "fetch_bytes_total": st["fetch_bytes"],
            "result_rows": st["result_rows"],
            "fetch_bytes_per_row": round(st["fetch_bytes"] / rows, 2),
            "compile_count": st["compile_count"],
        }

    out = {
        "kind": "serve_merge_bench", "devices": devices,
        "n_points": n_points, "k": k, "pipeline_depth": 2,
        "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "trials": trials, "per_merge": per_merge,
    }
    h, d = per_merge["host"], per_merge["device"]
    if d["fetch_bytes_per_row"]:
        out["fetch_ratio_host_vs_device"] = round(
            h["fetch_bytes_per_row"] / d["fetch_bytes_per_row"], 2)
    if h["qps"]:
        out["qps_ratio_device_vs_host"] = round(d["qps"] / h["qps"], 3)
    return out


def run_locality_bench(*, n_points=8192, k=16, duration_s=2.0,
                       concurrency=8, batch=16, max_batch=128,
                       max_delay_s=0.008, blobs=8, blob_sigma=0.02,
                       trials=2, seed=0) -> dict:
    """query_buckets=1 (unsorted single-bucket, the pre-locality serving
    path) vs query_buckets=auto (Morton admission + multi-bucket traversal)
    on clustered AND uniform workloads, pipeline depth 2, one CPU device.

    The headline numbers are DETERMINISTIC tile accounting, not timings:
    ``tiles_per_row`` = executed tile-rows / result rows from the engine's
    own counters (each engine config runs in its own ResidentKnnEngine, so
    the deltas are per-run exact). The locality claim is
    ``tiles_ratio_clustered = auto/b1 <= 0.5`` — the multi-bucket prune
    does less than half the tile work on coherent traffic — with
    ``qps_ratio_uniform >= ~0.95`` showing the sort+bucketing costs
    nothing on incoherent traffic (q/s on shared boxes is trajectory data;
    only oracle-exactness gates the exit code)."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    mesh = get_mesh(1)
    engines = {}
    for cfg, qb in (("b1", 1), ("auto", 0)):
        engines[cfg] = ResidentKnnEngine(
            points, k, mesh=mesh, engine="tiled", bucket_size=64,
            max_batch=max_batch, min_batch=16, query_buckets=qb)
        engines[cfg].warmup()

    def one_trial(cfg, workload, trial):
        eng = engines[cfg]
        srv = build_server(eng, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=2)
        srv.ready = True
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            exact = _probe_oracle_exact(base, points, k, seed)
            before = eng.timers.counters_snapshot()
            rep = _run_loadgen(base, duration_s=duration_s,
                               concurrency=concurrency, batch=batch,
                               seed=seed + trial, workload=workload,
                               blobs=blobs, blob_sigma=blob_sigma)
            after = eng.timers.counters_snapshot()
            rep["oracle_exact"] = exact
            for c in ("tiles_executed", "tiles_skipped", "result_rows"):
                rep[c] = after.get(c, 0) - before.get(c, 0)
            return rep
        finally:
            srv.close()

    one_trial("b1", "uniform", trials)  # cold-start burn (see run_smoke)
    runs = {(cfg, wl): [] for cfg in engines for wl in ("clustered",
                                                        "uniform")}
    for trial in range(trials):
        for cfg in engines:
            for wl in ("clustered", "uniform"):
                runs[(cfg, wl)].append(one_trial(cfg, wl, trial))

    per_config = {}
    for cfg, eng in engines.items():
        per_config[cfg] = {"query_buckets": dict(eng.query_buckets),
                           "sort_queries": eng.sort_queries}
        for wl in ("clustered", "uniform"):
            reps = runs[(cfg, wl)]
            med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
            rows = sum(r["result_rows"] for r in reps)
            tiles = sum(r["tiles_executed"] for r in reps)
            per_config[cfg][wl] = {
                "qps": med["qps"], "p99_ms": med["p99_ms"],
                "qps_trials": [r["qps"] for r in reps],
                "oracle_exact": all(r["oracle_exact"] for r in reps),
                "tiles_executed": tiles,
                "tiles_skipped": sum(r["tiles_skipped"] for r in reps),
                "result_rows": rows,
                "tiles_per_row": round(tiles / max(1, rows), 2),
            }

    out = {
        "kind": "serve_locality_bench", "n_points": n_points, "k": k,
        "devices": 1, "pipeline_depth": 2, "duration_s": duration_s,
        "concurrency": concurrency, "batch": batch, "blobs": blobs,
        "blob_sigma": blob_sigma, "trials": trials,
        "tile_units": "tile-rows (query row x point-tile visit)",
        "per_config": per_config,
    }
    b1, auto = per_config["b1"], per_config["auto"]
    for wl in ("clustered", "uniform"):
        if b1[wl]["tiles_per_row"]:
            out[f"tiles_ratio_{wl}"] = round(
                auto[wl]["tiles_per_row"] / b1[wl]["tiles_per_row"], 3)
        if b1[wl]["qps"]:
            out[f"qps_ratio_{wl}"] = round(
                auto[wl]["qps"] / b1[wl]["qps"], 3)
    return out


def run_streaming_bench(*, n_points=16384, k=16, num_slabs=8,
                        budget_slabs=2, duration_s=2.0, concurrency=4,
                        batch=16, max_batch=128, max_delay_s=0.008,
                        trials=2, seed=0,
                        stall_fraction_ceiling=0.5) -> dict:
    """Tiered slab index (serve/slabpool.py) at index size
    ``num_slabs / budget_slabs`` x the device budget (the default 8/2 =
    4x), driven by the loadgen ``sweep`` workload so the hot slab set
    drifts through the index — real eviction/readmission churn, the case
    clustered/uniform never produce once warm.

    Two gates ride the exit code (``streaming_compare`` in
    BENCH_serve.json): (1) a fixed probe batch served through the
    streaming engine must be BITWISE identical (dists AND neighbor ids)
    to a fully-resident ResidentKnnEngine of the same knobs, and (2) the
    stream-stall fraction — stall seconds per wall second of load — must
    stay under ``stall_fraction_ceiling``: the bounds-driven prefetcher
    (dispatch's next-nearest promotions + the batcher's batch-ahead
    hints) must hide most promotions under compute, or streaming is just
    a slow resident engine. Points are Morton-sorted so row slabs are
    spatially tight (the io partitioner's order — the same requirement
    routed serving documents); q/s is trajectory data."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    points = points[morton_argsort(points, points.min(axis=0),
                                   points.max(axis=0))]
    mesh = get_mesh(1)
    kw = dict(engine="tiled", bucket_size=64, max_batch=max_batch,
              min_batch=16)
    eng = StreamingKnnEngine(points=points, num_slabs=num_slabs, k=k,
                             mesh=mesh, prefetch_depth=2, **kw)
    # budget in BYTES against the engines' reported per-slab footprint
    # (all slabs share one shape class, so one number covers them)
    budget = eng.slab_device_bytes * budget_slabs
    eng.slab_pool.set_device_budget(budget)
    eng.warmup()
    index_bytes = eng.slab_device_bytes * num_slabs
    # qcache off: the post-churn parity probe re-posts the SAME batch —
    # an exact-hit would bypass the slab pool this bench exists to gate
    srv = build_server(eng, port=0, max_delay_s=max_delay_s,
                       pipeline_depth=2, qcache_rows=0)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        # bitwise parity probe BEFORE the churn (cold-ish pool) ...
        probe = np.random.default_rng(seed + 7).random((64, 3)).astype(
            np.float32)
        got = _post_probe(base, probe)
        ref = ResidentKnnEngine(points, k, mesh=mesh, **kw)
        want_d, want_n = ref.query(probe)
        parity_cold = (np.array_equal(got[0], np.asarray(want_d,
                                                         np.float32))
                       and np.array_equal(got[1], np.asarray(want_n)))
        reps = []
        for trial in range(trials):
            before = eng.slab_pool.stats()
            t0 = time.perf_counter()
            rep = _run_loadgen(base, duration_s=duration_s,
                               concurrency=concurrency, batch=batch,
                               seed=seed + trial, workload="sweep",
                               blob_sigma=0.05,
                               sweep_period=max(1.0, duration_s / 2))
            wall = time.perf_counter() - t0
            after = eng.slab_pool.stats()
            rep["wall_s"] = round(wall, 3)
            for c in ("promotions", "evictions", "stream_stalls",
                      "stream_stall_seconds", "device_hits", "host_hits",
                      "cold_reads"):
                rep[c] = round(after[c] - before[c], 6)
            rep["stall_fraction"] = round(
                rep["stream_stall_seconds"] / max(wall, 1e-9), 4)
            reps.append(rep)
        # ... and AFTER it (the pool has churned through the whole index)
        got2 = _post_probe(base, probe)
        parity_hot = (np.array_equal(got2[0], np.asarray(want_d,
                                                         np.float32))
                      and np.array_equal(got2[1], np.asarray(want_n)))
        oracle = _probe_oracle_exact(base, points, k, seed)
    finally:
        srv.close()
        eng.close()
    med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
    stall_fraction = max(r["stall_fraction"] for r in reps)
    pool = eng.slab_pool.stats()
    return {
        "kind": "serve_streaming_bench", "n_points": n_points, "k": k,
        "num_slabs": num_slabs, "budget_slabs": budget_slabs,
        "device_budget_bytes": budget, "index_device_bytes": index_bytes,
        "index_over_budget_ratio": round(index_bytes / budget, 2),
        "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "trials": trials, "workload": "sweep",
        "qps": med["qps"], "p99_ms": med["p99_ms"],
        "qps_trials": [r["qps"] for r in reps],
        "stall_fraction": stall_fraction,
        "stall_fraction_trials": [r["stall_fraction"] for r in reps],
        "stall_fraction_ceiling": stall_fraction_ceiling,
        "stall_ok": stall_fraction <= stall_fraction_ceiling,
        "promotions": sum(r["promotions"] for r in reps),
        "evictions": sum(r["evictions"] for r in reps),
        "cold_reads": sum(r["cold_reads"] for r in reps),
        "host_hits": sum(r["host_hits"] for r in reps),
        "pool_final": pool,
        "bitwise_parity_vs_resident": bool(parity_cold and parity_hot),
        "bitwise_parity_cold": bool(parity_cold),
        "bitwise_parity_hot": bool(parity_hot),
        "oracle_exact": bool(oracle),
    }


def _post_probe(base_url, q, path="/knn"):
    """POST a probe batch (JSON, neighbors on) -> (dists f32[n],
    neighbors i32[n, k]). f32 distances survive the JSON float64
    round-trip exactly (every f32 is representable), so the comparison
    upstream is genuinely bitwise. ``path`` selects a tenant namespace
    (``/v1/<tenant>/knn``) on a multi-index server."""
    body = json.dumps({"queries": np.asarray(q).tolist(),
                       "neighbors": True}).encode()
    req = urllib.request.Request(
        base_url + path, data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        obj = json.loads(resp.read())
    return (np.asarray(obj["dists"], np.float32),
            np.asarray(obj["neighbors"], np.int32))


def run_tenancy_bench(*, tenants=6, points_per_tenant=8192, k=8,
                      num_slabs=6, budget_slabs_total=12, duration_s=6.0,
                      concurrency=20, batch=16, max_batch=16,
                      max_delay_s=0.008, trials=3, seed=0,
                      qps_ratio_floor=1.3, skew_a=4.0, offered_qps=8.0,
                      promote_delay_s=0.4, blobs=8, blob_sigma=0.02,
                      cold_p99_ceiling_ms=6000.0) -> dict:
    """Multi-index tenancy (serve/tenancy.py): N tenants' indexes behind
    ONE shared device byte budget vs N isolated single-tenant servers at
    EQUAL TOTAL memory (each gets budget_slabs_total/N slabs), both
    driven by the same zipf-skewed clustered traffic (rank-i tenant
    draws 1/(i+1)^skew_a of the requests — one hot tenant, a cold tail;
    each request samples one Gaussian blob, so its certified slab set
    is a fraction of the index, the locality tiered serving exists
    for).

    The economics being measured: every tenant's index is the same
    size (``num_slabs`` slabs — default 6 tenants x 6 slabs = 36), and
    the shared budget (default 12 slabs) is split evenly for the
    isolated twins (2 each) — the static partition an operator without
    traffic knowledge would pick, and exactly equal in total bytes.
    The shared pool's LRU turns the zipf skew into residency: the hot
    tenant's whole index ends up device-resident (its slabs are
    re-touched too often to be eviction victims) while the six
    leftover slots absorb the cold tail's promotions — a cold
    request's whole working set fits the spare slots, so cold churn
    never evicts the hot index. The isolated hot twin is pinned at
    budget 2 against a 6-slab working set, so nearly every request
    waits out promotions — and it is carrying ~93% of the offered
    load. Memory the static split parked on idle tenants is memory
    the skew cannot use.
    On this CPU fixture a "device upload" is a host-memory memcpy, so
    residency would be free and the comparison would measure nothing:
    every promotion carries a deterministic injected latency of
    ``promote_delay_s`` (serve/faults.py, ``PROMOTE /slab/...``) on
    BOTH the shared pool and every isolated twin. The delay is scaled
    to the fixture, not to a wall clock: it keeps promotion cost at a
    few tens of dispatch-computes, the regime of a multi-GB slab over
    PCIe against a sub-millisecond kernel — the ratio the real
    system's streaming economics live in. (Prefetch is off on both
    sides: with promotions this expensive, speculative whole-plan
    prefetch through the pool's single async lane is pure poison —
    it would serialize behind itself and evict live slabs for
    speculative ones.) The comparison therefore measures
    promotion-count economics: how much less the shared pool uploads
    under skew, priced at a fixed cost per upload.

    Both sides run OPEN LOOP at the same offered load
    (``offered_qps`` total, split across the isolated servers by the
    same zipf weights the shared server's request stream draws from):
    a closed loop would let the idle cold twins free-run at saturation
    — traffic the skewed demand never offers them — and count it as
    isolated throughput; worse, under zipf picks a closed loop
    converts the cold tail's request share into worker-TIME share,
    drowning the hot tenant. Open loop offers each side the identical
    demand shape through a worker pool deep enough that multi-second
    promotion stalls never starve the attempt stream, and measures
    GOODPUT — answered 200s per second of the offered window (fast
    429/503 shedding does not count, and neither does a sparse
    schedule's early exit): the isolated hot twin saturates well below
    its offered slice because nearly every request waits out
    promotions, while the shared server keeps the hot tenant resident
    and absorbs the same demand.

    Four gates ride the exit code (``tenancy_compare`` in
    BENCH_serve.json): (1) shared achieved q/s >= ``qps_ratio_floor`` x
    the isolated servers' total at equal memory, equal client
    concurrency, and equal offered load; (2) every tenant's probe
    answers through
    ``/v1/<tenant>/knn`` are BITWISE identical (dists AND ids) to its
    isolated single-tenant twin's, before AND after the load churn —
    tenancy shares capacity, never results; (3) the shared server's
    warmup compile count stays FLAT vs one single-tenant engine (all
    tenants pad to the pool's shape classes, so the ExecutableCache
    hits across tenants); (4) the coldest tenant's p99 through the
    shared server stays under ``cold_p99_ceiling_ms`` — eviction
    fairness: a cold tenant is slower (stall-counted), never starved."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.faults import (FaultInjector,
                                                         FaultSpec)
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.tenancy import (MultiTenantEngine,
                                                          TenantSpec)
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    def _goodput(rep):
        """Achieved 200s/s. The loadgen's own ``qps`` counts every
        COMPLETED request — a server shedding load with fast 429/503s
        would inflate it toward the offered rate; answered queries are
        the thing the two deployments are being compared on."""
        return round(rep["ok"] / max(float(rep["duration_s"]), 1e-9), 2)

    def dma_model():
        """One injector per pool (separate firing state), same fixed
        cost: every promotion sleeps ``promote_delay_s``."""
        return FaultInjector([FaultSpec(
            "latency", path="/slab/", method="PROMOTE",
            delay_s=promote_delay_s)])

    names = [f"t{i}" for i in range(tenants)]

    def mk_points(i):
        rng = np.random.default_rng((seed, i))
        p = rng.random((points_per_tenant, 3)).astype(np.float32)
        return p[morton_argsort(p, p.min(axis=0), p.max(axis=0))]

    points = {n: mk_points(i) for i, n in enumerate(names)}
    mesh = get_mesh(1)
    kw = dict(engine="tiled", bucket_size=64, max_batch=max_batch,
              min_batch=16)
    # zipf weights mirror loadgen's pick distribution; they also split
    # the isolated servers' client concurrency so both sides see the
    # same offered-load shape at the same total worker count
    w = np.array([1.0 / (i + 1) ** skew_a for i in range(tenants)])
    w = w / w.sum()
    iso_conc = [max(1, int(round(concurrency * wi))) for wi in w]

    shared = MultiTenantEngine(
        [TenantSpec(n, points=points[n], num_slabs=num_slabs)
         for n in names],
        k=k, mesh=mesh, prefetch_depth=0, faults=dma_model(), **kw)
    budget = shared.slab_device_bytes * budget_slabs_total
    shared.slab_pool.set_device_budget(budget)
    warm = shared.warmup()
    shared_compiles = int(warm["compile_count"])
    # qcache off (both phases): parity + re-warm probes re-post one
    # batch — cached hits would neither touch slabs nor re-warm residency
    srv = build_server(shared, port=0, max_delay_s=max_delay_s,
                       pipeline_depth=3, qcache_rows=0)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"

    # isolated twins: same engine class, same knobs, each with its OWN
    # exec cache (a real isolated deployment compiles for itself) and a
    # 1/N slice of the device budget
    iso_budget = shared.slab_device_bytes * max(
        1, budget_slabs_total // tenants)
    iso = {}
    try:
        for n in names:
            e = StreamingKnnEngine(points=points[n], num_slabs=num_slabs,
                                   k=k, mesh=mesh, prefetch_depth=0,
                                   faults=dma_model(), **kw)
            e.slab_pool.set_device_budget(iso_budget)
            e.warmup()
            s = build_server(e, port=0, max_delay_s=max_delay_s,
                             pipeline_depth=3, qcache_rows=0)
            s.ready = True
            threading.Thread(target=s.serve_forever, daemon=True).start()
            iso[n] = (e, s, f"http://127.0.0.1:{s.server_address[1]}")
        single_compiles = int(iso[names[0]][0].stats()["compile_count"])

        # probe fits max_batch (the frontend 413s bigger bodies); 16
        # uniform queries still walk essentially every slab of a
        # 6-slab index, which is what parity and the hot re-warm need
        probe = np.random.default_rng(seed + 7).random((16, 3)).astype(
            np.float32)

        def parity():
            ok = {}
            for n in names:
                got = _post_probe(base, probe, path=f"/v1/{n}/knn")
                want = _post_probe(iso[n][2], probe)
                ok[n] = bool(np.array_equal(got[0], want[0])
                             and np.array_equal(got[1], want[1]))
            return ok

        parity_cold = parity()

        # shared phase first, isolated second — the phases must not
        # contend for the box's cores with each other. Each trial opens
        # with a hot-tenant probe: the parity sweep (and the previous
        # trial's cold churn) leaves OTHER tenants' slabs resident, and
        # the trial measures the steady state the skewed traffic itself
        # maintains, not the transient of rebuilding it (the isolated
        # hot twin needs no equivalent warm — a probe's residency IS
        # its steady state, a 3-slab LRU slice of a 6-slab working set)
        shared_reps = []
        for t in range(trials):
            _post_probe(base, probe, path=f"/v1/{names[0]}/knn")
            shared_reps.append(_run_loadgen(
                base, duration_s=duration_s, concurrency=concurrency,
                batch=batch, seed=seed + t, workload="clustered",
                blobs=blobs, blob_sigma=blob_sigma, tenants=names,
                tenant_skew=skew_a, qps=offered_qps))
        iso_totals = []
        for t in range(trials):
            out = [None] * tenants

            def one(i, n, t=t):
                out[i] = _run_loadgen(
                    iso[n][2], duration_s=duration_s,
                    concurrency=iso_conc[i], batch=batch,
                    seed=seed + 100 + t * tenants + i,
                    workload="clustered", blobs=blobs,
                    blob_sigma=blob_sigma,
                    qps=round(offered_qps * w[i], 3))

            ths = [threading.Thread(target=one, args=(i, n))
                   for i, n in enumerate(names)]
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            iso_totals.append({
                "qps_total": round(sum(_goodput(r) for r in out if r), 2),
                "per_tenant_qps": {n: (_goodput(out[i]) if out[i]
                                       else None)
                                   for i, n in enumerate(names)}})

        # ... and parity again after the load has churned both pools
        parity_hot = parity()
        pool_stats = shared.slab_pool.stats()
    finally:
        srv.close()
        for n in iso:
            iso[n][1].close()
            iso[n][0].close()
        shared.close()

    med_shared = sorted(shared_reps, key=_goodput)[len(shared_reps) // 2]
    med_iso = sorted(iso_totals, key=lambda r: r["qps_total"])[
        len(iso_totals) // 2]
    qps_ratio = (round(_goodput(med_shared) / med_iso["qps_total"], 3)
                 if med_iso["qps_total"] else None)
    tenancy_rep = med_shared.get("tenancy", {})
    cold_roll = tenancy_rep.get("hot_cold", {}).get("cold", {})
    cold_p99 = cold_roll.get("p99_ms")
    parity_all = all(parity_cold.values()) and all(parity_hot.values())
    return {
        "kind": "serve_tenancy_bench", "tenants": tenants,
        "points_per_tenant": points_per_tenant, "k": k,
        "num_slabs_per_tenant": num_slabs,
        "budget_slabs_total": budget_slabs_total,
        "device_budget_bytes": budget,
        "iso_device_budget_bytes_each": iso_budget,
        "zipf_a": skew_a, "duration_s": duration_s,
        "concurrency": concurrency, "iso_concurrency": iso_conc,
        "batch": batch, "trials": trials, "workload": "clustered",
        "blobs": blobs, "blob_sigma": blob_sigma,
        "offered_qps": offered_qps,
        "offered_qps_per_tenant": [round(offered_qps * wi, 3) for wi in w],
        "promote_delay_model_s": promote_delay_s,
        "qps_shared": _goodput(med_shared),
        "qps_shared_trials": [_goodput(r) for r in shared_reps],
        "qps_isolated_total": med_iso["qps_total"],
        "qps_isolated_trials": [t["qps_total"] for t in iso_totals],
        "qps_isolated_per_tenant": med_iso["per_tenant_qps"],
        "qps_ratio": qps_ratio, "qps_ratio_floor": qps_ratio_floor,
        "qps_ratio_ok": bool(qps_ratio is not None
                             and qps_ratio >= qps_ratio_floor),
        "per_tenant": tenancy_rep.get("per_tenant"),
        "hot_cold": tenancy_rep.get("hot_cold"),
        "cold_tenant": names[-1], "cold_p99_ms": cold_p99,
        "cold_p99_ceiling_ms": cold_p99_ceiling_ms,
        "cold_p99_ok": bool(cold_p99 is not None
                            and cold_p99 <= cold_p99_ceiling_ms),
        "compile_count_shared": shared_compiles,
        "compile_count_single_tenant": single_compiles,
        "compile_flat": bool(shared_compiles <= single_compiles),
        "bitwise_parity_cold": parity_cold,
        "bitwise_parity_hot": parity_hot,
        "parity_all": bool(parity_all),
        "pool_tenants": pool_stats.get("tenants"),
    }


def run_recall_bench(*, n_points=131072, k=16, bucket_size=64,
                     n_queries=384, targets=(0.85, 0.95, 0.99),
                     duration_s=2.0, concurrency=4, batch=64, trials=3,
                     seed=0, speedup_floor=3.0) -> dict:
    """Recall-SLO tier bench (serve/recall.py): measures what the
    approximate tier actually delivers and gates the claims in CI
    (``recall_compare`` in BENCH_serve.json).

    The index is CLUSTERED — 8 dense Gaussian blobs over a 1% uniform
    background, the shape real point sets have — because that is where
    exact serving pays a genuine certification tail: a query's kth
    radius sweeps through sparse big-box buckets that almost never hold
    a winner, and the prune-heavy plans cut exactly that tail (recall
    survives because the nearest-first schedule walks the dense buckets
    first). The loadgen workload generators draw their own blob centers,
    so clustered/sweep queries land off the index's blobs — the realistic
    case, not a best case.

    Three gates ride the exit code:

    1. recall_targets_ok — for every requested target and every
       calibrated workload shape (uniform / clustered / sweep, the
       harness's generators), the plan the policy selects must MEASURE
       at or above the REQUESTED target against the exact engine's ids.
    2. speedup_ok — the approximate tier at the cheapest target must
       serve >= ``speedup_floor`` x the exact engine's q/s on the
       clustered workload. Both sides are timed at the ENGINE tier
       (in-process, same batch slicing) where the comparison is
       deterministic; the HTTP end-to-end q/s split is recorded
       alongside as trajectory data (it dilutes with transport overhead
       and the loadgen client's own CPU, so it does not gate).
    3. exact_bitwise + contract_ok — a no-recall probe through the live
       server must be BITWISE identical (dists AND ids) to the engine's
       direct exact answer (the pre-tier path, untouched), and the
       approximate response contract must hold end to end: JSON
       ``exact: false`` + ``recall_target`` / ``recall_estimated`` /
       ``recall_plan``, the binary codec's X-Knn-* headers, the /stats
       recall section, the /metrics recall series, and every loadgen
       request carrying a target landing in the approx tier."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.recall import (
        RecallPolicy,
        measured_recall,
    )
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server
    from tools.recall_harness import workload_queries

    rng = np.random.default_rng(seed)
    centers = rng.random((8, 3))
    n_bg = n_points // 100
    pts = np.concatenate([
        centers[rng.integers(8, size=n_points - n_bg)]
        + rng.normal(0.0, 0.02, (n_points - n_bg, 3)),
        rng.random((n_bg, 3))])
    points = np.clip(pts, 0.0, 1.0).astype(np.float32)
    mb = 256
    engine = ResidentKnnEngine(points, k, mesh=get_mesh(1), engine="tiled",
                               bucket_size=bucket_size, max_batch=mb,
                               min_batch=16)
    policy = RecallPolicy()

    def run(q, plan=None):
        return np.concatenate(
            [np.asarray(engine.query(q[i:i + mb], plan=plan)[1])
             for i in range(0, len(q), mb)])

    workloads = ("uniform", "clustered", "sweep")
    queries = {wl: workload_queries(wl, n_queries, seed + 1,
                                    blob_sigma=0.05)
               for wl in workloads}
    exact_idx = {wl: run(q) for wl, q in queries.items()}

    per_target, plans_used = {}, {}
    recall_ok = True
    for t in targets:
        plan = policy.plan_for(t)
        row = {"plan": plan.name if plan else "exact", "measured": {}}
        for wl, q in queries.items():
            r = 1.0 if plan is None else measured_recall(run(q, plan),
                                                         exact_idx[wl])
            row["measured"][wl] = round(r, 4)
        row["met"] = all(v >= t for v in row["measured"].values())
        recall_ok = recall_ok and row["met"]
        per_target[f"{t:g}"] = row
        plans_used[f"{t:g}"] = plan

    # engine-tier q/s, exact vs the cheapest target's plan, clustered
    # workload (both programs are warm from the recall passes above)
    cheap = plans_used[f"{min(targets):g}"]
    qc = queries["clustered"]

    def best_s(plan):
        best = float("inf")
        for _ in range(max(1, trials)):
            t0 = time.perf_counter()
            run(qc, plan)
            best = min(best, time.perf_counter() - t0)
        return best

    exact_s, approx_s = best_s(None), best_s(cheap)
    speedup = exact_s / max(approx_s, 1e-9)

    # the served contract, end to end over HTTP
    srv = build_server(engine, port=0, max_delay_s=0.004, pipeline_depth=2,
                       recall_policy=policy)
    srv.ready = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    contract = {}
    try:
        probe = np.random.default_rng(seed + 7).random((64, 3)).astype(
            np.float32)
        got_d, got_i = _post_probe(base, probe)
        want_d, want_i = engine.query(probe)
        exact_bitwise = (
            np.array_equal(got_d, np.asarray(want_d, np.float32))
            and np.array_equal(got_i, np.asarray(want_i)))

        mid = f"{sorted(targets)[len(targets) // 2]:g}"
        mid_plan = plans_used[mid]
        body = json.dumps({"queries": probe[:8].tolist(),
                           "recall": float(mid)}).encode()
        req = urllib.request.Request(
            base + "/knn", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            obj = json.loads(resp.read())
        contract["json_fields"] = (
            obj.get("exact") is False
            and obj.get("recall_plan") == mid_plan.name
            and obj.get("recall_target") == float(mid)
            and obj.get("recall_estimated") == mid_plan.recall_estimated)
        req = urllib.request.Request(
            base + f"/knn?recall={mid}", data=probe[:8].tobytes(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            hdrs = resp.headers
            resp.read()
        contract["binary_headers"] = (
            hdrs.get("X-Knn-Exact") == "0"
            and hdrs.get("X-Knn-Recall-Plan") == mid_plan.name
            and hdrs.get("X-Knn-Recall-Target") == mid)
        with urllib.request.urlopen(base + "/stats", timeout=60) as resp:
            stats = json.loads(resp.read())
        contract["stats_surface"] = (
            stats.get("recall", {}).get("tiers", {}).get("approx", 0) > 0
            and mid_plan.name in stats.get("recall", {}).get(
                "policy", {}).get("selected", {}))
        with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
            metrics = resp.read().decode()
        contract["metrics_surface"] = (
            "knn_recall_requests_total" in metrics
            and "knn_recall_estimated_bucket" in metrics)

        rep_exact = _run_loadgen(base, duration_s=duration_s,
                                 concurrency=concurrency, batch=batch,
                                 seed=seed + 3, workload="clustered",
                                 blob_sigma=0.05)
        rep_approx = _run_loadgen(base, duration_s=duration_s,
                                  concurrency=concurrency, batch=batch,
                                  seed=seed + 3, workload="clustered",
                                  blob_sigma=0.05, recall=min(targets))
        tier = rep_approx.get("recall", {})
        contract["loadgen_tier"] = (
            tier.get("approx_requests", 0) > 0
            and tier.get("approx_share", 0.0) >= 1.0)
    finally:
        srv.close()
    contract_ok = all(contract.values())
    return {
        "kind": "serve_recall_bench", "n_points": n_points, "k": k,
        "bucket_size": bucket_size, "n_queries": n_queries,
        "workloads": list(workloads), "targets": [f"{t:g}" for t in targets],
        "policy": policy.stats()["plans"],
        "per_target": per_target,
        "qps_exact_engine": round(len(qc) / exact_s, 1),
        "qps_approx_engine": round(len(qc) / approx_s, 1),
        "speedup_clustered": round(speedup, 2),
        "speedup_floor": speedup_floor,
        "qps_exact_http": rep_exact.get("qps", 0) * batch,
        "qps_approx_http": rep_approx.get("qps", 0) * batch,
        "contract": contract,
        "recall_targets_ok": bool(recall_ok),
        "speedup_ok": bool(speedup >= speedup_floor),
        "exact_bitwise": bool(exact_bitwise),
        "contract_ok": bool(contract_ok),
    }


def run_multihost_bench(*, n_points=8192, k=16, hosts=2, duration_s=2.0,
                        concurrency=8, batch=64, max_batch=128,
                        max_delay_s=0.008, trials=2, seed=0) -> dict:
    """Pod serving (2 simulated host processes over ONE global CPU mesh +
    the fan-out front end) vs a single-process server of the SAME config
    (same mesh size, merge=device, same AOT programs).

    The headline number is DETERMINISTIC fetch accounting, not a timing:
    under the pod-mesh device merge each host fetches only its addressable
    1/R row slices, so the POD's fetched result bytes per row must equal
    the single-process server's — i.e. ``hosts`` x fewer than the
    every-host-fetches-the-full-result design
    (``fetch_ratio_per_host_fetch_vs_pod`` ~ hosts). ``qps_ratio`` is
    trajectory data on a shared box; only oracle-exactness (through the
    full front-end fan-out/assembly path) gates the exit code.
    """
    _setup_cpu_fixture(hosts)  # the single-process twin runs the same R
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        build_frontend,
        wait_hosts_ready,
    )
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)

    eng = ResidentKnnEngine(points, k, mesh=get_mesh(hosts), engine="tiled",
                            bucket_size=64, max_batch=max_batch,
                            min_batch=16, merge="device")
    eng.warmup()

    def loadgen_trial(base, trial):
        exact = _probe_oracle_exact(base, points, k, seed)
        rep = _run_loadgen(base, duration_s=duration_s,
                           concurrency=concurrency, batch=batch,
                           seed=seed + trial)
        rep["oracle_exact"] = exact
        return rep

    def single_trial(trial):
        srv = build_server(eng, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=2)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            return loadgen_trial(
                f"http://127.0.0.1:{srv.server_address[1]}", trial)
        finally:
            srv.close()

    # --- pod: one serve_main process per host, 1 device each, one global
    # mesh (jax.distributed over gloo)
    env = _pod_env()
    with tempfile.NamedTemporaryFile(suffix=".float3", delete=False) as f:
        pts_path = f.name
    points.tofile(pts_path)
    coord = _free_port()
    ports = [_free_port() for _ in range(hosts)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    base_cmd = [sys.executable, "-m",
                "mpi_cuda_largescaleknn_tpu.cli.serve_main",
                pts_path, "-k", str(k), "--engine", "tiled",
                "--bucket-size", "64", "--max-batch", str(max_batch),
                "--min-batch", "16", "--merge", "device",
                "--coordinator", f"127.0.0.1:{coord}",
                "--num-hosts", str(hosts)]
    procs = [subprocess.Popen(
        base_cmd + ["--host-id", str(i), "--port", str(ports[i])],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True) for i in range(hosts)]
    fe = None
    try:
        try:
            wait_hosts_ready(urls, timeout_s=600.0)
        except TimeoutError as e:
            errs = [p.communicate()[1][-500:] if p.poll() is not None
                    else "<running>" for p in procs]
            return {"kind": "serve_multihost_bench", "hosts": hosts,
                    "error": f"{e} :: {errs}"}
        fe = build_frontend(urls, port=0, max_delay_s=max_delay_s,
                            pipeline_depth=2)
        fe.ready = True
        threading.Thread(target=fe.serve_forever, daemon=True).start()
        fe_url = f"http://127.0.0.1:{fe.server_address[1]}"

        pod_trial = lambda trial: loadgen_trial(fe_url, trial)  # noqa: E731

        single_trial(trials)  # cold-start burn (see run_smoke)
        pod_trial(trials)
        runs = {"single": [], "pod": []}
        for trial in range(trials):
            runs["single"].append(single_trial(trial))
            runs["pod"].append(pod_trial(trial))

        def scrape_engine(url):
            with urllib.request.urlopen(url + "/stats", timeout=30) as r:
                return json.loads(r.read().decode())["engine"]

        host_engines = [scrape_engine(u) for u in urls]
        pod_fetch = sum(e["fetch_bytes"] for e in host_engines)
        pod_rows = sum(e["result_rows"] for e in host_engines)
        single_stats = eng.stats()

        out = {
            "kind": "serve_multihost_bench", "hosts": hosts,
            "n_points": n_points, "k": k, "pipeline_depth": 2,
            "duration_s": duration_s, "concurrency": concurrency,
            "batch": batch, "trials": trials,
        }
        for key, reps in runs.items():
            med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
            out[key] = {"qps": med["qps"], "p99_ms": med["p99_ms"],
                        "qps_trials": [r["qps"] for r in reps],
                        "oracle_exact": all(r["oracle_exact"]
                                            for r in reps)}
        single_per_row = (single_stats["fetch_bytes"]
                          / max(1, single_stats["result_rows"]))
        pod_per_row = pod_fetch / max(1, pod_rows)
        out["fetch_bytes_per_row_single"] = round(single_per_row, 2)
        out["fetch_bytes_per_row_pod"] = round(pod_per_row, 2)
        # the hosts-x claim: a per-host-fetch design pays hosts x the
        # single-process result bytes; the pod-mesh merge pays ~1 x
        out["fetch_ratio_per_host_fetch_vs_pod"] = round(
            hosts * single_per_row / max(pod_per_row, 1e-9), 2)
        # regression FLOOR on the pod-vs-single q/s ratio: the
        # replicate-everything pod legitimately trails one process on this
        # co-located CPU fixture (gloo collectives + doubled traversal),
        # but a collapse below 0.5 means the fan-out itself broke — that
        # gates, shared-box noise above the floor does not
        out["qps_ratio_floor"] = 0.5
        out["per_host_engines"] = [
            {"process_index": e["process_index"],
             "my_positions": e["my_positions"],
             "fetch_bytes": e["fetch_bytes"],
             "result_rows": e["result_rows"],
             "compile_count": e["compile_count"]} for e in host_engines]
        out["oracle_exact"] = (out["single"]["oracle_exact"]
                               and out["pod"]["oracle_exact"])
        if out["single"]["qps"]:
            out["qps_ratio_pod_vs_single"] = round(
                out["pod"]["qps"] / out["single"]["qps"], 3)
            out["qps_ratio_ok"] = (out["qps_ratio_pod_vs_single"]
                                   >= out["qps_ratio_floor"])
        return out
    finally:
        if fe is not None:
            fe.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        os.unlink(pts_path)


def run_routing_bench(*, n_points=32768, k=64, hosts=2, duration_s=2.0,
                      concurrency=12, batch=32, max_batch=128,
                      max_delay_s=0.008, blobs=8, blob_sigma=0.02,
                      trials=2, seed=0) -> dict:
    """Shard-local routing (``--routing bounds``) vs the replicate-
    everything pod (``--routing off``) on clustered AND uniform workloads:
    the same 2 host processes + front end either replicate every batch
    pod-wide (global-mesh collectives) or serve routed slab sub-batches.

    The index file is Morton-sorted — the io partitioner's production
    order — so the row slabs are spatially tight boxes and the bounds
    table can actually prune; a handful of rows are duplicated ACROSS the
    slab boundary so the bitwise probe exercises cross-host distance-0
    ties. The probe batch (clustered + uniform + on-duplicate queries,
    with neighbor ids) must be BIT-IDENTICAL between the two configs and
    oracle-exact — that gates the exit code; the q/s ratios are the
    headline trajectory numbers (clustered should clear ~1.5 x: most
    queries certify after one host, so each host traverses a fraction of
    the rows and no gloo collective runs at all; uniform should hold
    ~0.9 x: same total traversal work, minus collectives, plus an
    escalation round trip).

    Fixture shape matters on the 2-core CI box: the default is LARGER
    (32k points) and DEEPER (k=64) than the other serving benches, and the
    per-request batch is small (32) — at 8k/k=16 both configs saturate the
    HTTP/client transport ceiling (clustered traffic is already tile-skip
    cheap after PR 4, so there is no traversal left to route away), and a
    one-blob-per-request batch of 64+ rows routes as one lump to one host
    (imbalance eats the win). 32k x k=64 keeps the traversal compute-bound
    even under the per-bucket prune, and 32-row requests coalesce into
    mixed-blob pod batches whose sub-batches balance. BOTH pods stay
    resident and the trials interleave (the other benches' shared-box
    discipline) — sequential config runs were noise-dominated.
    """
    _setup_cpu_fixture(1)  # this process only runs HTTP + numpy folds
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        build_frontend,
        wait_hosts_ready,
    )
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort
    from tests.oracle import kth_nn_dist

    rng = np.random.default_rng(seed)
    pts = rng.random((n_points, 3)).astype(np.float32)
    pts = pts[morton_argsort(pts, pts.min(0), pts.max(0))]
    # duplicate 4 rows across the slab boundary: exact coordinate copies
    # with different global ids — the tie probe's cross-host targets
    # (adjacent in Morton order, so the slab boxes barely widen)
    half = n_points // hosts
    pts[half:half + 4] = pts[half - 4:half]
    with tempfile.NamedTemporaryFile(suffix=".float3", delete=False) as f:
        pts_path = f.name
    pts.tofile(pts_path)

    # fixed probe: on-duplicate (tie ids), clustered, and uniform rows
    prng = np.random.default_rng(seed + 1)
    centers = prng.random((blobs, 3))
    q_probe = np.concatenate([
        pts[half - 4:half + 4],
        np.clip(centers[prng.integers(blobs, size=28)]
                + prng.normal(0, blob_sigma, (28, 3)), 0, 1),
        prng.random((28, 3)),
    ]).astype(np.float32)

    env = _pod_env()

    def probe(base_url):
        body = json.dumps({"queries": q_probe.tolist(),
                           "neighbors": True}).encode()
        req = urllib.request.Request(
            base_url + "/knn", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            obj = json.loads(resp.read())
        return (np.asarray(obj["dists"], np.float32),
                np.asarray(obj["neighbors"], np.int32))

    def boot(routing: str) -> dict:
        ports = [_free_port() for _ in range(hosts)]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        base_cmd = [sys.executable, "-m",
                    "mpi_cuda_largescaleknn_tpu.cli.serve_main",
                    pts_path, "-k", str(k), "--engine", "tiled",
                    "--bucket-size", "64", "--max-batch", str(max_batch),
                    "--min-batch", "16"]
        if routing == "bounds":
            base_cmd += ["--routing", "bounds", "--num-hosts", str(hosts)]
        else:
            base_cmd += ["--merge", "device",
                         "--coordinator", f"127.0.0.1:{_free_port()}",
                         "--num-hosts", str(hosts)]
        procs = [subprocess.Popen(
            base_cmd + ["--host-id", str(i), "--port", str(ports[i])],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True) for i in range(hosts)]
        return {"procs": procs, "urls": urls, "fe": None}

    def teardown(pod):
        if pod.get("fe") is not None:
            pod["fe"].close()
        for p in pod["procs"]:
            p.terminate()
        for p in pod["procs"]:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    pods = {}
    per_config: dict = {}
    try:
        # both pods launch up front and STAY resident for the whole run:
        # the trials interleave across configs, so slow drift on a shared
        # box lands evenly on both sides of every ratio
        pods = {"replicate": boot("off"), "routed": boot("bounds")}
        for name, pod in pods.items():
            try:
                wait_hosts_ready(pod["urls"], timeout_s=600.0)
            except TimeoutError as e:
                errs = [p.communicate()[1][-500:] if p.poll() is not None
                        else "<running>" for p in pod["procs"]]
                return {"kind": "serve_routing_bench", "hosts": hosts,
                        "error": f"{name}: {e} :: {errs}"}
            # qcache off: this ratio isolates ROUTING; radius seeding
            # would accrue only to the routed side and skew it
            fe = build_frontend(pod["urls"], port=0,
                                max_delay_s=max_delay_s, pipeline_depth=2,
                                qcache_rows=0)
            fe.ready = True
            threading.Thread(target=fe.serve_forever, daemon=True).start()
            pod["fe"] = fe
            pod["base"] = f"http://127.0.0.1:{fe.server_address[1]}"

        for name, pod in pods.items():
            d, nbr = probe(pod["base"])
            per_config[name] = {
                "probe_dists": d, "probe_nbrs": nbr,
                "oracle_exact": bool(np.allclose(
                    d, kth_nn_dist(q_probe, pts, k),
                    rtol=5e-7, atol=1e-37))}
            _run_loadgen(pod["base"], duration_s=duration_s,  # cold burn
                         concurrency=concurrency, batch=batch,
                         seed=seed + 99, workload="clustered",
                         blobs=blobs, blob_sigma=blob_sigma)

        runs = {(name, wl): [] for name in pods
                for wl in ("clustered", "uniform")}
        for trial in range(trials):
            for name, pod in pods.items():
                for wl in ("clustered", "uniform"):
                    runs[(name, wl)].append(_run_loadgen(
                        pod["base"], duration_s=duration_s,
                        concurrency=concurrency, batch=batch,
                        seed=seed + trial, workload=wl, blobs=blobs,
                        blob_sigma=blob_sigma))
        for (name, wl), reps in runs.items():
            med = sorted(reps, key=lambda r: r["qps"])[len(reps) // 2]
            per_config[name][wl] = {
                "qps": med["qps"], "p99_ms": med["p99_ms"],
                "qps_trials": [r["qps"] for r in reps]}
        fan = pods["routed"]["fe"].fanout.stats()
        per_config["routed"]["routing_stats"] = fan.get("routing")
    finally:
        for pod in pods.values():
            teardown(pod)
        os.unlink(pts_path)

    out = {
        "kind": "serve_routing_bench", "hosts": hosts,
        "n_points": n_points, "k": k, "pipeline_depth": 2,
        "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "blobs": blobs, "blob_sigma": blob_sigma,
        "trials": trials,
        "clustered_target": 1.5, "uniform_floor": 0.9,
    }
    rep, rou = per_config["replicate"], per_config["routed"]
    if "error" in rep or "error" in rou:
        out["error"] = rep.get("error") or rou.get("error")
        return out
    out["bitwise_identical_to_routing_off"] = bool(
        np.array_equal(rep["probe_dists"], rou["probe_dists"])
        and np.array_equal(rep["probe_nbrs"], rou["probe_nbrs"]))
    out["oracle_exact"] = bool(rep["oracle_exact"] and rou["oracle_exact"])
    for cfg in per_config.values():
        cfg.pop("probe_dists", None)
        cfg.pop("probe_nbrs", None)
    out["per_config"] = per_config
    for wl in ("clustered", "uniform"):
        if rep[wl]["qps"]:
            out[f"qps_ratio_{wl}"] = round(rou[wl]["qps"]
                                           / rep[wl]["qps"], 3)
    out["clustered_ok"] = (out.get("qps_ratio_clustered", 0)
                           >= out["clustered_target"])
    out["uniform_ok"] = (out.get("qps_ratio_uniform", 0)
                         >= out["uniform_floor"])
    return out


def run_chaos_bench(*, n_points=8192, k=16, hosts=2, duration_s=2.0,
                    concurrency=8, batch=8, max_batch=128,
                    max_delay_s=0.008, seed=0) -> dict:
    """Chaos bench: kill one routed host mid-load (a deterministic
    serve/faults.py ``drop`` outage injected through POST /faults — the
    process-kill stand-in the fault layer exists for), measure
    availability + degraded-rate under the loss, then lift the outage and
    measure recovery time and post-rejoin BITWISE parity with the
    pre-outage answers.

    Topology: 2 in-process routed slab hosts + the real front end at
    ``--on-host-loss degrade`` with a fast health monitor; load rides
    tools/loadgen.py in a subprocess (its availability/status-code/
    degraded accounting is the measurement). Each slab engine runs a
    1-device mesh: with NO in-program collectives, two engines' programs
    can overlap freely on the shared CPU backend (two concurrent
    all_to_all programs would starve each other's XLA device threads and
    rendezvous-deadlock — routed hosts in production are separate
    processes, so only this co-located fixture cares).
    Three phases land in the report: ``healthy`` (baseline), ``outage``
    (one host dropping every request), ``recovered`` (after the monitor
    rejoined the host). Gates: outage-phase availability >=
    ``availability_floor`` (degrade mode keeps answering — flagged, not
    refused) and ``bitwise_parity_after_rejoin`` (the fixed probe batch's
    dists AND neighbor ids byte-equal before vs after the incident).
    """
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        HostSliceServer,
        build_frontend,
    )
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    points = points[morton_argsort(points, points.min(0), points.max(0))]

    servers = []
    for b, e in slab_bounds(len(points), hosts):
        eng = ResidentKnnEngine(points[b:e], k, mesh=get_mesh(1),
                                engine="tiled", bucket_size=64,
                                max_batch=max_batch, min_batch=16,
                                id_offset=b, emit="candidates")
        eng.warmup()
        srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv.ready = True
        servers.append(srv)
    urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]
    victim = urls[-1]

    # qcache off: the outage probes re-post one batch across phases —
    # an exact-hit would serve it without ever reaching the faulted host
    fe = build_frontend(
        urls, port=0, max_delay_s=max_delay_s, pipeline_depth=2,
        on_host_loss="degrade", retries=2, retry_backoff_s=0.01,
        request_timeout_s=30.0, qcache_rows=0,
        health_config=dict(fail_threshold=2, probe_interval_s=0.1,
                           backoff_base_s=0.05, backoff_cap_s=0.5))
    fe.ready = True
    threading.Thread(target=fe.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{fe.server_address[1]}"

    prng = np.random.default_rng(seed + 1)
    q_probe = prng.random((64, 3)).astype(np.float32)

    def probe():
        body = json.dumps({"queries": q_probe.tolist(),
                           "neighbors": True}).encode()
        req = urllib.request.Request(
            base + "/knn", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            obj = json.loads(resp.read())
        return (np.asarray(obj["dists"], np.float32),
                np.asarray(obj["neighbors"], np.int32),
                bool(obj.get("exact", True)))

    def set_faults(spec):
        req = urllib.request.Request(
            victim + "/faults", data=json.dumps({"spec": spec}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=30).read()

    def victim_state():
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            return json.loads(r.read())["pod"]["health"][victim]["state"]

    def phase(trial):
        rep = _run_loadgen(base, duration_s=duration_s,
                           concurrency=concurrency, batch=batch,
                           seed=seed + trial)
        return {"qps": rep["qps"], "availability": rep["availability"],
                "error_rate": rep["error_rate"],
                "degraded_rate": rep["degraded_rate"],
                "degraded": rep["degraded"], "net_error": rep["net_error"],
                "status_counts": rep["status_counts"],
                "p99_ms": rep["p99_ms"]}

    out = {
        "kind": "serve_chaos_bench", "hosts": hosts, "n_points": n_points,
        "k": k, "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "on_host_loss": "degrade",
        "availability_floor": 0.9,
    }
    try:
        pre_d, pre_n, pre_exact = probe()
        out["pre_probe_exact"] = pre_exact
        out["healthy"] = phase(0)

        # the incident: the victim host drops every request (route, probe,
        # stats) — indistinguishable from a dead process to the front end
        set_faults("drop:")
        t_kill = time.monotonic()
        out["outage"] = phase(1)
        dur_d, dur_n, dur_exact = probe()
        out["outage_probe_exact"] = dur_exact  # False: loss is FLAGGED
        out["victim_state_during_outage"] = victim_state()

        # recovery: lift the outage, let the monitor re-probe + rejoin
        set_faults("")
        t_clear = time.monotonic()
        deadline = t_clear + 60.0
        state = victim_state()
        while state != "healthy" and time.monotonic() < deadline:
            time.sleep(0.05)
            state = victim_state()
        out["victim_state_after_clear"] = state
        out["recovery_s"] = round(time.monotonic() - t_clear, 3)
        out["outage_total_s"] = round(time.monotonic() - t_kill, 3)
        out["recovered"] = phase(2)

        post_d, post_n, post_exact = probe()
        out["post_probe_exact"] = post_exact
        out["bitwise_parity_after_rejoin"] = bool(
            post_exact and np.array_equal(pre_d, post_d)
            and np.array_equal(pre_n, post_n))
        avail = out["outage"]["availability"]
        out["availability_ok"] = (avail is not None
                                  and avail >= out["availability_floor"])
        out["degraded_served_during_outage"] = (
            out["outage"]["degraded"] > 0 or not dur_exact)
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            st = json.loads(r.read())
        out["monitor"] = st["pod"]["monitor"]
        out["health_after"] = {u: h["state"]
                               for u, h in st["pod"]["health"].items()}
        out["host_retries"] = {
            u: h["retries"] for u, h in st["fanout"]["health"].items()}
        out["degraded_responses_total"] = st["server"].get(
            "knn_degraded_responses_total", 0)
    finally:
        fe.close()
        for s in servers:
            s.close()
    return out


def run_replica_bench(*, n_points=6144, k=8, slabs=2, replicas=2,
                      duration_s=2.0, concurrency=8, batch=8,
                      max_batch=64, max_delay_s=0.008, seed=0) -> dict:
    """Replica bench: a rolling single-host kill across an R=2 routed pod
    with a warm standby, gating on ZERO ``exact: false`` responses,
    availability >= 0.999, and the post-handoff probe being BITWISE
    identical to the pre-kill answers (``replica_compare``).

    Topology: ``slabs`` x ``replicas`` in-process routed hosts (replicas
    of a slab share one engine — byte-interchangeable by contract, so
    the ADOPTED standby, which re-materializes the slab itself, is the
    real parity subject) + the real front end at ``--on-host-loss
    degrade`` with ``handoff_floor=replicas`` (any single loss starts a
    handoff) and a fast health monitor. The roll: kill slab 0's second
    replica mid-load (loadgen must see zero degraded answers — the
    sibling absorbs the slab), wait for the standby to adopt + bind,
    probe bitwise parity, then kill slab 0's FIRST replica too — the
    slab is now served exclusively by the adopted standby, and the final
    probe must still be bitwise-equal to the never-failed answers. An
    R=1 twin of the same engines measures what the replication costs
    (``qps_ratio_r2_vs_r1``; trajectory data, not a gate). 1-device
    meshes per slab engine, the chaos bench's co-location discipline.
    """
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        HostSliceServer,
        build_frontend,
    )
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    points = points[morton_argsort(points, points.min(0), points.max(0))]
    with tempfile.NamedTemporaryFile(suffix=".float3", delete=False) as f:
        pts_path = f.name
    points.tofile(pts_path)

    def boot_host(eng, **kw):
        srv = HostSliceServer(("127.0.0.1", 0), eng, routing="bounds",
                              **kw)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        if eng is not None:
            srv.ready = True
        return srv

    engines = []
    for b, e in slab_bounds(n_points, slabs):
        eng = ResidentKnnEngine(points[b:e], k, mesh=get_mesh(1),
                                engine="tiled", bucket_size=64,
                                max_batch=max_batch, min_batch=16,
                                id_offset=b, emit="candidates")
        eng.warmup()
        engines.append(eng)
    r2_servers = [boot_host(engines[s]) for s in range(slabs)
                  for _ in range(replicas)]
    r1_servers = [boot_host(engines[s]) for s in range(slabs)]
    standby = boot_host(None, standby_config=dict(
        path=pts_path, num_hosts=slabs, k=k, shards=1, engine="tiled",
        bucket_size=64, max_batch=max_batch, min_batch=16))
    urls_r2 = [f"http://127.0.0.1:{s.server_address[1]}"
               for s in r2_servers]
    urls_r1 = [f"http://127.0.0.1:{s.server_address[1]}"
               for s in r1_servers]
    sb_url = f"http://127.0.0.1:{standby.server_address[1]}"
    hc = dict(fail_threshold=2, probe_interval_s=0.1,
              backoff_base_s=0.05, backoff_cap_s=0.5)
    # qcache off on both: the kill/handoff probes re-post one batch —
    # cached hits would mask the replica-spread and post-handoff paths
    fe2 = build_frontend(urls_r2, port=0, max_delay_s=max_delay_s,
                         pipeline_depth=2, on_host_loss="degrade",
                         retries=2, retry_backoff_s=0.01,
                         request_timeout_s=30.0, standbys=[sb_url],
                         handoff_floor=replicas, health_config=hc,
                         qcache_rows=0)
    fe1 = build_frontend(urls_r1, port=0, max_delay_s=max_delay_s,
                         pipeline_depth=2, on_host_loss="degrade",
                         retries=2, retry_backoff_s=0.01,
                         request_timeout_s=30.0, health_config=hc,
                         qcache_rows=0)
    for fe in (fe1, fe2):
        fe.ready = True
        threading.Thread(target=fe.serve_forever, daemon=True).start()
    base2 = f"http://127.0.0.1:{fe2.server_address[1]}"
    base1 = f"http://127.0.0.1:{fe1.server_address[1]}"

    prng = np.random.default_rng(seed + 1)
    q_probe = prng.random((64, 3)).astype(np.float32)

    def probe():
        body = json.dumps({"queries": q_probe.tolist(),
                           "neighbors": True}).encode()
        req = urllib.request.Request(
            base2 + "/knn", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            obj = json.loads(resp.read())
        return (np.asarray(obj["dists"], np.float32),
                np.asarray(obj["neighbors"], np.int32),
                bool(obj.get("exact", True)))

    def kill(url):
        req = urllib.request.Request(
            url + "/faults", data=json.dumps({"spec": "drop:"}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=30).read()

    def fe2_stats():
        with urllib.request.urlopen(base2 + "/stats", timeout=30) as r:
            return json.loads(r.read())

    def phase(base, trial):
        rep = _run_loadgen(base, duration_s=duration_s,
                           concurrency=concurrency, batch=batch,
                           seed=seed + trial)
        return {"qps": rep["qps"], "availability": rep["availability"],
                "degraded": rep["degraded"],
                "degraded_rate": rep["degraded_rate"],
                "net_error": rep["net_error"],
                "status_counts": rep["status_counts"],
                "p99_ms": rep["p99_ms"]}

    out = {
        "kind": "serve_replica_bench", "slabs": slabs,
        "replicas": replicas, "n_points": n_points, "k": k,
        "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "handoff_floor": replicas,
        "on_host_loss": "degrade", "availability_floor": 0.999,
    }
    try:
        pre_d, pre_n, pre_exact = probe()
        out["pre_probe_exact"] = pre_exact
        out["healthy_r2"] = phase(base2, 0)
        out["healthy_r1"] = phase(base1, 0)

        # roll 1: kill slab 0's SECOND replica mid-pod — the sibling
        # absorbs the slab, so loadgen must see zero degraded answers
        kill(urls_r2[1])
        t_kill = time.monotonic()
        out["outage1"] = phase(base2, 1)
        # the handoff: floor=replicas, so live 1 < 2 starts an adoption;
        # wait for the standby to adopt + the monitor to bind it
        deadline = time.monotonic() + 180.0
        bound = False
        while time.monotonic() < deadline:
            st = fe2_stats()
            ho = (st["pod"]["monitor"] or {}).get("handoff") or {}
            if ho.get("handoffs", 0) >= 1:
                bound = True
                break
            if ho.get("handoff_failures", 0) or ho.get(
                    "handoff_rejections", 0):
                break
            time.sleep(0.1)
        out["handoff_bound"] = bound
        out["handoff_s"] = round(time.monotonic() - t_kill, 3)
        st = fe2_stats()
        out["handoff_stats"] = (st["pod"]["monitor"] or {}).get("handoff")
        mid_d, mid_n, mid_exact = probe()
        out["post_handoff_probe_exact"] = mid_exact
        out["post_handoff_parity"] = bool(
            mid_exact and np.array_equal(pre_d, mid_d)
            and np.array_equal(pre_n, mid_n))

        # roll 2: kill slab 0's FIRST replica too — the slab now rides
        # the adopted standby alone; exactness and bytes must hold
        kill(urls_r2[0])
        out["outage2"] = phase(base2, 2)
        post_d, post_n, post_exact = probe()
        out["final_probe_exact"] = post_exact
        out["final_parity"] = bool(
            post_exact and np.array_equal(pre_d, post_d)
            and np.array_equal(pre_n, post_n))

        replica_stats = fe2_stats()["fanout"]["routing"]["replicas"]
        out["slab_live_after_roll"] = [p["live"] for p in
                                       replica_stats["per_slab"]]
        out["replica_spread"] = replica_stats["spread"]
        phases = [out["healthy_r2"], out["outage1"], out["outage2"]]
        out["zero_inexact"] = bool(
            pre_exact and mid_exact and post_exact
            and all(p["degraded"] == 0 for p in phases))
        avails = [p["availability"] for p in phases]
        out["availability_min"] = (min(avails)
                                   if all(a is not None for a in avails)
                                   else None)
        out["availability_ok"] = (
            out["availability_min"] is not None
            and out["availability_min"] >= out["availability_floor"])
        out["bitwise_parity_after_handoff"] = bool(
            out["post_handoff_parity"] and out["final_parity"])
        if out["healthy_r1"]["qps"]:
            out["qps_ratio_r2_vs_r1"] = round(
                out["healthy_r2"]["qps"] / out["healthy_r1"]["qps"], 3)
    finally:
        fe2.close()
        fe1.close()
        for s in r2_servers + r1_servers + [standby]:
            s.close()
        os.unlink(pts_path)
    return out


def run_kernel_bench(*, dims=(3, 8, 64), n_points=8192, n_queries=1024,
                     k=16, bucket_size=128, reps=5, seed=0) -> dict:
    """Elementwise (VPU) vs MXU matmul-form traversal kernel at each D:
    tile-rows/s and q/s through ``knn_update_tiled`` under score_dtype
    f32 vs bf16, plus the bitwise-exactness check that gates the exit
    code (the speed ratios are trajectory data like every other bench).

    Runs the SHIPPED configuration: below ``mxu_min_dim()`` (D=3, D=8 by
    default) a bf16 request scores exactly on the VPU — the expected
    ratio there is ~1.0 by construction — while high D rides the
    3-dot_general split-bf16 cross term + exact f32 rescore.
    """
    _setup_cpu_fixture(1)
    import jax
    import jax.numpy as jnp

    from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
    from mpi_cuda_largescaleknn_tpu.ops.distance import (
        mxu_min_dim,
        rescore_width,
    )
    from mpi_cuda_largescaleknn_tpu.ops.partition import partition_points
    from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled

    rng = np.random.default_rng(seed)
    out = {
        "kind": "kernel_bench", "n_points": n_points,
        "n_queries": n_queries, "k": k, "bucket_size": bucket_size,
        "reps": reps, "mxu_min_dim": mxu_min_dim(),
        "rescore_width": rescore_width(k, 1 << 30),
        "tile_row_units": "query row x point-tile visit (engine units)",
        "per_dim": {},
    }
    all_exact = True
    for d in dims:
        pts = rng.random((n_points, d)).astype(np.float32)
        qs = rng.random((n_queries, d)).astype(np.float32)
        p = partition_points(jnp.asarray(pts), bucket_size=bucket_size)
        q = partition_points(jnp.asarray(qs), bucket_size=bucket_size)
        st = init_candidates(q.num_buckets * q.bucket_size, k)
        row = {}
        results, fns, tile_rows, best = {}, {}, {}, {}
        for mode in ("f32", "bf16"):
            fns[mode] = jax.jit(lambda st, q, p, m=mode: knn_update_tiled(
                st, q, p, with_stats=True, score_dtype=m))
            res, tiles = fns[mode](st, q, p)
            jax.block_until_ready(res)          # compile + warm
            results[mode] = res
            tile_rows[mode] = int(tiles) * q.bucket_size
            best[mode] = float("inf")
        # interleave the timed reps AND alternate which mode goes first
        # each rep, so CPU-frequency and cache drift on a shared box
        # spread evenly across both modes (the same discipline as the
        # serving benches' interleaved trials)
        for rep in range(reps):
            order = ("f32", "bf16") if rep % 2 == 0 else ("bf16", "f32")
            for mode in order:
                t0 = time.perf_counter()
                r2, _t2 = fns[mode](st, q, p)
                jax.block_until_ready(r2)
                best[mode] = min(best[mode], time.perf_counter() - t0)
        for mode in ("f32", "bf16"):
            row[mode] = {
                "seconds": round(best[mode], 4),
                "tile_rows": tile_rows[mode],
                "tile_rows_per_s": round(tile_rows[mode] / best[mode], 1),
                "qps": round(n_queries / best[mode], 1),
            }
        exact = (np.array_equal(np.asarray(results["f32"].dist2),
                                np.asarray(results["bf16"].dist2))
                 and np.array_equal(np.asarray(results["f32"].idx),
                                    np.asarray(results["bf16"].idx)))
        all_exact = all_exact and exact
        row["exact_bitwise"] = bool(exact)
        row["mxu_engaged"] = d >= mxu_min_dim()
        # below the threshold both modes compile the IDENTICAL elementwise
        # program (the no-regression-at-low-D guarantee is architectural);
        # their measured ratio is pure box noise around 1.0
        row["same_program"] = d < mxu_min_dim()
        row["speedup_mxu_vs_vpu"] = round(
            row["bf16"]["tile_rows_per_s"] / row["f32"]["tile_rows_per_s"],
            3)
        out["per_dim"][str(d)] = row
    out["exact_bitwise"] = bool(all_exact)
    for d in dims:
        out[f"speedup_d{d}"] = out["per_dim"][str(d)]["speedup_mxu_vs_vpu"]
    return out


def run_wire_bench(*, n_points=16384, k=16, handoff_rows=131072,
                   throttle_bps=4e6, seed=0) -> dict:
    """Quantized wire exchange (serve/wire.py) vs the f32 baseline, with
    the exactness contract as the primary gate: the SAME in-process hosts
    are queried through a ``wire=f32`` front end and a ``wire=auto``
    (negotiated q16) front end, and every probe answer — kth distances,
    neighbor ids including the cross-host distance-0 tie rows, exact
    flags — must be BITWISE identical on four pod shapes: plain routed
    (2 slabs), replicated (2 slabs x R=2), streaming (one host streams 4
    sub-slabs), and mixed (one ``--wire f32`` host: the old-binary
    emulation must degrade to negotiated fallback, never a decode
    error). Byte accounting from the fan-outs' own WireStats gates
    candidate-exchange bytes-per-row at <= 0.45x f32 (the q16 layout:
    elided-anchor u16 level planes + varint anchor/id deltas, zlib'd);
    the x32 survivor re-fetch traffic is reported alongside as the
    all-in ratio, and the handoff leg pulls the SAME dense Morton-sorted
    rows over ``/slab_rows`` as chunk-streamed f32 vs d16 under a
    bandwidth throttle (decode overlaps the pacing gap exactly like real
    transfer overlaps decode), gating wall-clock at <= 0.6x. Every
    fixture is seeded and both codecs are deterministic, so the measured
    ratios are reproducible bit-for-bit across runs."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.frontend import (
        HostSliceServer,
        build_frontend,
    )
    from mpi_cuda_largescaleknn_tpu.serve.replica import pull_slab_rows
    from mpi_cuda_largescaleknn_tpu.serve.slabpool import StreamingKnnEngine
    from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

    rng = np.random.default_rng(seed)
    pts = rng.random((n_points, 3)).astype(np.float32)
    pts = pts[morton_argsort(pts, pts.min(0), pts.max(0))]
    half = n_points // 2
    # exact coordinate copies across the slab boundary with different
    # global ids: the parity probe's cross-host distance-0 tie targets
    pts[half:half + 4] = pts[half - 4:half]

    prng = np.random.default_rng(seed + 1)
    centers = prng.random((8, 3))
    q_probe = np.concatenate([
        pts[half - 4:half + 4],
        np.clip(centers[prng.integers(8, size=20)]
                + prng.normal(0, 0.02, (20, 3)), 0, 1),
        prng.random((20, 3)),
    ]).astype(np.float32)

    mesh = get_mesh(1)
    kw = dict(mesh=mesh, engine="tiled", bucket_size=64, max_batch=64,
              min_batch=16, emit="candidates")
    eng0 = ResidentKnnEngine(pts[:half], k, id_offset=0, **kw)
    eng1 = ResidentKnnEngine(pts[half:], k, id_offset=half, **kw)
    stream0 = StreamingKnnEngine(
        points=pts[:half], num_slabs=4, k=k, mesh=mesh, engine="tiled",
        bucket_size=64, max_batch=64, min_batch=16, id_offset=0,
        emit="candidates")
    for e in (eng0, eng1):
        e.warmup()

    servers: list = []
    frontends: list = []

    def boot(engine, **skw):
        srv = HostSliceServer(("127.0.0.1", 0), engine,
                              routing="bounds", **skw)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        srv.ready = True
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    def probe(base):
        body = json.dumps({"queries": q_probe.tolist(),
                           "neighbors": True}).encode()
        req = urllib.request.Request(
            base + "/knn", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    out = {"kind": "serve_wire_bench", "n_points": n_points, "k": k,
           "handoff_rows": handoff_rows, "throttle_bps": throttle_bps,
           "bytes_per_row_gate": 0.45, "handoff_time_gate": 0.6}
    agg = {"f32": [0, 0], "q16": [0, 0], "x32": [0, 0]}
    per_pod: dict = {}
    try:
        u0a, u0b = boot(eng0), boot(eng0)
        u1a, u1b = boot(eng1), boot(eng1)
        us0 = boot(stream0)
        u1f = boot(eng1, wire="f32")
        pods = {"routed": [u0a, u1a],
                "replicated": [u0a, u0b, u1a, u1b],
                "streaming": [us0, u1a],
                "mixed_f32_host": [u0a, u1f]}
        for name, urls in pods.items():
            cell: dict = {}
            res = {}
            for mode in ("f32", "auto"):
                # qcache off: bytes-per-row must count every row's
                # exchange; reuse would undercount the wire under test
                fe = build_frontend(urls, port=0, max_delay_s=0.004,
                                    pipeline_depth=2, wire=mode,
                                    qcache_rows=0)
                fe.ready = True
                threading.Thread(target=fe.serve_forever,
                                 daemon=True).start()
                frontends.append(fe)
                res[mode] = probe(
                    f"http://127.0.0.1:{fe.server_address[1]}")
                wire = fe.fanout.stats().get("wire") or {}
                cell[f"wire_{mode}"] = wire
                # accumulate the frontend-observed candidate traffic:
                # f32-mode pods feed the baseline bpr, auto-mode pods
                # the compressed + refetch bpr
                traffic = (wire.get("traffic") or {}).get("candidates", {})
                for codec, c in traffic.items():
                    if mode == "f32" and codec != "f32":
                        continue
                    agg[codec][0] += c["bytes"]
                    agg[codec][1] += c["rows"]
            a, b = res["f32"], res["auto"]
            cell["bitwise_parity"] = bool(
                a["dists"] == b["dists"]
                and a["neighbors"] == b["neighbors"]
                and a.get("exact", True) == b.get("exact", True))
            per_pod[name] = cell
        out["per_pod"] = per_pod
        out["parity_all"] = all(c["bitwise_parity"]
                                for c in per_pod.values())
        f32_bpr = agg["f32"][0] / agg["f32"][1] if agg["f32"][1] else 0.0
        q16_bpr = agg["q16"][0] / agg["q16"][1] if agg["q16"][1] else 0.0
        out["exchange"] = {
            "f32_bytes_per_row": round(f32_bpr, 2),
            "q16_bytes_per_row": round(q16_bpr, 2),
            "x32_refetch_bytes": agg["x32"][0],
            "x32_refetch_rows": agg["x32"][1],
        }
        out["bytes_per_row_ratio"] = (round(q16_bpr / f32_bpr, 3)
                                      if f32_bpr and q16_bpr else None)
        # the all-in view: compressed wave + exact re-fetch, normalized
        # by what the same rows would have cost at f32 (trajectory data;
        # the gate is the per-codec ratio above, per the issue)
        if f32_bpr and agg["q16"][1]:
            out["total_ratio_incl_refetch"] = round(
                (agg["q16"][0] + agg["x32"][0])
                / (agg["q16"][1] * f32_bpr), 3)
        out["bytes_ok"] = bool(
            out["bytes_per_row_ratio"] is not None
            and out["bytes_per_row_ratio"] <= out["bytes_per_row_gate"])

        # ---- slab handoff: equal rows, f32 vs d16, throttled pulls ----
        hrng = np.random.default_rng(seed + 2)
        hc = hrng.random((64, 3))
        hpts = np.clip(
            hc[hrng.integers(64, size=handoff_rows)]
            + hrng.normal(0, 0.004, (handoff_rows, 3)), 0, 1,
        ).astype(np.float32)
        hpts = hpts[morton_argsort(hpts, hpts.min(0), hpts.max(0))]
        heng = ResidentKnnEngine(
            hpts, 4, mesh=mesh, engine="tiled", bucket_size=256,
            max_batch=32, min_batch=16, id_offset=0, emit="candidates")
        hurl = boot(heng)
        pull_slab_rows(hurl, wire="f32")  # connection + page warmup
        base = {codec: c["bytes"] for codec, c in
                servers[-1].wire_stats.snapshot()
                .get("slab_rows", {}).items()}
        t0 = time.perf_counter()
        rows_f32, _ = pull_slab_rows(hurl, wire="f32",
                                     throttle_bps=throttle_bps)
        t_f32 = time.perf_counter() - t0
        t0 = time.perf_counter()
        rows_d16, _ = pull_slab_rows(hurl, wire="d16",
                                     throttle_bps=throttle_bps)
        t_d16 = time.perf_counter() - t0
        htraffic = servers[-1].wire_stats.snapshot().get("slab_rows", {})
        out["handoff"] = {
            "rows": handoff_rows,
            "lossless": bool(np.array_equal(rows_f32, hpts)
                             and np.array_equal(rows_d16, hpts)),
            "seconds_f32": round(t_f32, 3),
            "seconds_d16": round(t_d16, 3),
            "time_ratio": round(t_d16 / t_f32, 3) if t_f32 else None,
            "bytes": {codec: c["bytes"] - base.get(codec, 0)
                      for codec, c in htraffic.items()},
        }
        hb = out["handoff"]["bytes"]
        if hb.get("d16") and hb.get("f32"):
            out["handoff"]["bytes_ratio"] = round(
                hb["d16"] / hb["f32"], 3)
        out["handoff_ok"] = bool(
            out["handoff"]["lossless"]
            and out["handoff"]["time_ratio"] is not None
            and out["handoff"]["time_ratio"] <= out["handoff_time_gate"])
    finally:
        for fe in frontends:
            fe.shutdown()
        for srv in servers:
            srv.shutdown()
        stream0.close()
    return out


def run_cache_bench(*, n_points=32768, k=16, duration_s=2.0,
                    concurrency=4, batch=64, max_batch=128,
                    max_delay_s=0.008, trials=2, seed=0,
                    dup_frac=0.7, revisit_sigma=0.01,
                    qps_floor=1.5) -> dict:
    """Certified query cache (serve/qcache.py) on a revisit-heavy
    stream: the SAME offered workload (``--dup-frac`` exact replays +
    Gaussian-jittered revisits of a bounded issued pool) is driven at a
    cache-enabled server and a ``qcache_rows=0`` twin over ONE shared
    warm engine, interleaved per trial so drift hits both.

    Four gates ride the exit code (``cache_compare`` in
    BENCH_serve.json): (1) revisit-workload q/s >= ``qps_floor`` x the
    cache-off twin — exact hits must actually skip device work; (2)
    seeded-vs-unseeded BITWISE parity at the engine tier — a probe batch
    near cached anchors, heaps initialized at the certified
    triangle-inequality radius, must reproduce the unseeded dists AND
    neighbor ids exactly; (3) hit-path byte identity — the same JSON
    body posted twice returns identical response BYTES; (4) flat compile
    count across the measured cached traffic — the per-query seed radius
    is a dynamic operand, so seeding must mint zero new programs."""
    _setup_cpu_fixture(1)
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.qcache import certified_seeds
    from mpi_cuda_largescaleknn_tpu.serve.server import build_server

    rng = np.random.default_rng(seed)
    points = rng.random((n_points, 3)).astype(np.float32)
    mesh = get_mesh(1)
    eng = ResidentKnnEngine(points, k, mesh=mesh, engine="tiled",
                            bucket_size=64, max_batch=max_batch,
                            min_batch=16)
    eng.warmup()
    # mint every pow2 shape bucket the load can touch BEFORE the
    # compile-flat window opens — coalescing makes the bucket sequence
    # timing-dependent, the bucket SET is not
    b = 16
    while b <= max_batch:
        eng.query(rng.random((b, 3)).astype(np.float32))
        b *= 2

    def boot(rows):
        srv = build_server(eng, port=0, max_delay_s=max_delay_s,
                           pipeline_depth=2, qcache_rows=rows,
                           qcache_seed_rows=512 if rows else 0)
        srv.ready = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, f"http://127.0.0.1:{srv.server_address[1]}"

    srv_on, base_on = boot(4096)
    srv_off, base_off = boot(0)
    try:
        # gate (2): engine-tier seeded bitwise parity, deterministic
        arng = np.random.default_rng(seed + 3)
        anchors = arng.random((64, 3)).astype(np.float32)
        a_d, _a_n = eng.query(anchors)
        probes = np.clip(
            anchors + arng.normal(0.0, revisit_sigma,
                                  anchors.shape).astype(np.float32),
            0.0, 1.0).astype(np.float32)
        seeds = certified_seeds(probes, anchors,
                                np.asarray(a_d, np.float32))
        sd, sn = eng.query(probes, seed_radius=seeds)
        ud, un = eng.query(probes)
        seeded_bitwise = (np.array_equal(np.asarray(sd), np.asarray(ud))
                         and np.array_equal(np.asarray(sn),
                                            np.asarray(un)))
        cc0 = eng.compile_count
        reps_on, reps_off = [], []
        for trial in range(trials):
            for base, reps in ((base_on, reps_on), (base_off, reps_off)):
                reps.append(_run_loadgen(
                    base, duration_s=duration_s, concurrency=concurrency,
                    batch=batch, seed=seed + trial, workload="uniform",
                    dup_frac=dup_frac, revisit=revisit_sigma))
        compile_flat = eng.compile_count == cc0
        # gate (3): hit-path byte identity over live HTTP
        hp = arng.random((16, 3)).astype(np.float32)
        body = json.dumps({"queries": hp.tolist(),
                           "neighbors": True}).encode()

        def raw_post():
            req = urllib.request.Request(
                base_on + "/knn", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.read()

        hit_bytes_identical = raw_post() == raw_post()
        with urllib.request.urlopen(base_on + "/stats",
                                    timeout=30) as resp:
            qc_stats = json.loads(resp.read()).get("qcache", {})
        oracle = _probe_oracle_exact(base_on, points, k, seed)
    finally:
        srv_on.close()
        srv_off.close()
    med_on = sorted(r["qps"] for r in reps_on)[len(reps_on) // 2]
    med_off = sorted(r["qps"] for r in reps_off)[len(reps_off) // 2]
    ratio = med_on / med_off if med_off else None
    return {
        "kind": "serve_cache_bench", "n_points": n_points, "k": k,
        "duration_s": duration_s, "concurrency": concurrency,
        "batch": batch, "trials": trials, "dup_frac": dup_frac,
        "revisit_sigma": revisit_sigma,
        "qps_cache_on": med_on, "qps_cache_off": med_off,
        "qps_on_trials": [r["qps"] for r in reps_on],
        "qps_off_trials": [r["qps"] for r in reps_off],
        "qps_ratio": round(ratio, 3) if ratio else None,
        "qps_floor": qps_floor,
        "qps_ok": bool(ratio and ratio >= qps_floor),
        "seeded_bitwise": bool(seeded_bitwise),
        "hit_bytes_identical": bool(hit_bytes_identical),
        "compile_flat": bool(compile_flat),
        "qcache": qc_stats,
        "oracle_exact": bool(oracle),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--points", type=int, default=8192)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--depths", default="1,2",
                    help="comma-separated pipeline depths to bench")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds of closed-loop load per depth")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--trials", type=int, default=3,
                    help="interleaved trials per depth; median q/s reported")
    ap.add_argument("--devices", type=int, default=1,
                    help="virtual CPU devices / index shards")
    ap.add_argument("--max-delay-ms", type=float, default=8.0,
                    help="batcher flush deadline (docs/TUNING.md)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="write JSON report here")
    ap.add_argument("--merge-devices", type=int, default=4,
                    help="mesh size for the merge=host-vs-device bench "
                         "(0 skips it)")
    ap.add_argument("--merge-bench", action="store_true",
                    help="internal: run ONLY the merge bench in this "
                         "process (needs its own virtual device count) "
                         "and print its JSON")
    ap.add_argument("--locality-bench", action="store_true",
                    help="also run the query-locality bench (clustered vs "
                         "uniform workloads at query_buckets 1 vs auto) in "
                         "a subprocess and embed locality_compare")
    ap.add_argument("--locality-child", action="store_true",
                    help="internal: run ONLY the locality bench in this "
                         "process (needs its own 1-device fixture) and "
                         "print its JSON")
    ap.add_argument("--multihost-bench", action="store_true",
                    help="also run the multi-host serving bench (2 pod "
                         "processes + front end vs a single-process server "
                         "of the same config) in a subprocess and embed "
                         "multihost_compare")
    ap.add_argument("--multihost-child", action="store_true",
                    help="internal: run ONLY the multi-host bench in this "
                         "process (needs its own 2-device fixture for the "
                         "single-process twin) and print its JSON")
    ap.add_argument("--routing-bench", action="store_true",
                    help="also run the shard-local routing bench (2-host "
                         "pod at --routing bounds vs --routing off on "
                         "clustered + uniform workloads, bitwise-parity "
                         "probe) in a subprocess and embed routing_compare")
    ap.add_argument("--routing-child", action="store_true",
                    help="internal: run ONLY the routing bench in this "
                         "process (spawns its own pod processes) and "
                         "print its JSON")
    ap.add_argument("--chaos-bench", action="store_true",
                    help="also run the chaos bench (kill one routed host "
                         "mid-load via a deterministic fault-injected "
                         "outage, measure availability/degraded-rate, "
                         "recovery time, and post-rejoin bitwise parity) "
                         "in a subprocess and embed chaos_compare")
    ap.add_argument("--chaos-child", action="store_true",
                    help="internal: run ONLY the chaos bench in this "
                         "process (needs its own 2-device fixture) and "
                         "print its JSON")
    ap.add_argument("--replica-bench", action="store_true",
                    help="also run the replica bench (rolling single-host "
                         "kill across an R=2 routed pod with a warm "
                         "standby: zero exact:false, availability >= "
                         "0.999, post-handoff bitwise probe parity) in a "
                         "subprocess and embed replica_compare")
    ap.add_argument("--replica-child", action="store_true",
                    help="internal: run ONLY the replica bench in this "
                         "process (1-device fixture, boots its own pod + "
                         "standby) and print its JSON")
    ap.add_argument("--streaming-bench", action="store_true",
                    help="also run the tiered-slab streaming bench "
                         "(sweep-workload churn at index size 4x the "
                         "device budget, bitwise probe parity vs a "
                         "fully-resident engine + stream-stall-fraction "
                         "ceiling) in a subprocess and embed "
                         "streaming_compare")
    ap.add_argument("--streaming-child", action="store_true",
                    help="internal: run ONLY the streaming bench in this "
                         "process (1-device fixture) and print its JSON")
    ap.add_argument("--recall-bench", action="store_true",
                    help="also run the recall-SLO tier bench (measured "
                         "recall vs requested targets per workload, "
                         "approx-vs-exact q/s on clustered, exact-path "
                         "bitwise parity, response contract) in a "
                         "subprocess and embed recall_compare")
    ap.add_argument("--recall-child", action="store_true",
                    help="internal: run ONLY the recall bench in this "
                         "process (1-device single-thread fixture) and "
                         "print its JSON")
    ap.add_argument("--wire-bench", action="store_true",
                    help="also run the quantized-wire bench (negotiated "
                         "q16 candidate exchange vs f32 with bitwise "
                         "parity on routed/replicated/streaming/mixed "
                         "pods, bytes-per-row + throttled d16 slab "
                         "handoff gates) in a subprocess and embed "
                         "wire_compare")
    ap.add_argument("--wire-child", action="store_true",
                    help="internal: run ONLY the wire bench in this "
                         "process (1-device fixture, boots its own "
                         "in-process pods) and print its JSON")
    ap.add_argument("--tenancy-bench", action="store_true",
                    help="also run the multi-index tenancy bench (N "
                         "zipf-skewed tenants behind one shared device "
                         "byte budget vs N isolated servers at equal "
                         "total memory: aggregate q/s floor, per-tenant "
                         "bitwise parity, flat compile count, cold-tenant "
                         "p99 ceiling) in a subprocess and embed "
                         "tenancy_compare")
    ap.add_argument("--tenancy-child", action="store_true",
                    help="internal: run ONLY the tenancy bench in this "
                         "process (1-device fixture) and print its JSON")
    ap.add_argument("--cache-bench", action="store_true",
                    help="also run the certified query-cache bench "
                         "(revisit-heavy stream at a cache-enabled "
                         "server vs a cache-off twin: q/s floor, "
                         "seeded-vs-unseeded bitwise parity, hit-path "
                         "byte identity, flat compile count) in a "
                         "subprocess and embed cache_compare")
    ap.add_argument("--cache-child", action="store_true",
                    help="internal: run ONLY the cache bench in this "
                         "process (1-device single-thread fixture) and "
                         "print its JSON")
    ap.add_argument("--kernel-bench", action="store_true",
                    help="also run the distance-kernel bench (elementwise "
                         "VPU vs MXU matmul-form at D in {3, 8, 64}) in a "
                         "subprocess and embed kernel_compare")
    ap.add_argument("--kernel-child", action="store_true",
                    help="internal: run ONLY the kernel bench in this "
                         "process (1-device single-thread fixture) and "
                         "print its JSON")
    a = ap.parse_args(argv)

    if a.chaos_child:
        report = run_chaos_bench(
            n_points=a.points, k=a.k, duration_s=a.duration,
            concurrency=a.concurrency, batch=min(a.batch, 8),
            max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("bitwise_parity_after_rejoin")
                     and report.get("availability_ok")) else 1

    if a.replica_child:
        report = run_replica_bench(
            duration_s=a.duration, concurrency=a.concurrency,
            batch=min(a.batch, 8), max_delay_s=a.max_delay_ms / 1e3,
            seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("zero_inexact")
                     and report.get("availability_ok")
                     and report.get("bitwise_parity_after_handoff")) \
            else 1

    if a.streaming_child:
        # the streaming bench pins its OWN fixture shape (16k points, 8
        # slabs, 2-slab device budget = 4x over-budget); only the timing
        # knobs ride through
        report = run_streaming_bench(
            duration_s=a.duration, concurrency=a.concurrency,
            batch=min(a.batch, 16), trials=max(1, a.trials - 1),
            max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("bitwise_parity_vs_resident")
                     and report.get("stall_ok")) else 1

    if a.kernel_child:
        report = run_kernel_bench(n_points=a.points, k=a.k, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if report.get("exact_bitwise") else 1

    if a.cache_child:
        # the cache bench pins its OWN fixture shape (32k points, k=16,
        # one shared warm engine behind a cache-on and a cache-off
        # server — see run_cache_bench: the win lives in hit requests
        # skipping device work entirely, which needs compute-bound
        # batches); only the timing knobs ride through
        report = run_cache_bench(
            duration_s=a.duration, concurrency=a.concurrency,
            batch=min(a.batch, 64), trials=max(2, a.trials),
            max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("qps_ok")
                     and report.get("seeded_bitwise")
                     and report.get("hit_bytes_identical")
                     and report.get("compile_flat")) else 1

    if a.tenancy_child:
        # the tenancy bench pins its OWN fixture shape (3 tenants x 8k
        # points x 6 slabs, 12-slab shared budget vs 4 slabs per
        # isolated twin — see run_tenancy_bench: the shared-pool win
        # lives in the skewed-traffic memory economics, which need the
        # isolated hot twin genuinely over-budget) AND its own client
        # shape (open loop at a fixed offered rate, a worker pool deep
        # enough that multi-second promotion stalls never starve the
        # attempt stream); only duration/trials/seed ride through
        report = run_tenancy_bench(
            duration_s=max(4.0, a.duration), trials=max(2, a.trials),
            seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("parity_all")
                     and report.get("qps_ratio_ok")
                     and report.get("compile_flat")
                     and report.get("cold_p99_ok")) else 1

    if a.wire_child:
        # the wire bench pins its OWN fixture shapes (16k-point 2-slab
        # candidate pods + a 131k-row dense Morton-sorted handoff slab);
        # only the seed rides through — both codecs and all fixtures are
        # deterministic, so the measured ratios reproduce bit-for-bit
        report = run_wire_bench(seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("parity_all") and report.get("bytes_ok")
                     and report.get("handoff_ok")) else 1

    if a.recall_child:
        # the recall bench pins its OWN fixture shape (131k clustered
        # points + 1% background, k=16 — see run_recall_bench: the tier's
        # win lives in the clustered index's certification tail, which
        # the default smoke fixture is too small and too uniform to
        # have); only the timing knobs ride through
        report = run_recall_bench(
            duration_s=a.duration, concurrency=a.concurrency,
            batch=min(a.batch, 64), trials=a.trials, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("recall_targets_ok")
                     and report.get("speedup_ok")
                     and report.get("exact_bitwise")
                     and report.get("contract_ok")) else 1

    if a.routing_child:
        # the routing bench pins its OWN fixture shape (32k points, k=64,
        # 32-row requests — see run_routing_bench: at the default smoke
        # fixture both configs are transport-bound and the ratio measures
        # nothing); only the timing knobs ride through
        report = run_routing_bench(
            duration_s=a.duration, trials=max(1, a.trials - 1),
            max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if (report.get("oracle_exact")
                     and report.get("bitwise_identical_to_routing_off")) \
            else 1

    if a.multihost_child:
        report = run_multihost_bench(
            n_points=a.points, k=a.k, duration_s=a.duration,
            concurrency=a.concurrency, batch=a.batch,
            trials=max(1, a.trials - 1), max_delay_s=a.max_delay_ms / 1e3,
            seed=a.seed)
        print(json.dumps(report, indent=2))
        return 0 if report.get("oracle_exact") else 1

    if a.locality_child:
        report = run_locality_bench(
            n_points=a.points, k=a.k, duration_s=a.duration,
            concurrency=a.concurrency, batch=min(a.batch, 16),
            trials=max(1, a.trials - 1), max_delay_s=a.max_delay_ms / 1e3,
            seed=a.seed)
        print(json.dumps(report, indent=2))
        ok = all(report["per_config"][c][w]["oracle_exact"]
                 for c in report["per_config"]
                 for w in ("clustered", "uniform"))
        return 0 if ok else 1

    if a.merge_bench:
        report = run_merge_bench(
            n_points=a.points, k=a.k, devices=a.merge_devices,
            duration_s=a.duration, concurrency=a.concurrency,
            batch=a.batch, trials=max(1, a.trials - 1),
            max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
        print(json.dumps(report, indent=2))
        ok = all(r["oracle_exact"] for r in report["per_merge"].values())
        return 0 if ok else 1

    report = run_smoke(n_points=a.points, k=a.k,
                       depths=tuple(int(d) for d in a.depths.split(",")),
                       duration_s=a.duration, concurrency=a.concurrency,
                       batch=a.batch, trials=a.trials, devices=a.devices,
                       max_delay_s=a.max_delay_ms / 1e3, seed=a.seed)
    ok = all(r.get("oracle_exact") for r in report["per_depth"].values())
    # child benches need their own virtual device counts and the count is
    # frozen at this process's first jax import — strip this process's
    # fixture flags so each child's _setup_cpu_fixture can pin its own
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
        and "xla_cpu_multi_thread_eigen" not in f).strip()
    if a.merge_devices > 0:
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--merge-bench",
                 "--points", str(a.points), "--k", str(a.k),
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--merge-devices", str(a.merge_devices),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=120 + a.duration * (a.trials + 2) * 3)
            mc = json.loads(child.stdout)
            report["merge_compare"] = mc
            # the exit contract gates on oracle-exactness ONLY: a measured
            # exactness failure fails the run, bench-infrastructure
            # hiccups below never do
            ok = ok and all(v.get("oracle_exact")
                            for v in mc.get("per_merge", {}).values())
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            # degrade, never discard the depth results already measured —
            # and never flip the exit code for a bench that could not run
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:  # timeout: child never bound; the exception holds output
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["merge_compare"] = {"error": f"{str(e)[:300]} :: {detail}"}
    if a.locality_bench:
        # same subprocess discipline as the merge bench: the locality
        # child pins a 1-device single-thread-Eigen fixture of its own.
        # Oracle-exactness is the only exit-code gate; the tile/q-s ratios
        # are the trajectory numbers the BENCH series tracks.
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--locality-child",
                 "--points", str(a.points), "--k", str(a.k),
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=180 + a.duration * (a.trials + 2) * 6)
            lc = json.loads(child.stdout)
            report["locality_compare"] = lc
            ok = ok and all(
                lc["per_config"][c][w].get("oracle_exact")
                for c in lc.get("per_config", {})
                for w in ("clustered", "uniform"))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["locality_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.kernel_bench:
        # same subprocess discipline: the kernel child pins the 1-device
        # single-thread-Eigen fixture. The MXU-vs-VPU bitwise-exactness
        # check is the only exit-code gate; speed ratios are the BENCH
        # series' trajectory numbers (speedup_d3 ~1.0 by construction —
        # below mxu_min_dim the bf16 request scores exactly on the VPU —
        # and speedup_d64 is the matmul-form headline)
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--kernel-child",
                 "--points", str(a.points), "--k", str(a.k),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=600)
            kc = json.loads(child.stdout)
            report["kernel_compare"] = kc
            ok = ok and bool(kc.get("exact_bitwise"))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["kernel_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.streaming_bench:
        # same subprocess discipline: the streaming child pins the
        # 1-device single-thread fixture. BOTH streaming gates ride the
        # exit code: bitwise probe parity vs a fully-resident engine
        # (cold AND after the sweep churn) and the stream-stall-fraction
        # ceiling — the prefetcher must hide promotions under compute;
        # q/s and the churn counters are the trajectory numbers
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--streaming-child",
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=300 + a.duration * (a.trials + 2) * 6)
            sc = json.loads(child.stdout)
            report["streaming_compare"] = sc
            if "error" not in sc:  # infra hiccups degrade, never gate
                ok = (ok and bool(sc.get("bitwise_parity_vs_resident"))
                      and bool(sc.get("stall_ok")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["streaming_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.tenancy_bench:
        # same subprocess discipline: the tenancy child pins the
        # 1-device single-thread fixture and boots its own shared +
        # isolated servers. ALL FOUR tenancy gates ride the exit code
        # (the multi-index issue's acceptance bar): shared aggregate
        # q/s >= the floor multiple of the equal-memory isolated total,
        # per-tenant bitwise probe parity vs the single-tenant twins
        # (cold AND post-churn), warmup compile count flat vs one
        # tenant, and the cold tenant's p99 under its ceiling
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--tenancy-child",
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=600 + a.duration * (a.trials + 2) * 8)
            tc = json.loads(child.stdout)
            report["tenancy_compare"] = tc
            if "error" not in tc:  # infra hiccups degrade, never gate
                ok = (ok and bool(tc.get("parity_all"))
                      and bool(tc.get("qps_ratio_ok"))
                      and bool(tc.get("compile_flat"))
                      and bool(tc.get("cold_p99_ok")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["tenancy_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.cache_bench:
        # same subprocess discipline: the cache child pins the 1-device
        # single-thread fixture. ALL FOUR cache gates ride the exit code
        # (the query-cache issue's acceptance bar): revisit-workload q/s
        # >= the floor multiple of the cache-off twin, seeded-vs-unseeded
        # bitwise parity at the engine tier, hit-path responses
        # byte-identical over live HTTP, and a flat compile count across
        # the seeded traffic (the per-query radius is a dynamic operand,
        # never a new program)
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--cache-child",
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=600 + a.duration * (a.trials + 2) * 8)
            cb = json.loads(child.stdout)
            report["cache_compare"] = cb
            if "error" not in cb:  # infra hiccups degrade, never gate
                ok = (ok and bool(cb.get("qps_ok"))
                      and bool(cb.get("seeded_bitwise"))
                      and bool(cb.get("hit_bytes_identical"))
                      and bool(cb.get("compile_flat")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["cache_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.recall_bench:
        # same subprocess discipline: the recall child pins the 1-device
        # single-thread fixture. ALL FOUR recall gates ride the exit
        # code (the recall-SLO issue's acceptance bar): measured recall
        # >= the requested target on every calibrated workload shape,
        # approx-tier q/s >= the floor multiple of exact on clustered
        # (engine tier — deterministic; the HTTP split is trajectory
        # data), the no-recall default path bitwise-identical through
        # the live server, and the response/stats/metrics contract
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--recall-child",
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=900 + a.duration * 30)
            rl = json.loads(child.stdout)
            report["recall_compare"] = rl
            if "error" not in rl:  # infra hiccups degrade, never gate
                ok = (ok and bool(rl.get("recall_targets_ok"))
                      and bool(rl.get("speedup_ok"))
                      and bool(rl.get("exact_bitwise"))
                      and bool(rl.get("contract_ok")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["recall_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.multihost_bench:
        # same subprocess discipline: the multi-host child pins a 2-device
        # fixture for the single-process twin and spawns the pod processes
        # itself. The deterministic fetch-per-pod ratio is the headline;
        # oracle-exactness (through the front-end assembly) gates the exit.
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--multihost-child",
                 "--points", str(a.points), "--k", str(a.k),
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=600 + a.duration * (a.trials + 2) * 6)
            mh = json.loads(child.stdout)
            report["multihost_compare"] = mh
            if "error" not in mh:  # infra hiccups degrade, never gate
                # exactness AND the q/s regression floor both gate: a pod
                # serving below half a single host means the fan-out broke
                ok = (ok and bool(mh.get("oracle_exact"))
                      and bool(mh.get("qps_ratio_ok", True)))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["multihost_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.chaos_bench:
        # same subprocess discipline: the chaos child pins a 2-device
        # fixture and boots its own in-process routed pod. Availability
        # under single-host loss AND post-rejoin bitwise parity both gate
        # the exit code (the acceptance bar of the fault-tolerant serving
        # issue); recovery_s and the per-phase availability/degraded-rate
        # numbers are the BENCH series' trajectory data
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--chaos-child",
                 "--points", str(a.points), "--k", str(a.k),
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=300 + a.duration * 10)
            cc = json.loads(child.stdout)
            report["chaos_compare"] = cc
            if "error" not in cc:  # infra hiccups degrade, never gate
                ok = (ok and bool(cc.get("bitwise_parity_after_rejoin"))
                      and bool(cc.get("availability_ok")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["chaos_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.replica_bench:
        # same subprocess discipline: the replica child boots its own
        # R=2 routed pod + warm standby. ALL THREE replica gates ride the
        # exit code (the issue's acceptance bar): zero exact:false
        # through the rolling kill, availability >= 0.999, and the
        # post-handoff probe bitwise-equal to the pre-kill answers; the
        # R2-vs-R1 q/s ratio is the trajectory number
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--replica-child",
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=600 + a.duration * 10)
            rb = json.loads(child.stdout)
            report["replica_compare"] = rb
            if "error" not in rb:  # infra hiccups degrade, never gate
                ok = (ok and bool(rb.get("zero_inexact"))
                      and bool(rb.get("availability_ok"))
                      and bool(rb.get("bitwise_parity_after_handoff")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["replica_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.routing_bench:
        # same subprocess discipline: the routing child spawns its own pod
        # processes (replicate-everything twin AND routed twin) and probes
        # them with one fixed batch. Bitwise parity (incl. tie ids) and
        # oracle-exactness gate the exit; the clustered/uniform q/s ratios
        # are the headline trajectory numbers (clustered_target 1.5 x,
        # uniform_floor 0.9 x recorded alongside)
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--routing-child",
                 "--points", str(a.points), "--k", str(a.k),
                 "--duration", str(a.duration),
                 "--concurrency", str(a.concurrency),
                 "--batch", str(a.batch), "--trials", str(a.trials),
                 "--max-delay-ms", str(a.max_delay_ms),
                 "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=900 + a.duration * (a.trials + 2) * 10)
            rc_ = json.loads(child.stdout)
            report["routing_compare"] = rc_
            if "error" not in rc_:  # infra hiccups degrade, never gate
                ok = (ok and bool(rc_.get("oracle_exact"))
                      and bool(rc_.get("bitwise_identical_to_routing_off")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["routing_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    if a.wire_bench:
        # same subprocess discipline: the wire child boots its own pods.
        # ALL THREE wire gates ride the exit code (the issue's acceptance
        # bar): bitwise parity on every pod shape, candidate
        # bytes-per-row <= 0.45x f32, throttled d16 handoff <= 0.6x f32
        # wall-clock at equal rows
        try:
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--wire-child", "--seed", str(a.seed)],
                capture_output=True, text=True, env=env,
                timeout=900)
            wb = json.loads(child.stdout)
            report["wire_compare"] = wb
            if "error" not in wb:  # infra hiccups degrade, never gate
                ok = (ok and bool(wb.get("parity_all"))
                      and bool(wb.get("bytes_ok"))
                      and bool(wb.get("handoff_ok")))
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as e:
            if isinstance(e, json.JSONDecodeError):
                detail = (child.stderr or child.stdout or "")[-1500:]
            else:
                raw = e.stderr or e.stdout or b""
                detail = (raw.decode(errors="replace")
                          if isinstance(raw, bytes) else str(raw))[-1500:]
            report["wire_compare"] = {
                "error": f"{str(e)[:300]} :: {detail}"}
    text = json.dumps(report, indent=2)
    print(text)
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
