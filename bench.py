"""Benchmark — BASELINE config #1: unordered, single device, 1M float3, k=8.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "queries/s", "vs_baseline": N}

The reference publishes no numbers anywhere (BASELINE.md: no timers, no
benchmarks dir), so ``vs_baseline`` is measured against a DOCUMENTED ESTIMATE
of the reference's throughput on its era hardware: ~2e7 exact-kNN
queries/sec for 1M points k=8 on a V100-class GPU (order-of-magnitude from
the cudaKDTree papers' reported traversal rates, arXiv:2210.12859 /
2211.00120). vs_baseline = ours / that estimate.

Robustness: the TPU is reached through a single-client tunnel whose FIRST
contact alone can take 60-240+ s, and which can be down for whole windows.
So: ONE child process does the probe AND the measurement (first contact is
paid once), walking a size ladder from the full 1M config downward inside
the process; the parent reads its incremental stage lines, so even a
timeout kill preserves partial evidence. If the TPU attempt fails, it is
retried once (tunnels recover), and only then does a clearly-labeled
CPU-fallback measurement run. Probe outcome/duration is recorded in the
output JSON either way.

Env knobs: BENCH_N (ladder start), BENCH_K, BENCH_ENGINE, BENCH_REPS,
BENCH_BUDGET_S (total wall budget, default 900), BENCH_BUCKET_SIZE /
BENCH_POINT_GROUP (tile geometry, defaults from KnnConfig — tpu_tune.py
measures which geometry wins on chip).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_ESTIMATE_QPS = 2.0e7  # documented estimate, see module docstring
N_POINTS = int(os.environ.get("BENCH_N", 1_000_000))
K = int(os.environ.get("BENCH_K", 8))
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 900))
CPU_RESERVE_S = 150.0  # kept back for the labeled cpu-fallback measurement

# Persistent XLA compile cache, shared by every child (and by tune/probe
# runs in the same session): a timed-out attempt that got past
# warmup_done retries for the cost of a cache load, and later tune cells
# at the same geometry skip compile entirely. Rationale + keying in
# utils/compile_cache.py; set here (parent) so children inherit the env.
# Cross-process cache hits verified on the axon TPU backend itself
# (jit matmul: 1.97s cold -> 0.27s in a fresh process; entries written
# to .jax_cache). Whether Mosaic AOT kernels also hit it is confirmed
# per-session from warmup_done deltas in the probe_log.
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
from mpi_cuda_largescaleknn_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_cache)
enable_persistent_cache()

_CHILD = r"""
import json, os, sys, time
import numpy as np

k = int(sys.argv[1]); engine = sys.argv[2]
ladder = [int(x) for x in sys.argv[3].split(",") if x]
expect = sys.argv[4] if len(sys.argv) > 4 else "any"

t0 = time.perf_counter()
import jax
devs = jax.devices()
contact_s = time.perf_counter() - t0
platform = devs[0].platform
print("CONTACT " + json.dumps(
    {"platform": platform, "seconds": round(contact_s, 1)}), flush=True)
if expect == "tpu" and platform == "cpu":
    # asked for a TPU but jax fell back to host CPU: bail immediately so
    # the parent runs its (size-capped, labeled) cpu-fallback instead of
    # burning the whole attempt budget on a 1M-point CPU run
    sys.exit(3)

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

mesh = get_mesh(1)
rng = np.random.default_rng(7)
reps = max(1, int(os.environ.get("BENCH_REPS", 2)))
# parse geometry knobs ONCE, before the ladder: a malformed value must
# fail fast, not burn the whole bench budget as per-rung engine failures
cfg_kw = {}
if os.environ.get("BENCH_BUCKET_SIZE"):
    cfg_kw["bucket_size"] = int(os.environ["BENCH_BUCKET_SIZE"])
if os.environ.get("BENCH_POINT_GROUP"):
    cfg_kw["point_group"] = int(os.environ["BENCH_POINT_GROUP"])
tuned_kw = {}
if not cfg_kw and platform != "cpu":
    # no explicit geometry: adopt the best ON-CHIP cell from a committed
    # tune sweep, if one exists (tools/tpu_tune.py) — so the end-of-round
    # bench automatically benefits from the sweep without manual env
    # plumbing. Applied ONLY when the Pallas kernel runs (the sweep's
    # winners are kernel-specific; an explicit BENCH_ENGINE=tiled run or
    # the in-attempt twin fallback keeps its own engine defaults), and
    # CPU rows never steer the TPU config.
    try:
        with open(os.environ.get("BENCH_TUNE_REPORT",
                                 "tpu_tune_report.json")) as f:
            _cells = [r for r in json.load(f)
                      if r.get("engine") == "pallas_tiled"
                      and r.get("k") == k and "qps" in r
                      and r.get("platform") not in (None, "cpu")]
        if _cells:
            _best = max(_cells, key=lambda r: (r.get("n", 0), r["qps"]))
            tuned_kw["bucket_size"] = _best["bucket_size"]
            # always explicit: sweep cells without the key ran G1, and
            # leaving it unset would let the config's 0=auto default
            # substitute a different (unswept) group for the adopted cell
            tuned_kw["point_group"] = _best.get("point_group", 1)
            _lanes = (_best.get("env") or {}).get("LSK_CHUNK_LANES")
            if _lanes and not os.environ.get("LSK_CHUNK_LANES"):
                os.environ["LSK_CHUNK_LANES"] = str(_lanes)
            print("STAGE " + json.dumps({"tuned_geometry": {
                **{kk: _best.get(kk) for kk in
                   ("bucket_size", "point_group", "n", "qps")},
                "lanes": _lanes}}), flush=True)
    except (OSError, ValueError):
        pass  # no report / unreadable: engine defaults apply
KnnConfig(k=k, **cfg_kw).validate()
# auto resolves to the Pallas kernel on TPU; if Mosaic rejects it at this
# shape, fall back to the XLA twin WITHIN the TPU attempt (a kernel bug
# must not demote the whole measurement to the CPU ladder)
from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_engine
candidates = [resolve_engine(engine)]
if engine == "auto" and candidates[0] != "tiled":
    candidates.append("tiled")
done = False
for n in ladder:
  if done:
      break
  for eng_i, eng in enumerate(candidates):
    try:
        pts = rng.random((n, 3)).astype(np.float32)
        geo_kw = cfg_kw or (tuned_kw if eng == "pallas_tiled" else {})
        model = UnorderedKNN(KnnConfig(k=k, engine=eng, **geo_kw), mesh=mesh)
        print("STAGE " + json.dumps({"warmup_start": {"n": n, "engine": eng}}),
              flush=True)
        t0 = time.perf_counter()
        out = model.run(pts)  # warm the compile cache at full shape
        compile_s = time.perf_counter() - t0
        print("STAGE " + json.dumps(
            {"warmup_done": {"n": n, "engine": eng,
                             "seconds": round(compile_s, 1)}}),
            flush=True)
        best, ring_s = float("inf"), None
        for _ in range(reps):
            model.timers.phases.clear()
            t0 = time.perf_counter()
            out = model.run(pts)
            dt = time.perf_counter() - t0
            if dt < best:
                best = dt
                ring_s = model.timers.report().get("ring", {}).get("seconds")
        assert out.shape == (n,) and np.all(np.isfinite(out))
        # the headline number must be a CORRECT result: recompute 64
        # sampled outputs exactly (host numpy); a wrong-answer engine
        # falls back instead of publishing garbage fast
        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        verify_sample(pts, out, k, 64)
        from mpi_cuda_largescaleknn_tpu.obs.cost import cost_report
        kind = getattr(devs[0], "device_kind", None)
        cr = cost_report((model.last_stats or {}).get("pair_evals", 0),
                         ring_s or best, platform, kind)
        print("RESULT " + json.dumps({
            "n": n, "seconds": best, "compile_s": round(compile_s, 2),
            "device_seconds": ring_s, "engine_used": eng,
            "geometry": geo_kw or None,
            "platform": platform, "contact_s": round(contact_s, 1), **cr}),
            flush=True)
        done = True
        break
    except AssertionError as e:
        # non-finite/bad-shape/selfcheck-mismatch output: a correctness
        # bug — never shrink n for it, but do try the fallback engine
        if eng_i + 1 < len(candidates):
            print("FAILENGINE " + json.dumps(
                {"n": n, "engine": eng,
                 "error": f"AssertionError: {e}"[:400]}), flush=True)
            continue
        raise
    except Exception as e:  # resource exhaustion at this rung -> size down
        low = f"{type(e).__name__}: {e}".lower()
        is_resource = isinstance(e, MemoryError) or any(
            t in low for t in ("resource_exhausted", "out of memory", "oom",
                               "memoryerror", "failed to allocate",
                               "allocation"))
        tag = "FAILSIZE" if is_resource else "FAILENGINE"
        print(tag + " " + json.dumps(
            {"n": n, "engine": eng,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
        # a kernel-local resource failure (e.g. Mosaic VMEM exhaustion) must
        # still try the fallback engine at the SAME n — its memory profile
        # is unrelated; only when every engine failed here do we size down
        if eng_i + 1 < len(candidates):
            continue  # same n, fallback engine
        if is_resource:
            break  # all engines resource-failed: next smaller n
        raise  # a real bug with no fallback left must fail the bench
"""


def _parse_lines(text: str) -> dict:
    got = {"contact": None, "result": None, "failsizes": [],
           "failengines": [], "stages": []}
    for line in (text or "").splitlines():
        if line.startswith("CONTACT "):
            got["contact"] = json.loads(line[len("CONTACT "):])
        elif line.startswith("STAGE "):
            got["stages"].append(json.loads(line[len("STAGE "):]))
        elif line.startswith("RESULT "):
            got["result"] = json.loads(line[len("RESULT "):])
        elif line.startswith("FAILSIZE "):
            got["failsizes"].append(json.loads(line[len("FAILSIZE "):]))
        elif line.startswith("FAILENGINE "):
            got["failengines"].append(json.loads(line[len("FAILENGINE "):]))
    return got


def _log_probe(probe_log: list, attempt, got: dict) -> None:
    """One probe_log entry per child attempt — single point of truth for
    which child fields are preserved (stages attribute a timeout to its
    phase)."""
    probe_log.append({"attempt": attempt, "contact": got["contact"],
                      "rc": got["rc"], "wall_s": got["wall_s"],
                      "failsizes": got["failsizes"],
                      "failengines": got["failengines"],
                      "stages": got["stages"]})


def _run_child(ladder, engine: str, env: dict, timeout_s: float,
               expect: str = "any") -> dict:
    """One probe+measure child; returns parsed stage lines + outcome."""
    argv = [sys.executable, "-u", "-c", _CHILD, str(K), engine,
            ",".join(str(n) for n in ladder), expect]
    t0 = time.time()
    try:
        r = subprocess.run(argv, timeout=timeout_s, capture_output=True,
                           text=True, env=env)
        out, err, rc = r.stdout, r.stderr, r.returncode
    except subprocess.TimeoutExpired as e:
        def _s(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")
        out, err, rc = _s(e.stdout), _s(e.stderr), "timeout"
    got = _parse_lines(out)
    got["rc"] = rc
    got["wall_s"] = round(time.time() - t0, 1)
    if rc not in (0,) and got["result"] is None:
        sys.stderr.write((err or "")[-2000:] + "\n")
    return got


def main() -> int:
    t_start = time.time()
    engine = os.environ.get("BENCH_ENGINE", "auto")
    # children resolve the committed tune report relative to bench.py, not
    # their cwd (the driver may invoke bench from anywhere)
    os.environ.setdefault(
        "BENCH_TUNE_REPORT", os.path.join(_HERE, "tpu_tune_report.json"))
    ladder = [n for n in (N_POINTS, N_POINTS // 4, N_POINTS // 20)
              if n >= 1000] or [1000]
    ladder = list(dict.fromkeys(ladder))

    probe_log = []
    result = None

    # --- TPU attempts: probe+measure in one process, one retry -------------
    want_tpu = os.environ.get("JAX_PLATFORMS", "") not in ("cpu",)
    ladder_now = list(ladder)
    for attempt in range(2):
        if not want_tpu or not ladder_now:
            break
        remaining = BUDGET_S - (time.time() - t_start) - CPU_RESERVE_S
        if remaining < 240:  # not enough left for first contact + a run
            break
        # the first attempt may not eat the whole TPU budget: a hang must
        # leave enough for the retry (which drops the hung rung) to run
        cap = remaining if attempt == 1 else max(240.0, remaining * 0.55)
        got = _run_child(ladder_now, engine, dict(os.environ), cap,
                         expect="tpu")
        _log_probe(probe_log, attempt + 1, got)
        if got["result"] is not None:
            result = got["result"]
            break
        if got["rc"] == 3:  # contacted, but only CPU visible: no point retrying
            break
        if got["rc"] == "timeout":
            # the retry must not re-run the rung that hung: the stage lines
            # name the last rung started; drop it and everything larger.
            # No stage lines = the hang was first contact, not a rung —
            # keep the ladder and retry as-is (tunnels recover).
            # Exception: if the hung (n, engine) pair had REACHED
            # warmup_done, its compile is now in the persistent cache —
            # the retry re-runs the same rung and pays only a cache load
            # + the timed reps. Keyed on engine too: a cached pallas
            # compile must not mask a timeout inside the fallback
            # engine's still-uncached compile at the same n.
            started = [(s["warmup_start"]["n"],
                        s["warmup_start"].get("engine"))
                       for s in got["stages"] if "warmup_start" in s]
            compiled = {(s["warmup_done"].get("n"),
                         s["warmup_done"].get("engine"))
                        for s in got["stages"] if "warmup_done" in s}
            if started and started[-1] not in compiled:
                ladder_now = [n for n in ladder_now if n < started[-1][0]]

    # --- CPU fallback, clearly labeled -------------------------------------
    if result is None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)  # don't dial the tunnel
        remaining = max(60.0, BUDGET_S - (time.time() - t_start) - 10)
        # budget-aware size cap: the FULL 1M config runs in ~100s on the
        # CPU twin at the tuned geometry (compile 48s + 48s/run, round-5
        # measurement), so a healthy remaining budget measures the real
        # headline config instead of a 50K stand-in (the BENCH_r04
        # misjudgment); a thin budget still guarantees a number
        cpu_cap = (1_000_000 if remaining > 360
                   else 250_000 if remaining > 180 else 50_000)
        cpu_ladder = sorted({min(n, cpu_cap) for n in ladder}, reverse=True)
        # hold back ~100s whenever a bigger-than-50K rung is attempted:
        # the ladder only downshifts on RESOURCE errors, so if the big
        # rung times out on a slow host, a funded small retry still
        # produces a labeled number WITHIN the stated budget
        big = cpu_ladder[0] > 50_000
        cap_s = max(60.0, remaining - 100) if big else remaining
        got = _run_child(cpu_ladder, engine, env, cap_s)
        _log_probe(probe_log, "cpu-fallback", got)
        result = got["result"]
        if result is None and big:
            retry_s = BUDGET_S - (time.time() - t_start) - 5
            if retry_s >= 45:
                # never larger than what was asked for (BENCH_N can be
                # below 50K), never beyond the budget
                small = min(50_000, cpu_ladder[0])
                got = _run_child([small], engine, env, retry_s)
                _log_probe(probe_log, "cpu-fallback-small", got)
                result = got["result"]

    if result is None:
        print(json.dumps({
            "metric": f"knn_queries_per_sec_unordered_k{K}_1dev",
            "value": 0.0, "unit": "queries/s", "vs_baseline": 0.0,
            "platform": "none", "engine": engine, "probes": probe_log,
            "error": "no measurement completed within budget"}))
        return 0

    platform = result.get("platform", "unknown")
    label = platform if platform != "cpu" else "cpu-fallback"
    n_done, secs = result["n"], result["seconds"]
    qps = n_done / secs

    # A CPU fallback is NOT the project's best number — when this run
    # could not reach the chip, point at the committed on-chip
    # measurement (clearly labeled as such, value untouched) so a cold
    # reader of this JSON doesn't misjudge the repo by a tunnel outage
    # (the BENCH_r04 failure mode).
    best_tpu = None
    if label != "tpu":
        try:
            with open(os.path.join(
                    _HERE, "BENCH_pallas_batched_1m.json")) as f:
                _prior = json.load(f)
            # only cite a measurement of the SAME config this run was
            # asked for — a 1M/k=8 chip number next to a k=100 or 50K
            # fallback row would invite apples-to-oranges comparison
            _want = f"knn_queries_per_sec_unordered_{N_POINTS}pts_k{K}_1dev"
            if _prior.get("platform") == "tpu" and \
                    _prior.get("metric") == _want:
                attempted = any(p.get("attempt") in (1, 2)
                                for p in probe_log)
                best_tpu = {
                    "note": ("the chip attempt failed this run"
                             if attempted else
                             "this run did not attempt the chip") +
                            "; best committed on-chip measurement of "
                            "the REQUESTED config (self-checked) follows",
                    "metric": _prior.get("metric"),
                    "value": _prior.get("value"),
                    "vs_baseline": _prior.get("vs_baseline"),
                    "engine": _prior.get("engine"),
                    "source": "BENCH_pallas_batched_1m.json"}
        except (OSError, ValueError):
            pass

    print(json.dumps({
        "metric": f"knn_queries_per_sec_unordered_{n_done}pts_k{K}_1dev",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / REFERENCE_ESTIMATE_QPS, 4),
        "platform": label,
        "engine": result.get("engine_used", engine),
        "seconds": round(secs, 3),
        "geometry": result.get("geometry"),
        "compile_s": result.get("compile_s"),
        "device_seconds": result.get("device_seconds"),
        "pair_evals": result.get("pair_evals"),
        "pair_evals_per_sec": result.get("pair_evals_per_sec"),
        "mfu_estimate": result.get("mfu_estimate"),
        "assumed_peak_flops": result.get("assumed_peak_flops"),
        "first_contact_s": result.get("contact_s"),
        **({"best_committed_tpu": best_tpu} if best_tpu else {}),
        "probes": probe_log,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
