"""Benchmark — BASELINE config #1: unordered, single device, 1M float3, k=8.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "queries/s", "vs_baseline": N}

The reference publishes no numbers anywhere (BASELINE.md: no timers, no
benchmarks dir), so ``vs_baseline`` is measured against a DOCUMENTED ESTIMATE
of the reference's throughput on its era hardware: ~2e7 exact-kNN
queries/sec for 1M points k=8 on a V100-class GPU (order-of-magnitude from
the cudaKDTree papers' reported traversal rates, arXiv:2210.12859 /
2211.00120). vs_baseline = ours / that estimate.

Robustness: the TPU is reached through a tunnel that can be unavailable; the
probe runs in a subprocess with a timeout and the bench falls back to CPU
(reported in the JSON) rather than hanging the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_ESTIMATE_QPS = 2.0e7  # documented estimate, see module docstring
N_POINTS = int(os.environ.get("BENCH_N", 1_000_000))
K = int(os.environ.get("BENCH_K", 8))


def _tpu_available(timeout_s: float = 60.0) -> bool:
    probe = ("import jax; d=jax.devices(); "
             "import sys; sys.exit(0 if d and d[0].platform != 'cpu' else 1)")
    try:
        return subprocess.run([sys.executable, "-c", probe],
                              timeout=timeout_s, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    if not _tpu_available():
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        platform = "cpu-fallback"
    else:
        platform = "tpu"

    import numpy as np

    from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
    from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    n = N_POINTS if platform == "tpu" else min(N_POINTS, 20_000)
    rng = np.random.default_rng(7)
    pts = rng.random((n, 3)).astype(np.float32)

    engine = os.environ.get("BENCH_ENGINE", "auto")
    cfg = KnnConfig(k=K, engine=engine)
    model = UnorderedKNN(cfg, mesh=get_mesh(1))

    model.run(pts)  # warm the compile cache at full shape
    best = float("inf")
    for _ in range(max(1, int(os.environ.get("BENCH_REPS", 2)))):
        t0 = time.perf_counter()
        out = model.run(pts)
        best = min(best, time.perf_counter() - t0)
    assert out.shape == (n,) and np.all(np.isfinite(out))

    qps = n / best
    print(json.dumps({
        "metric": f"knn_queries_per_sec_unordered_{n}pts_k{K}_1dev",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / REFERENCE_ESTIMATE_QPS, 4),
        "platform": platform,
        "engine": engine,
        "seconds": round(best, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
