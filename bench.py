"""Benchmark — BASELINE config #1: unordered, single device, 1M float3, k=8.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "queries/s", "vs_baseline": N}

The reference publishes no numbers anywhere (BASELINE.md: no timers, no
benchmarks dir), so ``vs_baseline`` is measured against a DOCUMENTED ESTIMATE
of the reference's throughput on its era hardware: ~2e7 exact-kNN
queries/sec for 1M points k=8 on a V100-class GPU (order-of-magnitude from
the cudaKDTree papers' reported traversal rates, arXiv:2210.12859 /
2211.00120). vs_baseline = ours / that estimate.

Robustness: the TPU is reached through a single-client tunnel that can be
down or wedged (the relay dies when its host side closes). Every measurement
therefore runs in its OWN subprocess with a hard timeout, walking a size
ladder from the full 1M config downward; the largest size that completes is
reported. If no TPU run completes, a CPU-fallback measurement at reduced N is
reported (and labeled) rather than hanging the driver.

Env knobs: BENCH_N (ladder start), BENCH_K, BENCH_ENGINE, BENCH_REPS,
BENCH_BUDGET_S (total wall budget, default 540).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_ESTIMATE_QPS = 2.0e7  # documented estimate, see module docstring
N_POINTS = int(os.environ.get("BENCH_N", 1_000_000))
K = int(os.environ.get("BENCH_K", 8))
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 540))

_CHILD = r"""
import json, os, sys, time
import numpy as np

n = int(sys.argv[1]); k = int(sys.argv[2]); engine = sys.argv[3]

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

rng = np.random.default_rng(7)
pts = rng.random((n, 3)).astype(np.float32)
model = UnorderedKNN(KnnConfig(k=k, engine=engine), mesh=get_mesh(1))
model.run(pts)  # warm the compile cache at full shape
best = float("inf")
for _ in range(max(1, int(os.environ.get("BENCH_REPS", 2)))):
    t0 = time.perf_counter()
    out = model.run(pts)
    best = min(best, time.perf_counter() - t0)
assert out.shape == (n,) and np.all(np.isfinite(out))
print("RESULT " + json.dumps({"n": n, "seconds": best}), flush=True)
"""


def _tpu_available(timeout_s: float = 75.0) -> bool:
    probe = ("import jax; d=jax.devices(); "
             "import sys; sys.exit(0 if d and d[0].platform != 'cpu' else 1)")
    try:
        return subprocess.run([sys.executable, "-c", probe],
                              timeout=timeout_s, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_child(n: int, engine: str, env: dict, timeout_s: float):
    """One measurement in its own subprocess; returns seconds or None."""
    try:
        r = subprocess.run([sys.executable, "-c", _CHILD, str(n), str(K), engine],
                           timeout=timeout_s, capture_output=True, text=True,
                           env=env)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-2000:] + "\n")
        return None
    for line in r.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])["seconds"]
    return None


def main() -> int:
    t_start = time.time()
    engine = os.environ.get("BENCH_ENGINE", "auto")
    tpu = _tpu_available()
    env = dict(os.environ)
    if not tpu:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    platform = "tpu" if tpu else "cpu-fallback"

    ladder = [n for n in (N_POINTS, N_POINTS // 4, N_POINTS // 20)
              if n >= 1000] or [1000]
    if not tpu:
        ladder = [min(n, 50_000) for n in ladder[-2:]]
    ladder = list(dict.fromkeys(ladder))  # dedupe, keep order

    n_done, secs = None, None
    for i, n in enumerate(ladder):
        remaining = BUDGET_S - (time.time() - t_start) - 15
        if remaining < 45:
            break
        got = _run_child(n, engine, env,
                         remaining if i == len(ladder) - 1
                         else min(remaining, max(120, remaining / 2)))
        if got is not None:
            n_done, secs = n, got
            break

    if n_done is None:
        print(json.dumps({
            "metric": f"knn_queries_per_sec_unordered_k{K}_1dev",
            "value": 0.0, "unit": "queries/s", "vs_baseline": 0.0,
            "platform": platform, "engine": engine,
            "error": "no measurement completed within budget"}))
        return 0

    qps = n_done / secs
    print(json.dumps({
        "metric": f"knn_queries_per_sec_unordered_{n_done}pts_k{K}_1dev",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / REFERENCE_ESTIMATE_QPS, 4),
        "platform": platform,
        "engine": engine,
        "seconds": round(secs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
