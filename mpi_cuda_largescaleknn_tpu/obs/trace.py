"""jax.profiler integration (opt-in, see KnnConfig.profile_dir)."""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def profile_trace(profile_dir: str | None):
    """Wrap a region in a jax.profiler trace when a directory is given."""
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield
