"""Post-run sampled verification.

The reference's only verification artifact is a pair of DISABLED probe
blocks (`#if 0`) that printf every 16·1024-th result for manual diffing
(unorderedDataVariant.cu:215-227, prePartitionedDataVariant.cu:366-378, with
a hardcoded 12-rank debug constant). This module is that idea made real: an
always-available ``--selfcheck N`` that recomputes N sampled points' k-th-NN
distances exactly (streamed numpy brute force, O(N * n) with bounded memory)
and fails loudly on mismatch — machine-checked instead of eyeballed.
"""

from __future__ import annotations

import numpy as np


def kth_distance_exact(points: np.ndarray, query_idx: np.ndarray, k: int,
                       max_radius: float = np.inf,
                       budget_elems: int = 64_000_000) -> np.ndarray:
    """Exact k-th-NN distance for ``points[query_idx]`` against ALL points.

    Point blocks are sized so the distance slab stays within
    ``budget_elems`` f32 elements regardless of the sample count (peak
    memory a few hundred MB), and per-block selection uses ``np.partition``
    (linear) rather than a full sort."""
    q = points[query_idx].astype(np.float32)
    nq = max(1, len(q))
    block = max(1024, budget_elems // nq)
    # running k-smallest per sampled query (unsorted; only the max matters)
    best = np.full((nq, k), np.float32(max_radius) ** 2, np.float32)
    for lo in range(0, len(points), block):
        p = points[lo:lo + block].astype(np.float32)
        dx = q[:, None, 0] - p[None, :, 0]
        dy = q[:, None, 1] - p[None, :, 1]
        dz = q[:, None, 2] - p[None, :, 2]
        d2 = (dx * dx + dy * dy) + dz * dz
        cat = np.concatenate([best, d2], axis=1)
        best = np.partition(cat, k - 1, axis=1)[:, :k]
    return np.sqrt(best.max(axis=1))


def verify_sample(points: np.ndarray, dists: np.ndarray, k: int,
                  num_samples: int, max_radius: float = np.inf,
                  seed: int = 0, rtol: float = 1e-5,
                  atol: float = 1e-6) -> int:
    """Check ``num_samples`` random outputs against the exact answer.

    Returns the number of samples checked; raises AssertionError with the
    worst offender on mismatch. Tolerance covers XLA-vs-numpy FMA contraction
    differences (<= 1 ulp on the squared distances); inf patterns (under-full
    heaps) must match exactly.
    """
    n = len(points)
    num_samples = min(num_samples, n)
    rng = np.random.default_rng(seed)
    idx = rng.choice(n, size=num_samples, replace=False)
    want = kth_distance_exact(points, idx, k, max_radius)
    got = np.asarray(dists)[idx]
    inf_mismatch = np.isinf(got) != np.isinf(want)
    if inf_mismatch.any():
        i = int(np.argmax(inf_mismatch))
        raise AssertionError(
            f"selfcheck FAILED: point {idx[i]} got {got[i]}, exact {want[i]}")
    finite = ~np.isinf(want)
    if not np.allclose(got[finite], want[finite], rtol=rtol, atol=atol):
        err = np.abs(got[finite] - want[finite])
        i = int(np.argmax(err))
        gi = idx[finite][i]
        raise AssertionError(
            f"selfcheck FAILED: point {gi} got {got[finite][i]}, "
            f"exact {want[finite][i]} (|err| {err[i]:.3g})")
    return num_samples
