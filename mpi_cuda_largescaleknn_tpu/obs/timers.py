"""Phase timers + bandwidth counters + latency histograms.

The reference never measures itself (SURVEY.md §5: no timers anywhere, stdout
progress lines only) — this subsystem is the capability the TPU build adds so
BASELINE numbers can be produced at all. Wall-clock per phase, optional bytes
moved (for cross-shard exchange bandwidth), queries/sec derivation, and
log-bucketed latency histograms (p50/p95/p99) shared by ``--timings``, the
serving layer's ``/metrics`` endpoint, and ``tools/loadgen.py``.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import threading
import time
from dataclasses import dataclass, field


# shared histogram geometry — module-level so every histogram (server,
# loadgen, timers) is mergeable and renders identical /metrics buckets:
# geometric buckets, ~12% relative resolution, spanning [1 us, 120 s]
_HIST_FACTOR = 2 ** 0.1665
_HIST_BOUNDS: list[float] = [
    1e-6 * _HIST_FACTOR ** i
    for i in range(int(math.log(120.0 / 1e-6, _HIST_FACTOR)) + 2)
]


class LatencyHistogram:
    """Log-bucketed latency histogram with bounded memory.

    Buckets are geometric (``_HIST_BOUNDS``: factor ~1.122, ~12% relative
    resolution, [1 us, 120 s]); an observation beyond the top bound lands in
    a +inf overflow bucket. Percentiles are read off the cumulative counts and
    reported as the matched bucket's upper bound, so a quantile is
    conservative by at most one bucket width. ``record`` is thread-safe
    (serving handler threads and the loadgen's workers all feed one
    histogram).
    """

    _BOUNDS = _HIST_BOUNDS

    def __init__(self):
        self._lock = threading.Lock()
        self.counts = [0] * (len(self._BOUNDS) + 1)
        self.count = 0
        self.sum_seconds = 0.0

    def record(self, seconds: float) -> None:
        b = bisect.bisect_left(self._BOUNDS, seconds)
        with self._lock:
            self.counts[b] += 1
            self.count += 1
            self.sum_seconds += seconds

    def percentile(self, p: float) -> float:
        """Latency (seconds) at quantile ``p`` in [0, 100]; nan when empty."""
        if self.count == 0:
            return float("nan")
        rank = max(1, math.ceil(self.count * p / 100.0))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return (self._BOUNDS[i] if i < len(self._BOUNDS)
                        else float("inf"))
        return float("inf")

    def merge(self, other: "LatencyHistogram") -> None:
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.count += other.count
            self.sum_seconds += other.sum_seconds

    def report(self) -> dict:
        def q(p: float):
            # None, not nan/inf: the report is a strict-JSON artifact
            # (loadgen --out joins the BENCH series; /stats is scraped) and
            # json.dumps would emit the non-standard NaN/Infinity tokens
            v = self.percentile(p)
            return round(v, 6) if math.isfinite(v) else None

        return {"count": self.count,
                "sum_seconds": round(self.sum_seconds, 6),
                "p50": q(50), "p95": q(95), "p99": q(99)}

    def prometheus_lines(self, name: str) -> list[str]:
        """Render as a Prometheus-text histogram (cumulative ``le`` buckets).

        Empty buckets are elided (the geometry has ~170 buckets; a scrape
        only needs the populated prefix sums plus the +Inf terminal)."""
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for i, c in enumerate(self.counts[:-1]):
            cum += c
            if c:
                lines.append(
                    f'{name}_bucket{{le="{self._BOUNDS[i]:.6g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {self.count}')
        lines.append(f"{name}_sum {self.sum_seconds:.6g}")
        lines.append(f"{name}_count {self.count}")
        return lines


def labeled_metric_lines(name: str, rows, kind: str = "counter"):
    """Render one Prometheus text-format metric family with labels:
    ``rows`` is an iterable of ``(labels_dict, value)`` pairs, emitted in
    the caller's order (callers iterate sorted snapshots, so scrapes are
    deterministic). Shared by the wire-codec traffic stats and any other
    multi-labeled family — one place owns the label quoting."""
    rows = list(rows)
    if not rows:
        return []
    lines = [f"# TYPE {name} {kind}"]
    for labels, value in rows:
        lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
        lines.append(f"{name}{{{lab}}} {value}")
    return lines


@dataclass
class PhaseRecord:
    seconds: float = 0.0
    calls: int = 0
    bytes_moved: int = 0

    @property
    def gb_per_sec(self) -> float:
        return (self.bytes_moved / self.seconds / 1e9) if self.seconds else 0.0


@dataclass
class PhaseTimers:
    phases: dict[str, PhaseRecord] = field(default_factory=dict)
    histograms: dict[str, LatencyHistogram] = field(default_factory=dict)
    gauges: dict[str, float] = field(default_factory=dict)
    counters: dict[str, int] = field(default_factory=dict)
    _counter_lock: threading.Lock = field(default_factory=threading.Lock,
                                          repr=False)

    @contextlib.contextmanager
    def phase(self, name: str, bytes_moved: int = 0):
        rec = self.phases.setdefault(name, PhaseRecord())
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            rec.seconds += time.perf_counter() - t0
            rec.calls += 1
            rec.bytes_moved += bytes_moved

    def hist(self, name: str) -> LatencyHistogram:
        """Named latency histogram (created on first use); shows up in
        ``report()`` next to the phases, so ``--timings`` callers and the
        serving ``/stats`` endpoint share one percentile source."""
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = LatencyHistogram()
        return h

    def gauge(self, name: str, value: float) -> None:
        """Point-in-time gauge (e.g. pipeline occupancy). Single dict store,
        so concurrent writers are last-writer-wins — exactly gauge
        semantics; no lock needed."""
        self.gauges[name] = float(value)

    def count(self, name: str, by: int = 1) -> None:
        """Monotonic counter (e.g. ``fetch_bytes``, ``result_rows``) —
        unlike gauges, increments from concurrent completion threads must
        not lose updates, hence the lock."""
        with self._counter_lock:
            self.counters[name] = self.counters.get(name, 0) + int(by)

    def counter(self, name: str) -> int:
        with self._counter_lock:
            return self.counters.get(name, 0)

    def counters_snapshot(self) -> dict:
        """Atomic copy of every counter — for before/after deltas across a
        measurement window (e.g. serve_smoke's locality bench diffing
        ``tiles_executed`` over one loadgen run) without racing concurrent
        completion-thread increments between two ``counter()`` reads."""
        with self._counter_lock:
            return dict(self.counters)

    def report(self) -> dict:
        # list() snapshots: a serving /stats scrape may race a worker thread
        # inserting a new phase or histogram mid-iteration
        out = {name: {"seconds": round(r.seconds, 6), "calls": r.calls,
                      **({"GB/s": round(r.gb_per_sec, 3)} if r.bytes_moved else {})}
               for name, r in list(self.phases.items())}
        for name, h in list(self.histograms.items()):
            out[name] = h.report()
        for name, v in list(self.gauges.items()):
            out[name] = v
        with self._counter_lock:
            out.update(self.counters)
        return out

    def dump(self) -> str:
        return json.dumps(self.report())
