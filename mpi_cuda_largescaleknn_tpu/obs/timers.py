"""Phase timers + bandwidth counters.

The reference never measures itself (SURVEY.md §5: no timers anywhere, stdout
progress lines only) — this subsystem is the capability the TPU build adds so
BASELINE numbers can be produced at all. Wall-clock per phase, optional bytes
moved (for cross-shard exchange bandwidth), queries/sec derivation.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field


@dataclass
class PhaseRecord:
    seconds: float = 0.0
    calls: int = 0
    bytes_moved: int = 0

    @property
    def gb_per_sec(self) -> float:
        return (self.bytes_moved / self.seconds / 1e9) if self.seconds else 0.0


@dataclass
class PhaseTimers:
    phases: dict[str, PhaseRecord] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str, bytes_moved: int = 0):
        rec = self.phases.setdefault(name, PhaseRecord())
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            rec.seconds += time.perf_counter() - t0
            rec.calls += 1
            rec.bytes_moved += bytes_moved

    def report(self) -> dict:
        return {name: {"seconds": round(r.seconds, 6), "calls": r.calls,
                       **({"GB/s": round(r.gb_per_sec, 3)} if r.bytes_moved else {})}
                for name, r in self.phases.items()}

    def dump(self) -> str:
        return json.dumps(self.report())
