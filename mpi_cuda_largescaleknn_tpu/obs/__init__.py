from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers  # noqa: F401
from mpi_cuda_largescaleknn_tpu.obs.trace import profile_trace  # noqa: F401
