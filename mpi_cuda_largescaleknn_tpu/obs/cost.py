"""Analytic cost model: executed distance work -> FLOPs -> MFU estimate.

The reference never measures itself (SURVEY.md §5/§6) — establishing
roofline-style numbers is this framework's own capability. The unit of work
is one 3-D squared-distance evaluation (``FLOPS_PER_PAIR`` = 3 sub + 3 mul
+ 2 add = 8 f32 FLOPs); engines report how many pairs they actually scored
(ops/tiled.py ``with_stats``, parallel/ring.py ``return_stats``; flat
engines are analytic all-pairs).

The distance tile is elementwise VPU work — there is no matmul in the hot
loop (a Gram-matrix ``-2 q·p`` MXU formulation wastes the 128-wide
contraction on K=3) — so MFU is measured against the chip's VECTOR unit
peak, not the headline MXU number. The candidate-row merge (sorts,
compares) is real additional work not counted here: the estimate is a
LOWER bound on achieved utilization.

Per-chip vector-peak assumptions are order-of-magnitude from public specs
and overridable with ``LSK_PEAK_FLOPS`` (f32 FLOP/s); every report carries
the assumed peak so nothing is presented as more precise than it is.
"""

from __future__ import annotations

import os

FLOPS_PER_PAIR = 8  # 3 sub + 3 mul + 2 add per 3-D squared distance

# assumed peak VECTOR f32 FLOP/s per chip (see module docstring)
_PEAK_VPU_F32 = {
    "tpu": 4.0e12,   # TPU v4/v5-class VPU order of magnitude
    "cpu": 1.0e11,   # one AVX-ish host core pool, for labeled fallbacks
}


def peak_flops(platform: str) -> float:
    env = os.environ.get("LSK_PEAK_FLOPS")
    if env:
        return float(env)
    return _PEAK_VPU_F32.get(platform, _PEAK_VPU_F32["tpu"])


def cost_report(pair_evals: int, seconds: float, platform: str) -> dict:
    """{device flop estimate, pair-eval throughput, MFU vs vector peak}."""
    flops = pair_evals * FLOPS_PER_PAIR
    peak = peak_flops(platform)
    return {
        "pair_evals": int(pair_evals),
        "pair_evals_per_sec": round(pair_evals / seconds, 1) if seconds else 0.0,
        "distance_flops": int(flops),
        "assumed_peak_flops": peak,
        "mfu_estimate": round(flops / seconds / peak, 4) if seconds else 0.0,
    }
