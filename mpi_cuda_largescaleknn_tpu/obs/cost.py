"""Analytic cost model: executed distance work -> FLOPs -> MFU estimate.

The reference never measures itself (SURVEY.md §5/§6) — establishing
roofline-style numbers is this framework's own capability. The unit of work
is one 3-D squared-distance evaluation (``FLOPS_PER_PAIR`` = 3 sub + 3 mul
+ 2 add = 8 f32 FLOPs); engines report how many pairs they actually scored
(ops/tiled.py ``with_stats``, parallel/ring.py ``return_stats``; flat
engines are analytic all-pairs).

The distance tile is elementwise VPU work — there is no matmul in the hot
loop (a Gram-matrix ``-2 q·p`` MXU formulation wastes the 128-wide
contraction on K=3) — so MFU is measured against the chip's VECTOR unit
peak, not the headline MXU number. The candidate-row merge (sorts,
compares) is real additional work not counted here: the estimate is a
LOWER bound on achieved utilization.

Per-chip vector-peak assumptions are derived from the PROBED device kind
(``jax.devices()[0].device_kind``) using public per-generation VPU shapes
(lanes x sublanes x ALUs x clock), and overridable with ``LSK_PEAK_FLOPS``
(f32 FLOP/s); every report carries the assumed peak and the chip kind so
nothing is presented as more precise than it is.
"""

from __future__ import annotations

import os

FLOPS_PER_PAIR = 8  # 3 sub + 3 mul + 2 add per 3-D squared distance

# peak VECTOR f32 FLOP/s by device-kind substring (first match wins).
# VPU = 8 sublanes x 128 lanes x 4 ALUs x clock: v5e ~0.94 GHz -> 3.85e12,
# v4 ~1.05 GHz -> 4.3e12, v5p ~1.75 GHz -> 7.2e12; v6e wider -> ~8e12.
_PEAK_BY_KIND = (
    ("v5 lite", 3.85e12),
    ("v5e", 3.85e12),
    ("v5p", 7.2e12),
    ("v5", 7.2e12),   # bare "TPU v5" spelling = v5p (jax tpu_info)
    ("v6", 8.0e12),
    ("v4", 4.3e12),
    ("v3", 1.6e12),
)

# platform-level fallback when no device kind is known
_PEAK_VPU_F32 = {
    "tpu": 4.0e12,   # TPU v4/v5-class VPU order of magnitude
    "cpu": 1.0e11,   # one AVX-ish host core pool, for labeled fallbacks
}


def peak_flops(platform: str, device_kind: str | None = None) -> float:
    env = os.environ.get("LSK_PEAK_FLOPS")
    if env:
        return float(env)
    if device_kind:
        low = device_kind.lower()
        for frag, peak in _PEAK_BY_KIND:
            if frag in low:
                return peak
    return _PEAK_VPU_F32.get(platform, _PEAK_VPU_F32["tpu"])


def cost_report(pair_evals: int, seconds: float, platform: str,
                device_kind: str | None = None) -> dict:
    """{device flop estimate, pair-eval throughput, MFU vs vector peak}."""
    flops = pair_evals * FLOPS_PER_PAIR
    peak = peak_flops(platform, device_kind)
    return {
        "pair_evals": int(pair_evals),
        "pair_evals_per_sec": round(pair_evals / seconds, 1) if seconds else 0.0,
        "distance_flops": int(flops),
        "assumed_peak_flops": peak,
        "device_kind": device_kind or platform,
        "mfu_estimate": round(flops / seconds / peak, 4) if seconds else 0.0,
    }
