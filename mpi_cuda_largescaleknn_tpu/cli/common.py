"""Shared CLI parsing — the reference's exact 5-flag surface plus TPU knobs.

The reference's hand-rolled argv loop (identical in both programs,
unorderedDataVariant.cu:114-135 / prePartitionedDataVariant.cu:185-206):
positional input path, ``-o`` output, ``-k`` int (required), ``-r`` float max
search radius (default inf), ``-g`` GPU-affinity modulus, anything else ->
usage error + exit(1). We preserve that contract verbatim and add
double-dash TPU-side options the reference has no analogue for.
"""

from __future__ import annotations

import math
import sys

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig

TPU_FLAGS = """
TPU-side options (no reference analogue):
  --shards N        size of the 1-D device mesh (default: all devices)
  --engine E        tiled | pallas_tiled | bruteforce | tree | pallas | auto
                    (default auto = pallas_tiled, the fused nearest-first
                    kernel, on real TPUs; the XLA twin `tiled` elsewhere)
  --query-tile N    queries per inner tile (flat engines; default 2048)
  --point-tile N    tree points per inner tile (flat engines; default 2048)
  --bucket-size N   points per spatial bucket (tiled engines; default
                    auto: engine-tuned, see docs/TUNING.md)
  --point-group N   coarsen the resident point side by this power-of-two
                    factor (tiled engines; default auto: engine-tuned,
                    pass 1 to disable — see docs/TUNING.md; chunked runs
                    coarsen the resident side only)
  --query-chunk N   stream queries in chunks of N rows per device;
                    bounds candidate-heap memory to N*k per device for runs
                    whose heaps exceed HBM (e.g. -k 100 at 100M+ points)
  --merge M         chunked runs: host | device | auto — where the
                    cross-shard top-k merge runs (device keeps it inside
                    the SPMD program on the global mesh axis and fetches
                    final rows only; auto = device on power-of-two meshes,
                    host with a logged warning otherwise)
  --score-dtype T   distance scoring: f32 (exact elementwise, the default)
                    | bf16 (matmul-form MXU score + exact f32 rescore of
                    the top survivors — same final results whenever the
                    true top-k sits inside the rescore window; see
                    docs/TUNING.md "Distance kernel")
  --profile-dir D   write a jax.profiler trace
  --timings         print phase timings as JSON to stderr
  --checkpoint-dir D  snapshot exchange state between rounds (both
                    pipelines); an interrupted run relaunched with the same
                    args resumes at the lost round
  --checkpoint-every N  rounds between snapshots (default 1)
  --selfcheck N     after the run, verify N random outputs against an exact
                    streamed recomputation and fail loudly on mismatch (the
                    working version of the reference's disabled probe blocks)
  --write-indices P  also write the k neighbor IDs per point (int32, ascending
                    by distance, -1 = fewer than k found): unordered -> one
                    file P in global point order; prepartitioned -> one
                    P_%06d.int32 per shard. The reference computes these but
                    discards them (unorderedDataVariant.cu extractFinalResult)
  --coordinator A   multi-host: coordinator address host:port (the reference's
                    mpirun; here jax.distributed). Launch ONE copy of this CLI
                    per host with the same args plus --host-id
  --num-hosts N     multi-host: number of cooperating processes
  --host-id I       multi-host: this process's id in [0, N)
"""


def usage(program: str, error: str) -> "NoReturn":  # noqa: F821
    sys.stderr.write(f"Error: {error}\n\n")
    sys.stderr.write(
        f"{program} -k <k> [-r <maxRadius>] <input> -o <output>\n{TPU_FLAGS}")
    sys.exit(1)


def parse_args(program: str, argv: list[str]):
    """Returns (config, in_path, out_path, extras dict)."""
    k = 0
    max_radius = math.inf
    affinity = 0
    in_path = ""
    out_path = ""
    extras = {"shards": None, "engine": "auto", "query_tile": 2048,
              "point_tile": 2048, "bucket_size": 0, "point_group": 0,
              "profile_dir": None,
              "timings": False, "checkpoint_dir": None, "checkpoint_every": 1,
              "write_indices": None, "query_chunk": 0, "selfcheck": 0,
              "merge": "host", "score_dtype": "f32",
              "coordinator": None, "num_hosts": 1, "host_id": 0}
    i = 0
    try:
        while i < len(argv):
            arg = argv[i]
            if arg == "-o":
                i += 1; out_path = argv[i]
            elif not arg.startswith("-"):
                in_path = arg
            elif arg == "-r":
                i += 1; max_radius = float(argv[i])
            elif arg == "-g":
                i += 1; affinity = int(argv[i])
            elif arg == "-k":
                i += 1; k = int(argv[i])
            elif arg == "--shards":
                i += 1; extras["shards"] = int(argv[i])
            elif arg == "--engine":
                i += 1; extras["engine"] = argv[i]
            elif arg == "--query-tile":
                i += 1; extras["query_tile"] = int(argv[i])
            elif arg == "--point-tile":
                i += 1; extras["point_tile"] = int(argv[i])
            elif arg == "--bucket-size":
                i += 1; extras["bucket_size"] = int(argv[i])
            elif arg == "--point-group":
                i += 1; extras["point_group"] = int(argv[i])
            elif arg == "--profile-dir":
                i += 1; extras["profile_dir"] = argv[i]
            elif arg == "--timings":
                extras["timings"] = True
            elif arg == "--checkpoint-dir":
                i += 1; extras["checkpoint_dir"] = argv[i]
            elif arg == "--checkpoint-every":
                i += 1; extras["checkpoint_every"] = int(argv[i])
            elif arg == "--write-indices":
                i += 1; extras["write_indices"] = argv[i]
            elif arg == "--query-chunk":
                i += 1; extras["query_chunk"] = int(argv[i])
            elif arg == "--merge":
                i += 1; extras["merge"] = argv[i]
            elif arg == "--score-dtype":
                i += 1; extras["score_dtype"] = argv[i]
            elif arg == "--selfcheck":
                i += 1; extras["selfcheck"] = int(argv[i])
            elif arg == "--coordinator":
                i += 1; extras["coordinator"] = argv[i]
            elif arg == "--num-hosts":
                i += 1; extras["num_hosts"] = int(argv[i])
            elif arg == "--host-id":
                i += 1; extras["host_id"] = int(argv[i])
            else:
                usage(program, f"unknown cmdline arg '{arg}'")
            i += 1
    except (IndexError, ValueError):
        usage(program, f"invalid or missing value for '{argv[i - 1] if i else ''}'")

    if not in_path:
        usage(program, "no input file name specified")
    if not out_path:
        usage(program, "no output file name specified")
    if k < 1:
        usage(program, "no k specified, or invalid k value")

    cfg = KnnConfig(k=k, max_radius=max_radius, device_affinity=affinity,
                    engine=extras["engine"], query_tile=extras["query_tile"],
                    point_tile=extras["point_tile"],
                    bucket_size=extras["bucket_size"],
                    point_group=extras["point_group"],
                    num_shards=extras["shards"] or 0,
                    query_chunk=extras["query_chunk"],
                    merge=extras["merge"],
                    score_dtype=extras["score_dtype"],
                    profile_dir=extras["profile_dir"],
                    checkpoint_dir=extras["checkpoint_dir"],
                    checkpoint_every=extras["checkpoint_every"])
    return cfg, in_path, out_path, extras
