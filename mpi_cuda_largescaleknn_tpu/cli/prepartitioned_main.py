"""``tpuknn-prepartitioned`` — the ``cudaMpiKNN_prePartitionedData`` entry point.

Reference contract (README.md:38-41):
    mpirun -n numFiles ./cudaMpiKNN_prePartitionedData fileNames.txt -k 100 -o prefix
TPU form:
    python -m mpi_cuda_largescaleknn_tpu.cli.prepartitioned_main fileNames.txt \
        -k 100 -o prefix [--shards R]

One shard per listed file (count must equal the mesh size, the reference's
``#files == ranks`` check, prePartitionedDataVariant.cu:215-216); outputs one
``prefix_%06d.float`` per shard (:380-385).
"""

from __future__ import annotations

import sys

from mpi_cuda_largescaleknn_tpu.cli.common import parse_args
from mpi_cuda_largescaleknn_tpu.io.reader import read_list_of_file_names, read_points
from mpi_cuda_largescaleknn_tpu.io.writer import (
    write_rank_file,
    write_rank_indices,
)
from mpi_cuda_largescaleknn_tpu.models.prepartitioned import PrePartitionedKNN
from mpi_cuda_largescaleknn_tpu.obs.trace import profile_trace
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh


def main(argv: list[str] | None = None) -> int:
    cfg, in_path, out_prefix, extras = parse_args(
        "tpuknn-prepartitioned", sys.argv[1:] if argv is None else argv)

    if extras["num_hosts"] > 1:
        # pod-scale SPMD launch: per-host file IO + one global mesh
        from mpi_cuda_largescaleknn_tpu.cli.multihost import (
            run_prepartitioned_multihost,
        )
        return run_prepartitioned_multihost(cfg, in_path, out_prefix, extras)

    file_names = read_list_of_file_names(in_path)
    mesh = get_mesh(extras["shards"] if extras["shards"] is not None
                    else len(file_names))
    if len(file_names) != mesh.shape[AXIS]:
        raise RuntimeError("number of input files does not match mesh size")

    partitions = [read_points(f) for f in file_names]
    for r, p in enumerate(partitions):
        print(f"#{r}/{len(partitions)}: got {len(p)} points to work on")

    model = PrePartitionedKNN(cfg, mesh=mesh)
    want_idx = extras["write_indices"] is not None
    with profile_trace(cfg.profile_dir):
        got = model.run(partitions, return_neighbors=want_idx)
    results, idx_lists = got if want_idx else (got, None)
    for r, dists in enumerate(results):
        write_rank_file(out_prefix, r, dists)
        if want_idx:
            write_rank_indices(extras["write_indices"], r, idx_lists[r])
    if extras["selfcheck"] > 0:
        import numpy as np

        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        checked = verify_sample(np.concatenate(partitions),
                                np.concatenate(results), cfg.k,
                                extras["selfcheck"],
                                max_radius=cfg.max_radius)
        print(f"selfcheck OK ({checked} samples)")
    print("done all queries...")
    if extras["timings"]:
        sys.stderr.write(model.timers.dump() + "\n")
        sys.stderr.write(f"stats: {model.last_stats}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
