"""Multi-host (pod-scale) driver for the unordered pipeline.

The reference scales across nodes with ``mpirun -n R``: every rank reads
ONLY its slab of the input (unorderedDataVariant.cu:145-148) and appends
ONLY its slab of the output, barrier-fenced in rank order (:229-237) — no
node ever holds the whole dataset. This is that contract at pod scale:

- one copy of the CLI per host (``--coordinator/--num-hosts/--host-id``,
  the mpirun lifecycle as ``jax.distributed.initialize``);
- each host preads only the slabs of the mesh positions its local devices
  own (io/native.py threaded pread) and assembles its process-local block
  of the global sharded array (``jax.make_array_from_process_local_data``);
- the ring runs as ONE jitted SPMD program over the global mesh — the
  collectives ride ICI/DCN, no host ever sees remote rows;
- each host pwrites its result slabs at their byte offsets into the ONE
  output file (io/writer.py ``write_distances_slab``; host 0 pre-sizes,
  a global sync fences the concurrent writers — the reference's barrier
  serialization made parallel).

Validated off-pod by the 2-process CPU-mesh integration test
(tests/test_multihost.py): byte-identical output to a single-process run
with the same shard count.
"""

from __future__ import annotations

import os

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.io.reader import read_file_portion
from mpi_cuda_largescaleknn_tpu.io.writer import write_distances_slab
from mpi_cuda_largescaleknn_tpu.models.sharding import (
    pad_and_flatten,
    slab_bounds,
)
from mpi_cuda_largescaleknn_tpu.parallel.mesh import (
    AXIS,
    get_mesh,
    initialize_distributed,
    my_mesh_positions as _my_mesh_positions,
)
from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn


def run_unordered_multihost(cfg: KnnConfig, in_path: str, out_path: str,
                            extras: dict) -> int:
    import jax
    from jax.experimental import multihost_utils

    if extras.get("write_indices"):
        raise ValueError("--write-indices is not supported in "
                         "multi-host mode")
    if extras.get("selfcheck"):
        raise ValueError("--selfcheck is not supported in multi-host mode")
    if cfg.checkpoint_dir and not cfg.query_chunk:
        raise ValueError("multi-host --checkpoint-dir requires "
                         "--query-chunk (per-chunk result checkpoints; "
                         "round-level heap snapshots are single-host only)")

    initialize_distributed(extras["coordinator"], extras["num_hosts"],
                           extras["host_id"])
    mesh = get_mesh(extras["shards"])
    num_shards = mesh.shape[AXIS]
    proc = jax.process_index()

    n_total = os.path.getsize(in_path) // 12
    bounds = slab_bounds(n_total, num_shards)
    npad = max(e - b for b, e in bounds)

    my_pos = _my_mesh_positions(mesh)

    shards = []
    for s in my_pos:
        pts, begin, _ = read_file_portion(in_path, s, num_shards)
        assert begin == bounds[s][0]
        shards.append(pts)
    local_flat, local_ids, counts, _ = pad_and_flatten(
        shards, id_bases=[bounds[s][0] for s in my_pos], pad_to=npad)
    print(f"# host {proc}: mesh of {num_shards} device(s), "
          f"{sum(counts)} of {n_total} points local")

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P(AXIS))
    flat_g = jax.make_array_from_process_local_data(
        sharding, local_flat, (num_shards * npad, 3))
    ids_g = jax.make_array_from_process_local_data(
        sharding, local_ids, (num_shards * npad,))

    if cfg.query_chunk > 0:
        # streamed query chunks (the beyond-HBM heap regime) composed with
        # the pod-scale path: each host chunks its own blocks, optionally
        # checkpointing its rows per chunk (parallel/ring.py multi branch)
        from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn_chunked

        local_rows = ring_knn_chunked(
            flat_g, ids_g, cfg.k, mesh, chunk_rows=cfg.query_chunk,
            max_radius=cfg.max_radius, engine=cfg.engine,
            query_tile=cfg.query_tile, point_tile=cfg.point_tile,
            bucket_size=cfg.bucket_size, point_group=cfg.point_group,
            merge=cfg.merge,
            checkpoint_dir=cfg.checkpoint_dir,
            checkpoint_every=cfg.checkpoint_every)
    else:
        dists = ring_knn(flat_g, ids_g, cfg.k, mesh,
                         max_radius=cfg.max_radius, engine=cfg.engine,
                         query_tile=cfg.query_tile,
                         point_tile=cfg.point_tile,
                         bucket_size=cfg.bucket_size,
                         point_group=cfg.point_group)
        local_rows = {int(sh.index[0].start) // npad:
                      np.asarray(sh.data).reshape(-1)
                      for sh in dists.addressable_shards}

    # host 0 pre-sizes the single global output file (stale-bytes safety,
    # io/native_io.cpp lsk_create_sized), a sync fences it before the
    # concurrent slab writers — then each host writes ONLY its slabs
    if proc == 0:
        write_distances_slab(out_path, 0, np.empty((0,), np.float32),
                             n_total, presize=True)
    multihost_utils.sync_global_devices("lsk_output_presized")
    for s, cnt in zip(my_pos, counts):
        write_distances_slab(out_path, bounds[s][0],
                             local_rows[s][:cnt], n_total)
    multihost_utils.sync_global_devices("lsk_output_written")
    print("done all queries...")
    return 0


def run_prepartitioned_multihost(cfg: KnnConfig, in_path: str,
                                 out_prefix: str, extras: dict) -> int:
    """Pod-scale prepartitioned pipeline: one partition file per mesh
    position (the reference's one-file-per-rank, asserted at
    prePartitionedDataVariant.cu:215-216); each host reads ONLY the files
    of its local positions. The global pad-to-max (:251-266) needs every
    partition's count — obtained from file sizes (metadata stat, no data
    read), the ``Allreduce(MAX)`` of :254-255 done on the filesystem."""
    import jax
    from jax.experimental import multihost_utils

    from mpi_cuda_largescaleknn_tpu.io.reader import (
        read_list_of_file_names,
        read_points,
    )
    from mpi_cuda_largescaleknn_tpu.io.writer import write_rank_file
    from mpi_cuda_largescaleknn_tpu.parallel.demand import demand_knn

    for flag in ("write_indices", "checkpoint_dir"):
        if extras.get(flag):
            raise ValueError(f"--{flag.replace('_', '-')} is not supported "
                             "in multi-host mode")
    if extras.get("selfcheck"):
        raise ValueError("--selfcheck is not supported in multi-host mode")
    if cfg.query_chunk:
        raise ValueError("--query-chunk with the prepartitioned pipeline is "
                         "single-host only (the chunked demand driver "
                         "assembles chunks from host-local rows)")

    initialize_distributed(extras["coordinator"], extras["num_hosts"],
                           extras["host_id"])
    file_names = read_list_of_file_names(in_path)
    mesh = get_mesh(extras["shards"] if extras["shards"] is not None
                    else len(file_names))
    num_shards = mesh.shape[AXIS]
    if len(file_names) != num_shards:
        raise RuntimeError("number of input files does not match mesh size")
    proc = jax.process_index()

    sizes = [os.path.getsize(f) // 12 for f in file_names]
    npad = max(max(sizes), 1)
    id_bases = np.concatenate([[0], np.cumsum(sizes)[:-1]]).tolist()

    my_pos = _my_mesh_positions(mesh)
    parts = [read_points(file_names[s]) for s in my_pos]
    for s, p in zip(my_pos, parts):
        assert len(p) == sizes[s], (file_names[s], len(p), sizes[s])
        print(f"#{s}/{num_shards}: got {len(p)} points to work on")
    local_flat, local_ids, counts, _ = pad_and_flatten(
        parts, id_bases=[id_bases[s] for s in my_pos], pad_to=npad)

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P(AXIS))
    flat_g = jax.make_array_from_process_local_data(
        sharding, local_flat, (num_shards * npad, 3))
    ids_g = jax.make_array_from_process_local_data(
        sharding, local_ids, (num_shards * npad,))

    dists = demand_knn(flat_g, ids_g, cfg.k, mesh,
                       max_radius=cfg.max_radius, engine=cfg.engine,
                       query_tile=cfg.query_tile, point_tile=cfg.point_tile,
                       bucket_size=cfg.bucket_size,
                       point_group=cfg.point_group)

    local_rows = {int(sh.index[0].start) // npad:
                  np.asarray(sh.data).reshape(-1)
                  for sh in dists.addressable_shards}
    for s, cnt in zip(my_pos, counts):
        write_rank_file(out_prefix, s, local_rows[s][:cnt])
    multihost_utils.sync_global_devices("lsk_prepart_written")
    print("done all queries...")
    return 0
