"""``tpuknn-serve`` — the online serving entry point (no reference analogue).

The batch CLIs answer one self-join and exit; this one loads a point set,
builds the resident sharded index, AOT-compiles every shape bucket, and
serves queries over HTTP until killed:

    python -m mpi_cuda_largescaleknn_tpu.cli.serve_main points.float3 -k 100 \
        [--port 8080] [--engine auto] [--merge auto] [--shards R] \
        [--max-batch 1024] [--max-delay-ms 2] [--max-queue-rows 4096] \
        [--timeout-ms 5000]

Endpoints: POST /knn (JSON or binary), GET /healthz, /stats, /metrics
(Prometheus text). With --tenant (repeatable) the process serves MANY
indexes from one slab pool: POST /v1/<tenant>/knn, per-tenant /stats
namespaces, {tenant=} metric labels, and per-tenant admission quotas
(docs/SERVING.md "Multi-index tenancy"). See tools/loadgen.py.
"""

from __future__ import annotations

import math
import sys

# BEFORE any jax import: the persistent compile cache env vars are read at
# backend init, and a serving process is exactly the caller that must never
# repay the ~220s cold compile twice
from mpi_cuda_largescaleknn_tpu.utils.compile_cache import (
    enable_persistent_cache,
)

SERVE_FLAGS = """
  -k N              neighbors per query (required)
  -r R              max search radius (default inf)
  --port P          HTTP port (default 8080; 0 = pick a free port)
  --host H          bind address (default 127.0.0.1)
  --engine E        tiled | pallas_tiled | bruteforce | auto (default auto)
  --merge M         host | device | auto (default auto): where the R-way
                    cross-shard top-k merge runs — device keeps it inside
                    the SPMD program (all_to_all reduce-scatter + top_k;
                    one final [Q,k] fetch, no numpy merge), host fetches
                    R partials; auto = device on power-of-two meshes
  --shards N        size of the 1-D device mesh (default: all devices)
  --bucket-size N   points per spatial bucket (0 = engine-tuned auto)
  --score-dtype T   distance scoring: f32 (exact elementwise, the default)
                    | bf16 (matmul-form MXU score + exact f32 rescore of
                    the top survivors; docs/TUNING.md "Distance kernel")
  --query-buckets N query-side buckets per padded batch (0 = auto, ~k
                    queries per bucket; 1 = single whole-batch bucket AND
                    disables the Morton admission sort — the pre-locality
                    behavior). Served batches are Morton-sorted so the
                    buckets are spatially tight, tightening each bucket's
                    prune radius; see docs/TUNING.md "Query locality"
  --max-batch N     widest padded query batch / shape bucket (default 1024)
  --min-batch N     narrowest shape bucket (default 8)
  --num-slabs N     tiered slab index (beyond-HBM streaming; default 0 =
                    fully resident): split the index into N row slabs and
                    serve them through the device/host-RAM/mmap slab pool
                    (serve/slabpool.py) — bit-identical to fully resident
                    at every budget; a cold slab STALLS, never
                    approximates (docs/SERVING.md "Tiered slab index")
  --device-slab-budget B  device-memory budget in bytes for the resident
                    slab working set (suffixes k/m/g; 0 = unbounded),
                    counted against each slab engine's reported
                    device_bytes footprint; LRU-with-pin eviction
  --host-pool-bytes B  host-RAM row-pool budget in bytes (suffixes
                    k/m/g; 0 = unbounded); slabs past it re-read from
                    the mmap/file cold tier. Byte accounting is what
                    keeps mixed-size tenant slabs from blowing the host
                    tier — prefer it over --host-pool-slabs
  --host-pool-slabs N  DEPRECATED fallback: the same cap counted in
                    slabs (0 = unbounded). Kept for existing deploy
                    scripts; slab counts only bound memory when every
                    slab is the same size — use --host-pool-bytes.
                    Both caps apply when both are set
  --tenant NAME=PATH  multi-index tenancy (repeatable; serve/tenancy.py):
                    serve PATH's index as tenant NAME at
                    POST /v1/NAME/knn. All tenants share ONE slab pool
                    (--device-slab-budget, --host-pool-bytes), one AOT
                    executable cache (compile count stays flat as
                    tenants grow), and one admission controller. The
                    FIRST --tenant is the default tenant — legacy /knn
                    routes to it. Each tenant's index is split into
                    --num-slabs slabs (default 1). Incompatible with
                    pod/routed/standby modes; a positional input file is
                    not used (and rejected) in tenancy mode
  --tenant-quota-rows N  per-tenant admission quota: each tenant may
                    hold at most N queued+in-flight rows of the global
                    --max-queue-rows budget (0 = unsliced, global cap
                    only). Over-quota requests get 429 + Retry-After
                    like global overload, so one hot tenant cannot
                    starve the rest
  --prefetch-depth N  next-nearest slabs promoted asynchronously per
                    dispatched batch (default 1; the batcher additionally
                    announces the next batch's routed slab set a batch
                    ahead — docs/TUNING.md "Tiered slab index")
  --max-delay-ms F  micro-batch flush deadline (default 2.0)
  --pipeline-depth N  batches in flight between dispatch and demux
                    (default 2: next batch's device traversal overlaps the
                    previous batch's host merge; 1 = fully serialized)
  --max-queue-rows N  admission cap on queued+running rows (default 4096)
  --timeout-ms F    default per-request deadline (default 5000)
  --qcache-rows N   certified query cache capacity in cached rows
                    (default 4096; 0 disables the cache —
                    serve/qcache.py): byte-identical exact-hit reuse
                    keyed by (tenant, index generation, plan, query
                    bytes), plus in-flight dedup of concurrent
                    duplicates (docs/SERVING.md "Query cache & radius
                    seeding")
  --qcache-seed-rows N  triangle-inequality seed pool rows per tenant
                    (default 512; 0 disables radius seeding while
                    keeping the hit/dedup tiers): near-duplicates of a
                    cached query start their top-k heap at a certified
                    radius r = d_k(q0) + ||q - q0|| — provably
                    bit-identical answers, earlier tile pruning
  --recall-policy PATH  recall-SLO plan table (JSON from
                    tools/recall_harness.py) replacing the built-in
                    calibrated defaults; requests carrying
                    ``"recall": 0.95`` (or ``?recall=`` for binary) are
                    served by the cheapest plan whose measured recall
                    meets the target, flagged ``exact: false``
                    (serve/recall.py; docs/SERVING.md "Recall-SLO tier").
                    Exact stays the default for requests with no target
  --seq-timeout-s F how long a pod host waits for its turn in the
                    /shard_knn sequence order before answering 503 +
                    Retry-After (default 120; replicate mode only — a
                    lower seq that never arrives means the pod stream is
                    stalled). Fault injection for failure drills rides the
                    KNN_FAULTS env var / POST /faults (serve/faults.py)
  --no-warmup       skip compiling all shape buckets before serving
                    (first request per bucket then pays the compile)
  --timings         print engine phase timings as JSON on shutdown
  --verbose         log each HTTP request to stderr

Multi-host (pod) mode — launch ONE copy per host with the same args plus
--host-id; the processes join one global device mesh (jax.distributed, the
batch CLIs' lifecycle) and each serves its 1/R slice of the pod-final
answer over POST /shard_knn to the pod front end
(python -m mpi_cuda_largescaleknn_tpu.serve.frontend --hosts ...):
  --coordinator A   coordinator address host:port
  --num-hosts N     number of cooperating serving processes
  --host-id I       this process's id in [0, N)
  --routing M       off | bounds (default off). bounds = shard-local
                    routing: the hosts stay INDEPENDENT (no coordinator,
                    no global mesh) — each loads only its row slab
                    [N*i/H, N*(i+1)/H) of the input, serves full candidate
                    rows on POST /route_knn, and the front end routes each
                    query by the hosts' shard bounding boxes
                    (docs/SERVING.md "Shard-local routing"). Routing wins
                    need a spatially-ordered input file (the io
                    partitioner's Morton order); an unordered file stays
                    exact but routes every query everywhere
  --standby         routed mode only: start as a WARM STANDBY — load no
                    slab, build no engine, and wait for the pod front
                    end's replica manager to direct an adoption
                    (POST /adopt_slab). The standby then materializes the
                    named slab from this process's input file (or pulls
                    it from a surviving replica), AOT-warms every shape
                    bucket, and serves it — fingerprint-gated by the
                    front end before any query routes here
                    (docs/SERVING.md "Replication & slab handoff")
  --wire M          auto | f32 (default auto). Host-side wire-codec
                    capability: auto advertises the compressed codecs
                    (q16 candidate rows, d16 slab transfer — served only
                    when the peer asks; docs/SERVING.md "Wire formats");
                    f32 advertises and serves only the uncompressed
                    codec — the supported old-binary emulation for mixed
                    pods, and the kill switch if a codec misbehaves
"""


def usage(error: str) -> "NoReturn":  # noqa: F821
    sys.stderr.write(f"Error: {error}\n\n")
    sys.stderr.write(f"tpuknn-serve -k <k> [options] <input>\n{SERVE_FLAGS}")
    sys.exit(1)


def parse_bytes(text: str) -> int:
    """'268435456', '256m', '2g', '64k' -> bytes (suffixes are binary)."""
    t = text.strip().lower()
    mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}.get(t[-1:], 1)
    return int(float(t[:-1] if mult > 1 else t) * mult)


def parse_serve_args(argv: list[str]) -> dict:
    opt = {"k": 0, "max_radius": math.inf, "in_path": "", "port": 8080,
           "host": "127.0.0.1", "engine": "auto", "merge": "auto",
           "score_dtype": "f32", "shards": None,
           "bucket_size": 0, "query_buckets": 0,
           "max_batch": 1024, "min_batch": 8,
           "num_slabs": 0, "device_slab_budget": 0,
           "host_pool_slabs": 0, "host_pool_bytes": 0,
           "tenants": [], "tenant_quota_rows": 0,
           "prefetch_depth": 1,
           "max_delay_ms": 2.0, "pipeline_depth": 2,
           "max_queue_rows": 4096, "seq_timeout_s": None,
           "qcache_rows": 4096, "qcache_seed_rows": 512,
           "recall_policy": None,
           "timeout_ms": 5000.0, "warmup": True, "timings": False,
           "verbose": False,
           "coordinator": None, "num_hosts": 1, "host_id": 0,
           "routing": "off", "standby": False, "wire": "auto"}
    i = 0
    try:
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("-"):
                opt["in_path"] = arg
            elif arg == "-k":
                i += 1; opt["k"] = int(argv[i])
            elif arg == "-r":
                i += 1; opt["max_radius"] = float(argv[i])
            elif arg == "--port":
                i += 1; opt["port"] = int(argv[i])
            elif arg == "--host":
                i += 1; opt["host"] = argv[i]
            elif arg == "--engine":
                i += 1; opt["engine"] = argv[i]
            elif arg == "--merge":
                i += 1; opt["merge"] = argv[i]
            elif arg == "--score-dtype":
                i += 1; opt["score_dtype"] = argv[i]
            elif arg == "--shards":
                i += 1; opt["shards"] = int(argv[i])
            elif arg == "--bucket-size":
                i += 1; opt["bucket_size"] = int(argv[i])
            elif arg == "--query-buckets":
                i += 1; opt["query_buckets"] = int(argv[i])
            elif arg == "--max-batch":
                i += 1; opt["max_batch"] = int(argv[i])
            elif arg == "--min-batch":
                i += 1; opt["min_batch"] = int(argv[i])
            elif arg == "--num-slabs":
                i += 1; opt["num_slabs"] = int(argv[i])
            elif arg == "--device-slab-budget":
                i += 1; opt["device_slab_budget"] = parse_bytes(argv[i])
            elif arg == "--host-pool-slabs":
                i += 1; opt["host_pool_slabs"] = int(argv[i])
            elif arg == "--host-pool-bytes":
                i += 1; opt["host_pool_bytes"] = parse_bytes(argv[i])
            elif arg == "--tenant":
                i += 1
                name, sep, path = argv[i].partition("=")
                if not sep or not name or not path:
                    usage(f"--tenant wants NAME=PATH, got '{argv[i]}'")
                opt["tenants"].append((name, path))
            elif arg == "--tenant-quota-rows":
                i += 1; opt["tenant_quota_rows"] = int(argv[i])
            elif arg == "--prefetch-depth":
                i += 1; opt["prefetch_depth"] = int(argv[i])
            elif arg == "--max-delay-ms":
                i += 1; opt["max_delay_ms"] = float(argv[i])
            elif arg == "--pipeline-depth":
                i += 1; opt["pipeline_depth"] = int(argv[i])
            elif arg == "--max-queue-rows":
                i += 1; opt["max_queue_rows"] = int(argv[i])
            elif arg == "--timeout-ms":
                i += 1; opt["timeout_ms"] = float(argv[i])
            elif arg == "--seq-timeout-s":
                i += 1; opt["seq_timeout_s"] = float(argv[i])
            elif arg == "--qcache-rows":
                i += 1; opt["qcache_rows"] = int(argv[i])
            elif arg == "--qcache-seed-rows":
                i += 1; opt["qcache_seed_rows"] = int(argv[i])
            elif arg == "--recall-policy":
                i += 1; opt["recall_policy"] = argv[i]
            elif arg == "--coordinator":
                i += 1; opt["coordinator"] = argv[i]
            elif arg == "--num-hosts":
                i += 1; opt["num_hosts"] = int(argv[i])
            elif arg == "--host-id":
                i += 1; opt["host_id"] = int(argv[i])
            elif arg == "--routing":
                i += 1; opt["routing"] = argv[i]
            elif arg == "--standby":
                opt["standby"] = True
            elif arg == "--wire":
                i += 1; opt["wire"] = argv[i]
            elif arg == "--no-warmup":
                opt["warmup"] = False
            elif arg == "--timings":
                opt["timings"] = True
            elif arg == "--verbose":
                opt["verbose"] = True
            else:
                usage(f"unknown cmdline arg '{arg}'")
            i += 1
    except (IndexError, ValueError):
        usage(f"invalid or missing value for '{argv[i - 1] if i else ''}'")
    if opt["tenants"]:
        if opt["in_path"]:
            usage("tenancy mode takes its inputs from --tenant NAME=PATH "
                  f"— drop the positional input '{opt['in_path']}'")
        if opt["num_hosts"] > 1 or opt["routing"] != "off" or opt["standby"]:
            usage("--tenant (multi-index tenancy) is single-process "
                  "serving — it does not combine with pod, routed, or "
                  "standby modes")
        names = [n for n, _p in opt["tenants"]]
        if len(set(names)) != len(names):
            usage(f"duplicate tenant names in {names}")
    elif not opt["in_path"]:
        usage("no input file name specified")
    if opt["k"] < 1:
        usage("no k specified, or invalid k value")
    if opt["routing"] not in ("off", "bounds"):
        usage(f"--routing must be off or bounds, got '{opt['routing']}'")
    if opt["wire"] not in ("auto", "f32"):
        usage(f"--wire must be auto or f32, got '{opt['wire']}'")
    if opt["routing"] == "bounds" and opt["coordinator"]:
        usage("--routing bounds hosts are independent processes — they "
              "never join a global mesh, so --coordinator is a config "
              "error (use --routing off for the pod-collective mode)")
    if opt["standby"] and opt["routing"] != "bounds":
        usage("--standby is the routed tier's slab-handoff target — "
              "launch with --routing bounds")
    if opt["num_slabs"] < 0:
        usage(f"--num-slabs must be >= 0, got {opt['num_slabs']}")
    if opt["num_slabs"] > 0:
        if opt["num_hosts"] > 1 and opt["routing"] != "bounds":
            usage("--num-slabs (tiered slab streaming) does not combine "
                  "with the pod-collective mode — the streamed slab set "
                  "varies per batch, a pod-wide SPMD program cannot; use "
                  "--routing bounds hosts (each streams its own slab)")
        if opt["standby"]:
            usage("--standby hosts materialize their engine at adoption "
                  "time — launch the adopted engine without --num-slabs")
    return opt


def main(argv: list[str] | None = None) -> int:
    opt = parse_serve_args(sys.argv[1:] if argv is None else argv)
    enable_persistent_cache()

    from mpi_cuda_largescaleknn_tpu.io.reader import read_points
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import (
        get_mesh,
        initialize_distributed,
    )
    from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine
    from mpi_cuda_largescaleknn_tpu.serve.server import (
        build_server,
        serve_forever,
    )

    routed = opt["routing"] == "bounds"
    if opt["num_hosts"] > 1 and not routed:
        # pod mode: join the global mesh BEFORE any device query — the
        # engine below then builds over all hosts' devices and its AOT
        # query programs are pod-wide collectives (serve/frontend.py)
        initialize_distributed(opt["coordinator"], opt["num_hosts"],
                               opt["host_id"])

    if routed and opt["standby"]:
        # warm standby (slab handoff): no slab, no engine — record the
        # engine-construction knobs and wait for POST /adopt_slab from
        # the front end's replica manager (serve/replica.py)
        from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

        standby_config = {
            "path": opt["in_path"], "num_hosts": opt["num_hosts"],
            "k": opt["k"], "shards": opt["shards"],
            "engine": opt["engine"], "merge": opt["merge"],
            "bucket_size": opt["bucket_size"],
            "max_radius": opt["max_radius"],
            "max_batch": opt["max_batch"], "min_batch": opt["min_batch"],
            "query_buckets": opt["query_buckets"],
            "score_dtype": opt["score_dtype"]}
        server = HostSliceServer((opt["host"], opt["port"]), None,
                                 routing="bounds",
                                 standby_config=standby_config,
                                 wire=opt["wire"],
                                 verbose=opt["verbose"])
        host, port = server.server_address[:2]
        print(f"standby host on http://{host}:{port} — no slab adopted "
              f"yet; waiting for POST /adopt_slab ({opt['in_path']}, "
              f"{opt['num_hosts']} slabs)")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            print("shutting down")
        finally:
            server.close()
        return 0

    if opt["tenants"]:
        # multi-index tenancy: many indexes behind ONE slab pool, one AOT
        # cache, one admission controller (serve/tenancy.py). Each
        # tenant's index streams as --num-slabs slabs (default 1)
        from mpi_cuda_largescaleknn_tpu.serve.tenancy import (
            MultiTenantEngine,
            TenantSpec,
        )

        engine = MultiTenantEngine(
            [TenantSpec(name, path=path,
                        num_slabs=max(1, opt["num_slabs"]))
             for name, path in opt["tenants"]],
            k=opt["k"], mesh=get_mesh(opt["shards"]),
            device_slab_budget=opt["device_slab_budget"],
            host_pool_slabs=opt["host_pool_slabs"],
            host_pool_bytes=opt["host_pool_bytes"],
            prefetch_depth=opt["prefetch_depth"], engine=opt["engine"],
            bucket_size=opt["bucket_size"], max_radius=opt["max_radius"],
            max_batch=opt["max_batch"], min_batch=opt["min_batch"],
            merge=opt["merge"], query_buckets=opt["query_buckets"],
            score_dtype=opt["score_dtype"])
        print(f"multi-index tenancy: {len(opt['tenants'])} tenants "
              f"({', '.join(n for n, _p in opt['tenants'])}), "
              f"{engine.n_points} points total, default tenant "
              f"'{engine.default_tenant}', quota "
              f"{opt['tenant_quota_rows'] or 'unsliced'} rows/tenant")
        recall_policy = None
        if opt["recall_policy"]:
            from mpi_cuda_largescaleknn_tpu.serve.recall import RecallPolicy

            recall_policy = RecallPolicy.from_file(opt["recall_policy"])
        server = build_server(
            engine, host=opt["host"], port=opt["port"],
            max_delay_s=opt["max_delay_ms"] / 1e3,
            pipeline_depth=opt["pipeline_depth"],
            max_queue_rows=opt["max_queue_rows"],
            default_timeout_s=opt["timeout_ms"] / 1e3,
            verbose=opt["verbose"], recall_policy=recall_policy,
            tenant_quota_rows=opt["tenant_quota_rows"],
            qcache_rows=opt["qcache_rows"],
            qcache_seed_rows=opt["qcache_seed_rows"])
        try:
            serve_forever(server, warmup=opt["warmup"])
        except KeyboardInterrupt:
            print("shutting down")
        finally:
            server.close()
            if opt["timings"]:
                sys.stderr.write(engine.timers.dump() + "\n")
        return 0

    streaming = opt["num_slabs"] > 0
    id_offset = 0
    if routed and streaming:
        # beyond-HBM routed host: load THIS host's row slab once, then
        # stream it as --num-slabs sub-slabs through the tiered pool —
        # the host's device budget no longer caps its slab size
        # (docs/SERVING.md "Tiered slab index")
        from mpi_cuda_largescaleknn_tpu.serve.engine import load_slab_rows
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )

        if not (0 <= opt["host_id"] < opt["num_hosts"]):
            usage(f"--host-id {opt['host_id']} outside [0, "
                  f"{opt['num_hosts']})")
        rows, id_offset, n_total = load_slab_rows(
            opt["in_path"], opt["host_id"], opt["num_hosts"])
        engine = StreamingKnnEngine(
            points=rows, num_slabs=opt["num_slabs"], k=opt["k"],
            device_slab_budget=opt["device_slab_budget"],
            host_pool_slabs=opt["host_pool_slabs"],
            host_pool_bytes=opt["host_pool_bytes"],
            prefetch_depth=opt["prefetch_depth"],
            mesh=get_mesh(opt["shards"]), engine=opt["engine"],
            bucket_size=opt["bucket_size"], max_radius=opt["max_radius"],
            max_batch=opt["max_batch"], min_batch=opt["min_batch"],
            merge=opt["merge"], query_buckets=opt["query_buckets"],
            score_dtype=opt["score_dtype"], id_offset=id_offset,
            emit="candidates")
        print(f"routed host {opt['host_id']}/{opt['num_hosts']}: streaming"
              f" rows [{id_offset}:{id_offset + engine.n_points}) of "
              f"{n_total} as {opt['num_slabs']} slabs (device budget "
              f"{opt['device_slab_budget'] or 'unbounded'} B)")
    elif routed:
        # shard-local routing: this process owns ONE row slab of the index
        # and serves it independently — no global mesh, global neighbor
        # ids via the engine's id offset, full candidate rows emitted for
        # the front end's cross-host fold. Only the slab is MATERIALIZED
        # (serve/engine.py materialize_slab_engine — the same path the
        # slab handoff's /adopt_slab uses): routed hosts exist so each
        # box holds 1/H of the index, so a whole-file read would defeat
        # the point
        from mpi_cuda_largescaleknn_tpu.serve.engine import (
            materialize_slab_engine,
        )

        if not (0 <= opt["host_id"] < opt["num_hosts"]):
            usage(f"--host-id {opt['host_id']} outside [0, "
                  f"{opt['num_hosts']})")
        engine, id_offset, n_total = materialize_slab_engine(
            opt["in_path"], opt["host_id"], opt["num_hosts"],
            k=opt["k"], shards=opt["shards"], engine=opt["engine"],
            merge=opt["merge"], bucket_size=opt["bucket_size"],
            max_radius=opt["max_radius"], max_batch=opt["max_batch"],
            min_batch=opt["min_batch"],
            query_buckets=opt["query_buckets"],
            score_dtype=opt["score_dtype"])
        print(f"routed host {opt['host_id']}/{opt['num_hosts']}: loaded "
              f"rows [{id_offset}:{id_offset + engine.n_points}) of "
              f"{n_total} from {opt['in_path']}")
    elif streaming:
        # single-process beyond-HBM serving: the index stays in the
        # source file (mmap cold tier) + a bounded host-RAM pool; only
        # --device-slab-budget bytes of slab engines are resident at once
        from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
            StreamingKnnEngine,
        )

        engine = StreamingKnnEngine(
            opt["in_path"], num_slabs=opt["num_slabs"], k=opt["k"],
            device_slab_budget=opt["device_slab_budget"],
            host_pool_slabs=opt["host_pool_slabs"],
            host_pool_bytes=opt["host_pool_bytes"],
            prefetch_depth=opt["prefetch_depth"],
            mesh=get_mesh(opt["shards"]), engine=opt["engine"],
            bucket_size=opt["bucket_size"], max_radius=opt["max_radius"],
            max_batch=opt["max_batch"], min_batch=opt["min_batch"],
            merge=opt["merge"], query_buckets=opt["query_buckets"],
            score_dtype=opt["score_dtype"])
        n_total = engine.n_points
        print(f"tiered index: {n_total} points from {opt['in_path']} in "
              f"{opt['num_slabs']} slabs ({engine.slab_device_bytes} B "
              f"per resident slab; device budget "
              f"{opt['device_slab_budget'] or 'unbounded'} B, host pool "
              + (f"{opt['host_pool_bytes']} B" if opt["host_pool_bytes"]
                 else f"{opt['host_pool_slabs'] or 'unbounded'} slabs")
              + ")")
    else:
        points = read_points(opt["in_path"])
        n_total = len(points)
        print(f"loaded {len(points)} points from {opt['in_path']}")
        engine = ResidentKnnEngine(
            points, opt["k"], mesh=get_mesh(opt["shards"]),
            engine=opt["engine"], bucket_size=opt["bucket_size"],
            max_radius=opt["max_radius"], max_batch=opt["max_batch"],
            min_batch=opt["min_batch"], merge=opt["merge"],
            query_buckets=opt["query_buckets"],
            score_dtype=opt["score_dtype"])

    if opt["num_hosts"] > 1 or routed:
        from mpi_cuda_largescaleknn_tpu.serve.frontend import HostSliceServer

        server = HostSliceServer((opt["host"], opt["port"]), engine,
                                 routing=opt["routing"],
                                 seq_timeout_s=opt["seq_timeout_s"],
                                 wire=opt["wire"],
                                 verbose=opt["verbose"])
        try:
            if opt["warmup"]:
                # pod mode: collective — every host compiles+executes the
                # same bucket sequence in lock-step before any fan-out
                # traffic lands. Routed mode: local warmup, no lock-step.
                info = engine.warmup()
                print(f"warmup compiles done: {info['per_bucket_s']}")
            server.ready = True
            host, port = server.server_address[:2]
            if routed:
                print(f"serving routed slab host {opt['host_id']}/"
                      f"{opt['num_hosts']} on http://{host}:{port} "
                      f"(rows [{id_offset}:{id_offset + engine.n_points}) "
                      f"of {n_total})")
            else:
                print(f"serving pod slice {engine.process_index}/"
                      f"{engine.process_count} on http://{host}:{port} "
                      f"(mesh positions {engine.stats()['my_positions']})")
            server.serve_forever()
        except KeyboardInterrupt:
            print("shutting down")
        finally:
            server.close()
            if opt["timings"]:
                sys.stderr.write(engine.timers.dump() + "\n")
        return 0
    recall_policy = None
    if opt["recall_policy"]:
        from mpi_cuda_largescaleknn_tpu.serve.recall import RecallPolicy

        recall_policy = RecallPolicy.from_file(opt["recall_policy"])
        print(f"recall policy from {opt['recall_policy']}: "
              + ", ".join(f"{p.name} (est {p.recall_estimated:g})"
                          for p in recall_policy.plans))
    server = build_server(
        engine, host=opt["host"], port=opt["port"],
        max_delay_s=opt["max_delay_ms"] / 1e3,
        pipeline_depth=opt["pipeline_depth"],
        max_queue_rows=opt["max_queue_rows"],
        default_timeout_s=opt["timeout_ms"] / 1e3,
        verbose=opt["verbose"],
        recall_policy=recall_policy,
        qcache_rows=opt["qcache_rows"],
        qcache_seed_rows=opt["qcache_seed_rows"])
    try:
        serve_forever(server, warmup=opt["warmup"])
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        server.close()
        if opt["timings"]:
            sys.stderr.write(engine.timers.dump() + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
