"""``tpuknn-unordered`` — the ``cudaMpiKNN_unorderedData`` entry point.

Reference contract (README.md:30-33):
    mpirun -n R ./cudaMpiKNN_unorderedData points.float3 -o distances.float -k 100
TPU form (one process drives the whole mesh; no mpirun):
    python -m mpi_cuda_largescaleknn_tpu.cli.unordered_main points.float3 \
        -o distances.float -k 100 [--shards R]

Byte-compatible ``.float3`` in / ``.float`` out, output in global point order
(unorderedDataVariant.cu:229-237 layout).
"""

from __future__ import annotations

import sys

from mpi_cuda_largescaleknn_tpu.cli.common import parse_args
from mpi_cuda_largescaleknn_tpu.io.reader import read_file_portion
from mpi_cuda_largescaleknn_tpu.io.writer import write_distances, write_indices
from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN
from mpi_cuda_largescaleknn_tpu.obs.trace import profile_trace
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh


def main(argv: list[str] | None = None) -> int:
    cfg, in_path, out_path, extras = parse_args(
        "tpuknn-unordered", sys.argv[1:] if argv is None else argv)

    if extras["num_hosts"] > 1:
        # pod-scale SPMD launch: per-host slab IO + one global mesh
        # (the reference's mpirun contract, see cli/multihost.py)
        from mpi_cuda_largescaleknn_tpu.cli.multihost import (
            run_unordered_multihost,
        )
        return run_unordered_multihost(cfg, in_path, out_path, extras)

    mesh = get_mesh(extras["shards"])
    points, _begin, n_total = read_file_portion(in_path, 0, 1)
    print(f"# mesh of {mesh.shape[AXIS]} device(s): "
          f"got {n_total} points to work on")

    model = UnorderedKNN(cfg, mesh=mesh)
    want_idx = extras["write_indices"] is not None
    with profile_trace(cfg.profile_dir):
        got = model.run(points, return_neighbors=want_idx)
    if want_idx:
        dists, idx = got
        write_indices(extras["write_indices"], idx)
    else:
        dists = got
    write_distances(out_path, dists)
    if extras["selfcheck"] > 0:
        from mpi_cuda_largescaleknn_tpu.obs.selfcheck import verify_sample
        checked = verify_sample(points, dists, cfg.k, extras["selfcheck"],
                                max_radius=cfg.max_radius)
        print(f"selfcheck OK ({checked} samples)")
    print("done all queries...")
    if extras["timings"]:
        import json

        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            measure_exchange_bandwidth,
        )
        report = model.timers.report()
        num_shards = mesh.shape[AXIS]
        if num_shards > 1:
            npad = max(e - b for b, e in slab_bounds(n_total, num_shards))
            report["exchange"] = measure_exchange_bandwidth(
                mesh, npad, bucket_size=cfg.bucket_size, engine=cfg.engine)
        sys.stderr.write(json.dumps(report) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
