"""``tpuknn-partition`` — spatial pre-partitioner for the prepartitioned flow.

The reference's second program consumes "one file per rank, pre-partitioned
in a spatially coherent manner" (README.md:17-23) but the reference provides
no partitioner. This tool produces those files from one raw ``.float3``:

    python -m mpi_cuda_largescaleknn_tpu.cli.partition_main points.float3 \
        -n 8 -o parts/run

writes ``parts/run_%06d.float3`` (near-equal sizes, Morton-coherent) and
``parts/run.txt`` (the file list ``prepartitioned_main`` takes as input).
Out-of-core: three sequential streaming passes in native C++ (numpy fallback
off-toolchain).
"""

from __future__ import annotations

import sys

from mpi_cuda_largescaleknn_tpu.io.partition_file import partition_float3_file


def usage(err: str) -> "NoReturn":  # noqa: F821
    sys.stderr.write(f"Error: {err}\n\n"
                     "tpuknn-partition <input.float3> -n <numParts> "
                     "-o <outPrefix> [--bits B]\n")
    sys.exit(1)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    in_path, out_prefix, num_parts, bits = "", "", 0, 7
    i = 0
    try:
        while i < len(argv):
            a = argv[i]
            if a == "-o":
                i += 1; out_prefix = argv[i]
            elif a == "-n":
                i += 1; num_parts = int(argv[i])
            elif a == "--bits":
                i += 1; bits = int(argv[i])
            elif not a.startswith("-"):
                in_path = a
            else:
                usage(f"unknown cmdline arg '{a}'")
            i += 1
    except (IndexError, ValueError):
        usage(f"invalid or missing value for '{argv[i - 1] if i else ''}'")
    if not in_path:
        usage("no input file name specified")
    if not out_prefix:
        usage("no output prefix specified")
    if num_parts < 1:
        usage("no part count specified, or invalid -n value")
    if not 1 <= bits <= 10:
        usage(f"--bits must be in [1, 10], got {bits}")

    counts = partition_float3_file(in_path, num_parts, out_prefix, bits)
    for r, c in enumerate(counts):
        print(f"#{r}: {c} points -> {out_prefix}_{r:06d}.float3")
    print(f"file list -> {out_prefix}.txt")
    return 0


if __name__ == "__main__":
    sys.exit(main())
