"""Run configuration.

The public configuration surface of the reference is its 5-flag CLI, identical
in both programs (unorderedDataVariant.cu:114-135, prePartitionedDataVariant.cu:185-206):
positional input path, ``-o`` output, ``-k`` int (required >= 1), ``-r`` float
max search radius (default +inf), ``-g`` int GPU-affinity modulus.

``KnnConfig`` carries that surface plus the TPU-side knobs the reference has no
analogue for (tile sizes, engine selection, mesh size, checkpointing).
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class KnnConfig:
    # --- reference-parity knobs -------------------------------------------
    k: int = 0                       # `-k`; must be >= 1 to run
    max_radius: float = math.inf     # `-r`; candidates beyond this never enter
    device_affinity: int = 0         # `-g`; kept for CLI parity (no-op on TPU,
                                     # the runtime owns device binding)

    # --- TPU-side knobs ----------------------------------------------------
    engine: str = "auto"             # "auto" (pallas_tiled on TPU, tiled
                                     # elsewhere) | "tiled" | "pallas_tiled"
                                     # | "bruteforce" | "tree" | "pallas"
    query_tile: int = 2048           # queries processed per inner tile
    point_tile: int = 2048           # tree points per inner tile
    bucket_size: int = 0             # tiled engines: points per spatial
                                     # bucket; 0 = auto per engine from
                                     # measured data (parallel/ring.py
                                     # resolve_bucket_size: twin 128,
                                     # pallas 256 — round-5 tune sweep)
    point_group: int = 0             # tiled self-join drivers: coarsen the
                                     # point side by this power-of-two factor
                                     # (fine query buckets -> tighter prune
                                     # radius; wide resident tiles -> DMA and
                                     # fold efficiency; docs/TUNING.md).
                                     # 0 = auto per engine (_effective_group:
                                     # pallas G2 per the tune sweep, else 1)
    num_shards: int = 1              # size of the 1-D mesh axis
    query_chunk: int = 0             # >0: stream queries in chunks of this
                                     # many rows/device (bounds heap memory
                                     # to chunk*k per device — the k=100 /
                                     # beyond-HBM regime)
    merge: str = "host"              # chunked runs: cross-shard top-k merge
                                     # placement — "host" (the ring),
                                     # "device" (replicate-traverse-merge,
                                     # reduction in-program on the global
                                     # mesh axis), "auto" (device on
                                     # power-of-two meshes)
    score_dtype: str = "f32"         # distance scoring: "f32" = exact
                                     # elementwise (VPU), "bf16" =
                                     # matmul-form MXU score + exact f32
                                     # rescore of the survivors
                                     # (ops/distance.py, docs/TUNING.md
                                     # "Distance kernel")
    profile_dir: str | None = None   # jax.profiler trace output
    checkpoint_dir: str | None = None  # ring-state checkpoint/resume
    checkpoint_every: int = 1        # rounds between snapshots
    verbose: bool = False

    def validate(self) -> None:
        if self.k < 1:
            raise ValueError("no k specified, or invalid k value")
        if self.engine not in ("auto", "tiled", "pallas_tiled", "bruteforce",
                               "tree", "pallas"):
            raise ValueError(f"unknown engine '{self.engine}'")
        if self.merge not in ("host", "device", "auto"):
            raise ValueError(f"unknown merge mode '{self.merge}' "
                             "(expected host | device | auto)")
        if self.score_dtype not in ("f32", "bf16"):
            raise ValueError(f"unknown score_dtype '{self.score_dtype}' "
                             "(expected f32 | bf16)")
        pg = self.point_group
        if pg < 0 or (pg and (pg & (pg - 1)) != 0):
            raise ValueError(
                "point_group must be 0 (auto) or a power of two >= 1, "
                f"got {pg}")
