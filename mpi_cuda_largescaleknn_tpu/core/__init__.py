from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig  # noqa: F401
from mpi_cuda_largescaleknn_tpu.core.types import (  # noqa: F401
    PAD_SENTINEL,
    Aabb,
    CandidateState,
    aabb_box_distance,
    aabb_of_points,
    pad_points,
)
