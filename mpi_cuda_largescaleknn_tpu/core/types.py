"""Core array types.

Design notes vs. the reference:

- Points are ``f32[N, 3]`` arrays (the reference's ``float3*`` device buffers).
- The per-query candidate list is SoA ``(f32[N, k] dist2, i32[N, k] idx)``
  kept sorted ascending by dist2, instead of the reference's packed
  ``uint64_t`` (dist-bits << 32 | index) max-heap
  (``cukd::FlexHeapCandidateList``, used at unorderedDataVariant.cu:84-85).
  Semantics preserved exactly — see ops/candidates.py.
- XLA needs static shapes, so every shard is padded to a uniform size with
  ``PAD_SENTINEL`` coordinates. The reference already relies on uniform
  padding in the prepartitioned variant (buffers sized to
  ``maxNumPointsAnybodyHas``, prePartitionedDataVariant.cu:251-266) and on a
  ``N+1`` slack alloc in the unordered one (unorderedDataVariant.cu:156-158);
  we generalize: sentinel points sit at distance ~1e30 from any real point, so
  their squared distance overflows f32 to +inf and they can never displace a
  real candidate (nor a cutoff-radius slot) in the heap.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# Far-away-but-finite coordinate for padding points. (1e30)^2 overflows f32 to
# +inf, so any real-vs-sentinel distance is +inf (never inserted), while
# finite-minus-finite subtraction avoids the inf-inf => nan trap.
PAD_SENTINEL = 1.0e30


class CandidateState(NamedTuple):
    """Persistent per-query top-k accumulator (one row per query).

    ``dist2`` ascending per row; empty slots hold ``max_radius**2`` (+inf when
    no ``-r`` given) and idx -1 — mirroring FlexHeapCandidateList's
    initialization with its cutoff radius and its "adopt existing buffer"
    reopening with cutoff -1 (unorderedDataVariant.cu:84-85, :97).
    """

    dist2: jnp.ndarray  # f32[num_queries, k]
    idx: jnp.ndarray    # i32[num_queries, k]


class Aabb(NamedTuple):
    """Axis-aligned bounding box = the reference's ``cukd::box_t<float3>``
    (6 contiguous floats, prePartitionedDataVariant.cu:290-291)."""

    lower: jnp.ndarray  # f32[3]
    upper: jnp.ndarray  # f32[3]


def aabb_of_points(points: jnp.ndarray, valid_mask: jnp.ndarray | None = None) -> Aabb:
    """Bounds of the real (non-sentinel) points.

    Reference computes this on the host over its own points
    (prePartitionedDataVariant.cu:230-232). Empty set => lower=+inf, upper=-inf
    (the ``setEmpty()`` convention).
    """
    if valid_mask is None:
        valid_mask = points[:, 0] < PAD_SENTINEL / 2
    big = jnp.float32(jnp.inf)
    lo = jnp.min(jnp.where(valid_mask[:, None], points, big), axis=0)
    hi = jnp.max(jnp.where(valid_mask[:, None], points, -big), axis=0)
    return Aabb(lo, hi)


def aabb_box_distance(a_lower, a_upper, b_lower, b_upper) -> jnp.ndarray:
    """Min Euclidean distance between two AABBs.

    Same formula as the reference's ``computeDistance``
    (prePartitionedDataVariant.cu:150-155):
    per-component ``max(0, max(a.lo-b.hi, b.lo-a.hi))``, then the norm.
    Empty boxes (lo=+inf/hi=-inf) give +inf distance, i.e. always prunable.
    """
    diff = jnp.maximum(0.0, jnp.maximum(a_lower - b_upper, b_lower - a_upper))
    d2 = jnp.sum(diff * diff, axis=-1)
    # an empty box produces inf-inf=nan in the subtraction; treat as +inf
    return jnp.where(jnp.isnan(d2), jnp.inf, jnp.sqrt(d2))


def pad_points(points, padded_size: int):
    """Pad ``f32[N,D]`` to ``f32[padded_size,D]`` with PAD_SENTINEL rows.

    Returns (padded_points, valid_mask[padded_size]).
    """
    n, dim = points.shape
    assert padded_size >= n, (padded_size, n)
    pad = jnp.full((padded_size - n, dim), PAD_SENTINEL, dtype=jnp.float32)
    out = jnp.concatenate([jnp.asarray(points, jnp.float32), pad], axis=0)
    mask = jnp.arange(padded_size) < n
    return out, mask
