"""The pre-partitioned pipeline: one spatially-coherent shard per device.

End-to-end equivalent of ``cudaMpiKNN_prePartitionedData``'s main()
(prePartitionedDataVariant.cu:176-389): each device owns one input partition
(the reference: one file per rank, asserted at :215-216), shards are padded to
the global max count (:251-266), and the bounds-pruned early-exit engine
refines every partition's heaps until no device can improve. Results come
back per-partition (the reference writes one ``prefix_%06d.float`` per rank,
:380-385).
"""

from __future__ import annotations

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.sharding import (
    check_neighbor_id_capacity,
    pad_and_flatten,
    trim_per_shard,
)
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.parallel.demand import (
    demand_knn,
    demand_knn_stepwise,
)
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh


class PrePartitionedKNN:
    """kNN distances for pre-partitioned point sets over a 1-D mesh."""

    def __init__(self, config: KnnConfig, mesh=None):
        config.validate()
        self.config = config
        self.mesh = mesh if mesh is not None else get_mesh(
            config.num_shards if config.num_shards > 0 else None)
        self.timers = PhaseTimers()
        self.last_stats: dict | None = None

    def run(self, partitions: list[np.ndarray],
            return_neighbors: bool = False):
        """partitions: one f32[Ni,3] array per device -> per-partition f32[Ni]
        k-th-NN distances (global over the union of all partitions).

        With ``return_neighbors`` also returns per-partition i32[Ni, k]
        neighbor ids, globally numbered by partition concatenation order
        (-1 where fewer than k neighbors exist).
        """
        cfg = self.config
        num_shards = self.mesh.shape[AXIS]
        if return_neighbors:
            check_neighbor_id_capacity(sum(len(p) for p in partitions))
        if len(partitions) != num_shards:
            # the reference's "number of input files does not match MPI size"
            # (prePartitionedDataVariant.cu:215-216)
            raise ValueError(
                f"number of input partitions ({len(partitions)}) does not "
                f"match mesh size ({num_shards})")

        with self.timers.phase("pad"):
            sizes = np.cumsum([0] + [len(p) for p in partitions])
            flat, ids, counts, npad = pad_and_flatten(
                partitions, id_bases=list(sizes[:-1]))

        with self.timers.phase("demand_ring"):
            kwargs = ({"checkpoint_dir": cfg.checkpoint_dir,
                       "checkpoint_every": cfg.checkpoint_every}
                      if cfg.checkpoint_dir else {})
            if cfg.query_chunk > 0:
                from mpi_cuda_largescaleknn_tpu.parallel.demand import (
                    demand_knn_chunked,
                )
                run_fn = demand_knn_chunked
                kwargs["chunk_rows"] = cfg.query_chunk
                kwargs["return_candidates"] = return_neighbors
            else:
                run_fn = (demand_knn_stepwise if cfg.checkpoint_dir
                          else demand_knn)
            # chunked drivers coarsen only the resident side (no self-join
            # correspondence for warm start/skip — see ring_knn_chunked)
            kwargs["point_group"] = cfg.point_group
            dists, cands, stats = run_fn(
                flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                engine=cfg.engine, query_tile=cfg.query_tile,
                point_tile=cfg.point_tile, bucket_size=cfg.bucket_size,
                return_stats=True, **kwargs)
            dists = np.asarray(dists)
            rounds = np.asarray(stats["rounds"]).reshape(-1)
            self.last_stats = {
                # chunked runs report per-chunk round counts; the scalar
                # "rounds" stays comparable across drivers as the max
                # (0 when a resumed run had nothing left to do)
                "rounds": int(rounds.max()) if rounds.size else 0,
                "kernels_run": np.asarray(stats["kernels_run"]).tolist(),
                # direction-rotations executed per device (x shard_bytes =
                # exchange bytes actually moved; the per-direction gating in
                # parallel/demand.py stops paying for a direction once no
                # device needs future deliveries from it)
                "rotations_run": np.asarray(
                    stats.get("rotations_run", [])).tolist(),
            }
            if cfg.query_chunk > 0:
                self.last_stats["rounds_per_chunk"] = rounds.tolist()

        with self.timers.phase("extract"):
            out = trim_per_shard(dists, counts, npad)
            if return_neighbors:
                idx = trim_per_shard(np.asarray(cands.idx), counts, npad)
                return out, idx
            return out
