"""Host-side shard layout helpers shared by both pipelines.

Slab semantics follow the reference's ``readFilePortion`` exactly
(unorderedDataVariant.cu:41-63): shard r of R owns rows
``[N*r/R, N*(r+1)/R)`` of the global array (sizes differ by at most one), so
concatenating per-shard results in rank order reproduces the reference's
single-output-file byte layout (unorderedDataVariant.cu:229-237).
"""

from __future__ import annotations

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL


def check_neighbor_id_capacity(n_total: int) -> None:
    """Neighbor-id output carries global ids as int32: ids 0..n-1 fit
    exactly while n <= 2^31 (max id INT32_MAX). Beyond that the wrap in
    ``pad_and_flatten`` keeps the distance path correct but makes ids
    ambiguous — refuse rather than emit wrong identities."""
    if n_total > 2**31:
        raise ValueError("neighbor ids are int32: datasets beyond 2^31 "
                         "points must use the distance-only path")


def slab_bounds(num_total: int, num_shards: int) -> list[tuple[int, int]]:
    return [(num_total * r // num_shards, num_total * (r + 1) // num_shards)
            for r in range(num_shards)]


def pad_and_flatten(shards: list[np.ndarray], id_bases: list[int] | None = None,
                    pad_to: int | None = None, dim: int | None = None):
    """Pack per-shard point arrays into the engines' shard-major layout.

    Returns (points f32[R*Npad,3], ids i32[R*Npad], counts [R], Npad) where
    Npad = max shard size (the prepartitioned variant's pad-to-max,
    prePartitionedDataVariant.cu:251-266), padding rows = PAD_SENTINEL / id -1.
    ``id_bases[r]`` is shard r's global index offset (slab begin).

    Beyond 2^31 total points the global id no longer fits int32 — a naive
    base+arange would wrap NEGATIVE and the engines would treat real points
    as padding (silent data loss). The distance path only ever consults the
    SIGN of an id (valid vs padding; merges order by distance alone), so
    ids wrap modulo 2^31 and stay non-negative; neighbor-id output at that
    scale is refused upstream (``--write-indices`` documents the int32
    limit).
    """
    num_shards = len(shards)
    counts = [len(s) for s in shards]
    npad = max(max(counts), 1) if pad_to is None else pad_to
    assert npad >= max(counts)
    if dim is None:
        # derive D from the data; callers with possibly ALL-empty shards
        # (pod hosts owning only padding slabs) must pass dim explicitly
        dims = {np.asarray(s).shape[-1] for s in shards if len(s)}
        dim = dims.pop() if len(dims) == 1 else 3
    points = np.full((num_shards * npad, dim), PAD_SENTINEL, np.float32)
    ids = np.full(num_shards * npad, -1, np.int32)
    for r, s in enumerate(shards):
        points[r * npad:r * npad + counts[r]] = np.asarray(s, np.float32)
        base = id_bases[r] if id_bases is not None else 0
        gids = (base + np.arange(counts[r], dtype=np.int64)) % (2**31)
        ids[r * npad:r * npad + counts[r]] = gids.astype(np.int32)
    return points, ids, counts, npad


def slab_aabbs(points: np.ndarray, bounds: list[tuple[int, int]]) -> list[dict]:
    """Per-slab bounding boxes + point counts, JSON-ready: the serving
    engine computes these ONCE at index upload and exposes them on /stats,
    so the pod front end can assemble its routing bounds table
    (serve/frontend.py ``PodBoundsTable``) without touching the device.
    An empty slab carries the ``lo/hi = None`` sentinel (count 0) — the
    router must treat it as unreachable, never as a zero-size box at the
    origin."""
    out = []
    for b, e in bounds:
        s = np.asarray(points[b:e], np.float32)
        if len(s) == 0:
            out.append({"lo": None, "hi": None, "count": 0})
        else:
            out.append({"lo": [float(x) for x in s.min(axis=0)],
                        "hi": [float(x) for x in s.max(axis=0)],
                        "count": int(len(s))})
    return out


def trim_per_shard(flat: np.ndarray, counts: list[int], npad: int) -> list[np.ndarray]:
    """Undo the padding: per-shard arrays of true length."""
    return [np.asarray(flat[r * npad:r * npad + c]) for r, c in enumerate(counts)]
