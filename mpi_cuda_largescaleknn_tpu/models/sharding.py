"""Host-side shard layout helpers shared by both pipelines.

Slab semantics follow the reference's ``readFilePortion`` exactly
(unorderedDataVariant.cu:41-63): shard r of R owns rows
``[N*r/R, N*(r+1)/R)`` of the global array (sizes differ by at most one), so
concatenating per-shard results in rank order reproduces the reference's
single-output-file byte layout (unorderedDataVariant.cu:229-237).
"""

from __future__ import annotations

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL


def slab_bounds(num_total: int, num_shards: int) -> list[tuple[int, int]]:
    return [(num_total * r // num_shards, num_total * (r + 1) // num_shards)
            for r in range(num_shards)]


def pad_and_flatten(shards: list[np.ndarray], id_bases: list[int] | None = None,
                    pad_to: int | None = None):
    """Pack per-shard point arrays into the engines' shard-major layout.

    Returns (points f32[R*Npad,3], ids i32[R*Npad], counts [R], Npad) where
    Npad = max shard size (the prepartitioned variant's pad-to-max,
    prePartitionedDataVariant.cu:251-266), padding rows = PAD_SENTINEL / id -1.
    ``id_bases[r]`` is shard r's global index offset (slab begin).
    """
    num_shards = len(shards)
    counts = [len(s) for s in shards]
    npad = max(max(counts), 1) if pad_to is None else pad_to
    assert npad >= max(counts)
    points = np.full((num_shards * npad, 3), PAD_SENTINEL, np.float32)
    ids = np.full(num_shards * npad, -1, np.int32)
    for r, s in enumerate(shards):
        points[r * npad:r * npad + counts[r]] = np.asarray(s, np.float32)
        base = id_bases[r] if id_bases is not None else 0
        ids[r * npad:r * npad + counts[r]] = base + np.arange(counts[r], dtype=np.int32)
    return points, ids, counts, npad


def trim_per_shard(flat: np.ndarray, counts: list[int], npad: int) -> list[np.ndarray]:
    """Undo the padding: per-shard arrays of true length."""
    return [np.asarray(flat[r * npad:r * npad + c]) for r, c in enumerate(counts)]
