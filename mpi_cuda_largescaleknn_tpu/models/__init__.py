from mpi_cuda_largescaleknn_tpu.models.unordered import UnorderedKNN  # noqa: F401
from mpi_cuda_largescaleknn_tpu.models.prepartitioned import PrePartitionedKNN  # noqa: F401
