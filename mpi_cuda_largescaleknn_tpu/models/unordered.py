"""The unordered-data pipeline (flagship): random point order, ring exchange.

End-to-end equivalent of ``cudaMpiKNN_unorderedData``'s main()
(unorderedDataVariant.cu:105-239): slab-split the global point set, run the
R-round ring with stationary queries + persistent heaps, extract per-point
k-th-NN distances, and return them in global point order (= concatenation of
slabs in rank order, matching the reference's barrier-fenced rank-serialized
append to one output file, :229-237).
"""

from __future__ import annotations

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.sharding import (
    check_neighbor_id_capacity,
    pad_and_flatten,
    slab_bounds,
    trim_per_shard,
)
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
from mpi_cuda_largescaleknn_tpu.parallel.ring import (
    ring_knn,
    ring_knn_chunked,
    ring_knn_stepwise,
)


class UnorderedKNN:
    """kNN distances for an unordered global point set over a 1-D mesh."""

    def __init__(self, config: KnnConfig, mesh=None):
        config.validate()
        self.config = config
        self.mesh = mesh if mesh is not None else get_mesh(
            config.num_shards if config.num_shards > 0 else None)
        self.timers = PhaseTimers()
        self.last_stats: dict | None = None  # executed-work stats of the
        # most recent run (pair_evals etc., parallel/ring.py _ring_stats)

    def run(self, points: np.ndarray, return_neighbors: bool = False):
        """points f32[N,3] -> f32[N] distance of each point to its k-th NN.

        With ``return_neighbors`` also returns i32[N, k] global neighbor ids
        (ascending by distance; -1 where fewer than k neighbors exist, e.g.
        under ``-r``) — a capability the reference computes but discards
        (the packed u64 entries at unorderedDataVariant.cu:163-168 hold ids
        that extractFinalResult never reads). Ids are int32: datasets beyond
        2^31 points need the distance-only path.
        """
        cfg = self.config
        num_shards = self.mesh.shape[AXIS]
        n_total = len(points)
        if return_neighbors:
            check_neighbor_id_capacity(n_total)

        with self.timers.phase("shard_and_pad"):
            bounds = slab_bounds(n_total, num_shards)
            shards = [points[b:e] for b, e in bounds]
            flat, ids, counts, npad = pad_and_flatten(
                shards, id_bases=[b for b, _ in bounds],
                dim=int(np.asarray(points).shape[-1]))

        cands = None
        # tree bytes x rotations: the bidirectional sweep rotates two
        # copies per device for ring_total_rounds-1 rounds (the final
        # round is fold-only); the chunked path repeats that per chunk
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            ring_total_rounds,
        )
        n_chunks = (max(1, -(-npad // cfg.query_chunk))
                    if cfg.query_chunk > 0 else 1)
        rotations = 2 * (ring_total_rounds(num_shards) - 1)
        with self.timers.phase("ring", bytes_moved=(
                num_shards * npad * 12 * rotations * n_chunks)):
            if cfg.query_chunk > 0:
                got = ring_knn_chunked(
                    flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                    engine=cfg.engine, query_tile=cfg.query_tile,
                    point_tile=cfg.point_tile, bucket_size=cfg.bucket_size,
                    point_group=cfg.point_group,
                    chunk_rows=cfg.query_chunk, merge=cfg.merge,
                    score_dtype=cfg.score_dtype,
                    checkpoint_dir=cfg.checkpoint_dir,
                    checkpoint_every=cfg.checkpoint_every,
                    return_candidates=return_neighbors, return_stats=True)
            elif cfg.checkpoint_dir:
                got = ring_knn_stepwise(
                    flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                    engine=cfg.engine, query_tile=cfg.query_tile,
                    point_tile=cfg.point_tile, bucket_size=cfg.bucket_size,
                    point_group=cfg.point_group,
                    score_dtype=cfg.score_dtype,
                    checkpoint_dir=cfg.checkpoint_dir,
                    checkpoint_every=cfg.checkpoint_every,
                    return_candidates=return_neighbors, return_stats=True)
            else:
                got = ring_knn(
                    flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                    engine=cfg.engine, query_tile=cfg.query_tile,
                    point_tile=cfg.point_tile, bucket_size=cfg.bucket_size,
                    point_group=cfg.point_group,
                    score_dtype=cfg.score_dtype,
                    return_candidates=return_neighbors, return_stats=True)
            if return_neighbors:
                dists, cands, self.last_stats = got
            else:
                dists, self.last_stats = got
            dists = np.asarray(dists)

        with self.timers.phase("extract"):
            out = np.concatenate(trim_per_shard(dists, counts, npad))
            if return_neighbors:
                idx = np.concatenate(
                    trim_per_shard(np.asarray(cands.idx), counts, npad))
                return out, idx
        return out
