"""The unordered-data pipeline (flagship): random point order, ring exchange.

End-to-end equivalent of ``cudaMpiKNN_unorderedData``'s main()
(unorderedDataVariant.cu:105-239): slab-split the global point set, run the
R-round ring with stationary queries + persistent heaps, extract per-point
k-th-NN distances, and return them in global point order (= concatenation of
slabs in rank order, matching the reference's barrier-fenced rank-serialized
append to one output file, :229-237).
"""

from __future__ import annotations

import numpy as np

from mpi_cuda_largescaleknn_tpu.core.config import KnnConfig
from mpi_cuda_largescaleknn_tpu.models.sharding import (
    pad_and_flatten,
    slab_bounds,
    trim_per_shard,
)
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
from mpi_cuda_largescaleknn_tpu.parallel.ring import (
    ring_knn,
    ring_knn_stepwise,
)


class UnorderedKNN:
    """kNN distances for an unordered global point set over a 1-D mesh."""

    def __init__(self, config: KnnConfig, mesh=None):
        config.validate()
        self.config = config
        self.mesh = mesh if mesh is not None else get_mesh(
            config.num_shards if config.num_shards > 0 else None)
        self.timers = PhaseTimers()

    def run(self, points: np.ndarray) -> np.ndarray:
        """points f32[N,3] -> f32[N] distance of each point to its k-th NN."""
        cfg = self.config
        num_shards = self.mesh.shape[AXIS]
        n_total = len(points)

        with self.timers.phase("shard_and_pad"):
            bounds = slab_bounds(n_total, num_shards)
            shards = [points[b:e] for b, e in bounds]
            flat, ids, counts, npad = pad_and_flatten(
                shards, id_bases=[b for b, _ in bounds])

        with self.timers.phase("ring", bytes_moved=(
                num_shards * npad * 12 * num_shards)):  # tree bytes x rounds
            if cfg.checkpoint_dir:
                dists = ring_knn_stepwise(
                    flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                    engine=cfg.engine, query_tile=cfg.query_tile,
                    point_tile=cfg.point_tile, bucket_size=cfg.bucket_size,
                    checkpoint_dir=cfg.checkpoint_dir,
                    checkpoint_every=cfg.checkpoint_every)
            else:
                dists = ring_knn(
                    flat, ids, cfg.k, self.mesh, max_radius=cfg.max_radius,
                    engine=cfg.engine, query_tile=cfg.query_tile,
                    point_tile=cfg.point_tile, bucket_size=cfg.bucket_size)
            dists = np.asarray(dists)

        with self.timers.phase("extract"):
            out = np.concatenate(trim_per_shard(dists, counts, npad))
        return out
