"""Resident query engine: the index lives on the mesh, queries fly through.

Build once: the point set is slab-sharded over the 1-D device mesh and
median-split into spatial buckets per shard (the same partition the ring
drivers hoist out of their jits — ``partition_sharded``). Query forever: an
incoming batch is padded to its shape bucket, replicated to every device,
traversed against each device's resident buckets (the exact nearest-first
prune of ops/tiled.py — each shard returns its local top-k), and the
R-way partial candidates are reduced to the global top-k either on the
host or — the default on power-of-two meshes — inside the SPMD program
itself (``merge="device"``): a reduce-scatter over candidate states (one
``all_to_all`` + a width-R*k ``top_k``; the log2(R) ``ppermute`` tree of
ops/candidates.py ``tree_merge_candidates`` is the all-reduce sibling —
parallel/ring.py ``device_merge_final``), after which each
device emits its 1/R slice of the FINAL answer, so ``complete`` fetches a
single [Q, k] result instead of R partials (k*R x fewer bytes over the
host link) and the numpy merge leaves the critical path entirely. The two
placements are bit-identical, ties included — the tree's operand ordering
reproduces the host's stable shard-major sort (TPU-KNN, arXiv:2206.14286:
keep the top-k reduction on-device as regular VPU work; EQuARX,
arXiv:2506.17615: cross-device reductions belong inside the program).

Shape discipline is the whole point (TPU-KNN, arXiv:2206.14286: peak
throughput needs large *fixed* shapes): query programs are AOT-compiled
(``jit(...).lower(...).compile()``) per power-of-two batch bucket, so a
served shape can NEVER silently retrace — an unexpected shape raises, and
``compile_count`` is an honest counter the recompile-freedom tests assert
on. ``auto`` resolves to the Pallas kernel on TPU / the XLA twin elsewhere
(parallel/ring.py resolve_engine); a runtime Pallas failure degrades to the
twin via ``degrade()`` (driven by serve/admission.py).

Pipelining: ``query`` is split into an async ``dispatch`` (stage + pad +
queue the AOT executable call on a single launch thread, so dispatch
returns right after staging even where PJRT executes synchronously) and a
blocking ``complete`` (resolve the launch future, fetch, R-way merge,
slice) so the batcher can keep batch t+1's device traversal in flight while
batch t's host merge runs — the serving-side analogue of the ring's
communication/compute overlap.

Pod mode: when the mesh spans processes (``jax.process_count() > 1``, the
batch CLIs' ``jax.distributed`` lifecycle), the SAME engine runs on every
host over the ONE global mesh — each host uploads only its addressable
index slabs, stages the (front-end-replicated) batch from its own copy,
and fetches only its 1/R row slices of the pod-final answer
(``complete_slices``; requires ``merge="device"`` — host merge would need
partials no process can address). The query program is byte-identical to
the single-host one; only the axis the reduction collectives ride grows
(serve/frontend.py).

Query locality: the whole speedup of the tiled traversal is the per-bucket
prune radius (ops/tiled.py ``_worst2``) — and a served batch of scattered
user queries wrapped in ONE bucket widens that radius to the max over the
batch, degrading toward brute force. So ``dispatch`` first sorts each batch
by 3-D Morton code over the index bounding box (utils/math.py; pads sort
last) and the query program traverses ``query_buckets`` contiguous slices
of the sorted batch, each with its own in-program AABB and radius — sorted
order makes contiguous slices spatially tight, so the prune actually bites.
``complete`` un-permutes the merged rows, so callers never observe the
sort. The traversal runs with the canonical (dist2, id) tie order
(ops/candidates.py ``merge_candidates(canonical=True)``), which makes the
result bit-identical across bucket geometries — ``query_buckets=1``
(unsorted, the old behavior) and any B produce the same bytes, ties
included (tests/test_query_locality.py). The tile counters the traversal
already carries are surfaced as ``tiles_executed`` / ``tiles_skipped``
engine counters (and /metrics), so the locality win is a number:
``tools/serve_smoke.py --locality-bench``.
"""

from __future__ import annotations

import math
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL
from mpi_cuda_largescaleknn_tpu.models.sharding import (
    pad_and_flatten,
    slab_aabbs,
    slab_bounds,
)
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.utils.math import next_pow2


class UnservableShapeError(ValueError):
    """A batch no shape bucket covers reached the engine (the admission
    layer should have rejected or split it)."""


class ExecutableCache:
    """Shared AOT-executable cache keyed by resident-set SHAPE CLASS.

    A compiled query program is specialized to the SHAPES of its resident
    operands, not their values — so engines whose resident arrays share a
    shape class (same per-shard padding, bucket geometry, dim, dtype; the
    tiered slab pool pads every slab engine to a common class exactly for
    this) can reuse ONE executable. The pool hands every slab engine the
    same cache; an eviction/re-promotion cycle then never recompiles, and
    ``compiles`` is the pool-wide recompile-freedom counter the streaming
    tests assert on (serve/slabpool.py)."""

    def __init__(self):
        self._cv = threading.Condition()
        # keys carry every program-identity component (engine, merge,
        # qpad, query buckets, score dtype, emit, k, radius, tie order,
        # dim) PLUS the resident arg shapes/dtypes — all reads and writes
        # under the lock (promotion thread vs stall-path builders)
        self._cache: guarded_by("_cv") = {}
        #: keys some caller is currently compiling — a concurrent miss
        #: WAITS for the build instead of paying a duplicate
        #: seconds-long XLA compile (and double-counting ``compiles``,
        #: the recompile-freedom number the tests pin)
        self._building: guarded_by("_cv") = set()
        self.compiles: guarded_by("_cv") = 0
        self.hits: guarded_by("_cv") = 0

    def get(self, key):
        """Return the cached executable, or None with the key CLAIMED
        for building — the caller then MUST ``put`` (or ``abort`` on
        failure) so parked waiters wake."""
        with self._cv:
            while True:
                exe = self._cache.get(key)
                if exe is not None:
                    self.hits += 1
                    return exe
                if key in self._building:
                    self._cv.wait(0.05)
                    continue
                self._building.add(key)
                return None

    def put(self, key, exe) -> None:
        with self._cv:
            self._cache.setdefault(key, exe)
            self._building.discard(key)
            self.compiles += 1
            self._cv.notify_all()

    def abort(self, key) -> None:
        """Release a claimed key after a failed compile (waiters retry)."""
        with self._cv:
            self._building.discard(key)
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {"programs": len(self._cache),
                    "compiles": self.compiles, "hits": self.hits,
                    "shapes": sorted({k[2] for k in self._cache})}


class _InFlightBatch:
    """A dispatched-but-uncompleted engine call (``dispatch`` -> ``complete``).

    ``fut`` resolves to the executable's result triple on the engine's
    launch thread — (d2, idx, tiles) per-shard partials under
    ``merge="host"``, the final (dists, idx, tiles) under
    ``merge="device"``; ``merge_mode`` records which, so ``complete``
    demuxes the right way. ``queries`` retains the ORIGINAL (unsorted) host
    batch so a completion-time failure (async Pallas errors surface at
    fetch, not at launch) can be replayed on the degraded twin — which
    replays under the engine's CURRENT merge mode, the twin contract being
    merge-placement-independent. ``engine_name`` records which engine
    DISPATCHED it — after a mid-stream degradation, stale handles are
    distinguishable from twin failures. ``perm`` is the Morton admission
    sort (None when sorting is off): row i of the staged batch is
    ``queries[perm[i]]``, so ``complete`` scatters results back through it.
    ``tiles_possible`` is the program's static tile-schedule ceiling — the
    skipped-tile counter's denominator. ``plan`` is the recall-SLO
    execution plan the batch was dispatched under (serve/recall.py;
    None = exact) — retained so a degradation replay re-runs the SAME
    plan and the completion layers can label the batch's tier.
    """

    __slots__ = ("queries", "n", "qpad", "engine_name", "merge_mode",
                 "fut", "t0", "perm", "tiles_possible", "plan")

    def __init__(self, queries, n, qpad, engine_name, merge_mode, fut, t0,
                 perm=None, tiles_possible=0, plan=None):
        self.queries = queries
        self.n = n
        self.qpad = qpad
        self.engine_name = engine_name
        self.merge_mode = merge_mode
        self.fut = fut
        self.t0 = t0
        self.perm = perm
        self.tiles_possible = tiles_possible
        self.plan = plan


class ResidentKnnEngine:
    """One resident sharded index + a family of fixed-shape query programs.

    Thread-compatibility: ``query`` is serialized by an internal lock — the
    micro-batcher is the intended (single) caller, but a direct caller must
    not corrupt the stats either.
    """

    def __init__(self, points: np.ndarray, k: int, *, mesh=None,
                 engine: str = "auto", bucket_size: int = 0,
                 max_radius: float = math.inf, max_batch: int = 1024,
                 min_batch: int = 8, merge: str = "auto",
                 query_buckets: int = 0, score_dtype: str = "f32",
                 id_offset: int = 0, emit: str = "final",
                 timers: PhaseTimers | None = None,
                 executable_cache: ExecutableCache | None = None,
                 pad_shard_rows: int = 0):
        import jax

        from mpi_cuda_largescaleknn_tpu.ops.distance import (
            mxu_min_dim,
            validate_score_dtype,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            resolve_bucket_size,
            resolve_engine,
            resolve_merge,
            resolve_query_buckets,
        )

        points = np.asarray(points, np.float32)
        if points.ndim != 2 or points.shape[1] < 1:
            raise ValueError(f"points must be [N, D], got {points.shape}")
        if k < 1:
            raise ValueError("k must be >= 1")
        min_batch = max(8, next_pow2(min_batch))
        max_batch = next_pow2(max_batch)

        if emit not in ("final", "candidates"):
            raise ValueError(f"emit must be 'final' or 'candidates', "
                             f"got {emit!r}")
        self.k = int(k)
        self.n_points = len(points)
        #: global row index of this engine's first point: a routed pod host
        #: (serve/frontend.py --routing bounds) serves one slab of a larger
        #: index, and its neighbor ids must be GLOBAL rows — the canonical
        #: (dist2, id) tie order then matches the replicate-everything pod
        #: bit for bit, because slab sharding keeps ids ascending by host
        self.id_offset = int(id_offset)
        #: what completions carry: "final" = the public (kth-dist, ids)
        #: contract; "candidates" = full per-candidate (dist2[Q,k], ids)
        #: rows — a PARTIAL result the routed front end folds across hosts
        #: (``complete_candidates``)
        self.emit = emit
        #: routed slab engines keep a host-side reference to their rows
        #: (a reference to the caller's array, not a copy — the slab is
        #: 1/H of the index, already resident in host RAM from loading):
        #: the slab-handoff pull path serves these on GET /slab_rows so a
        #: warm standby can adopt the slab from a surviving replica
        #: instead of re-reading the source file (serve/replica.py)
        self.host_points = points if emit == "candidates" else None
        #: point dimensionality — the whole ops/io/serve stack is D-generic
        #: (the matmul-form scorer is what makes high D affordable); only
        #: the Morton admission sort is 3-D-specific and disables itself
        self.dim = int(points.shape[1])
        self.max_radius = float(max_radius)
        self.mesh = mesh if mesh is not None else get_mesh(None)
        self.num_shards = self.mesh.shape[AXIS]
        self.engine_name = resolve_engine(engine)
        self.bucket_size = resolve_bucket_size(bucket_size, self.engine_name)
        self.merge_mode = resolve_merge(merge, self.num_shards)
        #: distance scoring mode, part of every AOT bucket key: "f32" =
        #: exact elementwise (VPU), "bf16" = matmul-form MXU score + exact
        #: f32 rescore (ops/distance.py). A mid-stream Pallas degradation
        #: keeps the mode — the XLA twin takes the same knob.
        #: ``score_mode`` is the EFFECTIVE path: below ``mxu_min_dim()``
        #: a bf16 request still scores exactly on the VPU (the matmul form
        #: cannot win there), and the per-mode tile counters follow the
        #: path that actually runs
        self.score_dtype = validate_score_dtype(score_dtype)
        self.score_mode = ("mxu" if (self.score_dtype == "bf16"
                                     and self.dim >= mxu_min_dim())
                           else "vpu")
        #: pod mode: the mesh spans processes — every host runs ONE engine
        #: over the same global mesh, dispatches IDENTICAL batches in the
        #: same order (the front end's contract), and fetches only its
        #: addressable 1/R row slices of the pod-final answer
        #: (``complete_slices``). Host merge would need remote partials no
        #: process can address, so the cross-host level REQUIRES the
        #: in-program reduction.
        self._multi = jax.process_count() > 1
        self.process_count = jax.process_count()
        self.process_index = jax.process_index()
        if self._multi and self.merge_mode != "device":
            raise ValueError(
                "multi-host serving requires the device-side merge (the R "
                "partial candidate blocks live on devices this process "
                "cannot address) — got merge="
                f"'{self.merge_mode}' on a {self.num_shards}-shard pod "
                "mesh; use merge='device' on a power-of-two mesh")
        if self.merge_mode == "device":
            # each device emits a 1/R slice of the merged result, so every
            # shape bucket must tile the mesh: both are powers of two, so
            # bucket >= R suffices. When R exceeds max_batch an explicit
            # 'device' is a config error; 'auto' quietly keeps the host
            # merge instead of failing a construction that host-merge
            # engines always served
            if self.num_shards > max_batch and merge == "auto":
                self.merge_mode = "host"
            else:
                min_batch = max(min_batch, self.num_shards)
        if max_batch < min_batch:
            raise ValueError(f"max_batch {max_batch} < min_batch {min_batch}"
                             + (" (device merge needs buckets >= num_shards)"
                                if min_batch == self.num_shards else ""))
        #: ascending power-of-two padded batch sizes; all client batch sizes
        #: in [1, max_batch] round up into one of these
        self.shape_buckets = [b for b in
                              (min_batch << i for i in range(64))
                              if b <= max_batch] or [min_batch]
        self.max_batch = self.shape_buckets[-1]
        #: query_buckets knob (0 = auto, 1 = single whole-batch bucket =
        #: the pre-locality behavior). Resolved per padded shape: the map
        #: below is part of each shape bucket's AOT program identity.
        #: Flat engines have no buckets to traverse, so they stay at 1.
        use_tiled = self.engine_name in ("tiled", "pallas_tiled")
        self.query_buckets_setting = int(query_buckets)
        self.query_buckets = {
            q: (resolve_query_buckets(query_buckets, q, self.k)
                if use_tiled and self.dim == 3 else 1)
            for q in self.shape_buckets}
        #: Morton admission: sort every dispatched batch by Z-order code
        #: over the index bbox (pads last), un-permuted at complete().
        #: Off when the caller pinned query_buckets=1 — that configuration
        #: IS the unsorted baseline the exactness tests and the locality
        #: bench compare against. The Morton encoder is 3-D (utils/math.py),
        #: so non-3-D indexes serve single-bucket unsorted batches — still
        #: exact, just without the locality prune.
        self.sort_queries = (use_tiled and self.query_buckets_setting != 1
                             and self.dim == 3)
        #: canonical (dist2, id) tie order inside the traversal — what
        #: makes results bit-identical across query bucket geometries. The
        #: boundary tie-fix routes ids through a f32 top_k (exact below
        #: 2**24; XLA:CPU's integer TopK is a scalar loop), so huge indices
        #: fall back to fold-arrival ties (distances stay exact; only
        #: equal-distance id CHOICES may then differ across geometries)
        self.canonical_ties = (use_tiled
                               and self.id_offset + self.n_points < (1 << 24))
        #: shared timers/counters sink: the tiered slab pool hands every
        #: slab engine ONE PhaseTimers so fetch/result/tile accounting
        #: accumulates across promotions and evictions instead of dying
        #: with each evicted engine (serve/slabpool.py)
        self.timers = timers if timers is not None else PhaseTimers()
        #: shared AOT cache (None = private per-engine dict only): slab
        #: engines of one pool share compiled programs per shape class
        self._exec_cache = executable_cache
        #: pad each local shard to at least this many rows — the slab
        #: pool's common shape class, so every slab engine lowers to
        #: identical program shapes (single-host engines only; pod mode
        #: already pads to the global max slab)
        self._pad_shard_rows = int(pad_shard_rows)
        self._lock = threading.Lock()
        # mutable engine identity: a mid-stream Pallas degradation
        # (degrade()) swaps engine_name while dispatches and /stats
        # scrapes run on other threads. The identity scalars live under
        # their OWN small lock (never held across an XLA compile) so a
        # /stats or /metrics scrape cannot block for the seconds-to-
        # minutes _get_executable holds _lock while compiling a cold
        # bucket (--no-warmup, post-degrade) — exactly when the health
        # monitor's scrape/rejoin probes most need an answer. _lock
        # still serializes dispatch/degrade/warmup, so identity reads
        # inside a _lock region stay mutually consistent; acquisition
        # order is always _lock -> _meta_lock (lskcheck-proven).
        self._meta_lock = threading.Lock()
        self.compile_count: guarded_by("_meta_lock") = 0
        self.degraded_reason: guarded_by("_meta_lock") = None
        self.engine_name: guarded_by("_meta_lock") = self.engine_name
        #: qpad per published executable (the stats compiled_shapes list,
        #: kept beside the scalars so scrapes never touch _executables)
        self._compiled_shapes: guarded_by("_meta_lock") = []
        #: (engine_name, merge_mode, qpad, B, score_dtype) -> AOT executable
        self._executables: guarded_by("_lock") = {}
        # launch pool: ``dispatch`` hands the executable call here and
        # returns after staging, so the dispatch stage never blocks on
        # device compute — even on backends whose PJRT client executes
        # synchronously (this container's CPU pin; TPU dispatch is natively
        # async and the hop is ~50us). The pool is the CPU stand-in for the
        # device's async program queue: 1 worker keeps launches strictly
        # FIFO; the server widens it to the pipeline depth so a depth-d
        # pipeline can keep d fixed-shape programs in flight (executions
        # are pure reads of the resident index, so concurrent launches
        # cannot race; result DELIVERY order is the batcher's FIFO queue)
        self._launch_workers = 1
        self._launch = ThreadPoolExecutor(max_workers=1,
                                          thread_name_prefix="knn-launch")

        with self.timers.phase("index_build"):
            self._build_index(points, jax)

    # ------------------------------------------------------------------ build

    def _build_index(self, points, jax):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.parallel.mesh import (
            AXIS,
            my_mesh_positions,
        )
        from mpi_cuda_largescaleknn_tpu.parallel.ring import partition_sharded

        # index bounding box: the Morton admission sort's quantization
        # frame (queries outside it clamp to the faces — still ordered)
        self._index_lo = (points.min(axis=0) if len(points)
                          else np.zeros(self.dim))
        self._index_hi = (points.max(axis=0) if len(points)
                          else np.ones(self.dim))
        bounds = slab_bounds(len(points), self.num_shards)
        #: per-shard AABB + point count, computed ONCE at upload from the
        #: host-side slabs (exact — no sentinel rows to mask) and exposed on
        #: /stats: the pod front end's routing bounds table is assembled
        #: from these (serve/frontend.py PodBoundsTable). Per-SHARD boxes
        #: beat one whole-slab box: the router prunes on the min over a
        #: host's shard bounds, which is tighter than the union box's.
        self.shard_bounds = slab_aabbs(points, bounds)
        sharding = NamedSharding(self.mesh, P(AXIS))
        if self._multi:
            # pod mode: every host loads the same full point set (serving
            # indexes are small next to the heap/query traffic) but uploads
            # only the slabs of the mesh positions its devices own — the
            # batch CLIs' process-ownership discipline (cli/multihost.py)
            npad = max(e - b for b, e in bounds)
            my_pos = self._my_pos = my_mesh_positions(self.mesh)
            local_flat, local_ids, _counts, self.npad_local = pad_and_flatten(
                [points[bounds[s][0]:bounds[s][1]] for s in my_pos],
                id_bases=[bounds[s][0] + self.id_offset for s in my_pos],
                pad_to=npad, dim=self.dim)
            rows = self.num_shards * npad
            flat = jax.make_array_from_process_local_data(
                sharding, local_flat, (rows, self.dim))
            ids = jax.make_array_from_process_local_data(
                sharding, local_ids, (rows,))
            self._flat_pts, self._flat_ids = flat, ids
        else:
            self._my_pos = list(range(self.num_shards))
            shards = [points[b:e] for b, e in bounds]
            pad_to = None
            if self._pad_shard_rows:
                pad_to = max(self._pad_shard_rows, 1,
                             max((len(s) for s in shards), default=1))
            flat, ids, _counts, self.npad_local = pad_and_flatten(
                shards, id_bases=[b + self.id_offset for b, _ in bounds],
                pad_to=pad_to, dim=self.dim)
            # the flat resident side serves the bruteforce engine; the
            # bucketed one serves the tiled engines — both stay
            # device-resident for the life of the process (the reference
            # re-uploads per launch)
            self._flat_pts = jax.device_put(flat, sharding)
            self._flat_ids = jax.device_put(ids, sharding)
        self._buckets = partition_sharded(self._flat_pts, self._flat_ids,
                                          self.mesh, self.bucket_size)
        #: per-bucket ||p||^2, computed ONCE at upload and resident beside
        #: the buckets — the matmul expansion's precomputed norm term
        #: (ops/distance.py). Only materialized when the MXU score is on.
        self._bucket_norms2 = None
        # lsk: allow[lock-guard] _build_index runs from __init__ only —
        if self.score_mode == "mxu" and self.engine_name in (  # unshared
                "tiled", "pallas_tiled"):
            from mpi_cuda_largescaleknn_tpu.ops.distance import norms2

            # jit keeps the buckets' dim-0 sharding (elementwise reduce
            # over the component axis), single- and multi-host alike
            self._bucket_norms2 = jax.jit(norms2)(self._buckets.pts)
        self._replicated = NamedSharding(self.mesh, P())
        #: this engine's device-resident byte footprint — flat arrays,
        #: bucketed partition, and the precomputed norms (summed over the
        #: whole mesh). The tiered slab pool budgets device memory against
        #: exactly this number, and /stats reports it per slab so
        #: ``knn_slab_pool_resident`` has a truthful denominator.
        resident = [self._flat_pts, self._flat_ids, *self._buckets]
        if self._bucket_norms2 is not None:
            resident.append(self._bucket_norms2)
        self.device_bytes = int(sum(
            int(np.prod(a.shape)) * a.dtype.itemsize for a in resident))

    def _stage_replicated(self, q: np.ndarray):
        """Upload a host batch replicated to every mesh device. Single
        host: a plain ``device_put``. Pod mode: every process holds the
        identical bytes (the front end replicated them), so each builds the
        global array from its own copy — no cross-host transfer."""
        import jax

        if not self._multi:
            return jax.device_put(q, self._replicated)
        return jax.make_array_from_callback(
            q.shape, self._replicated, lambda idx: q[idx])

    # ------------------------------------------------------------- compilation

    def bucket_for(self, n: int) -> int:
        """Smallest shape bucket covering an ``n``-query batch."""
        for b in self.shape_buckets:
            if b >= n:
                return b
        raise UnservableShapeError(
            f"batch of {n} queries exceeds max_batch {self.max_batch}")

    def _build_query_fn(self, engine_name: str, qpad: int, qbuckets: int,
                        plan_key: tuple | None = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from mpi_cuda_largescaleknn_tpu.ops.brute_force import (
            knn_update_bruteforce,
        )
        from mpi_cuda_largescaleknn_tpu.ops.candidates import init_candidates
        from mpi_cuda_largescaleknn_tpu.ops.partition import BucketedPoints
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary
        from mpi_cuda_largescaleknn_tpu.parallel.ring import (
            _tiled_engine_fn,
            device_merge_final,
        )

        k = self.k
        num_shards = self.num_shards
        device_merge = self.merge_mode == "device"
        emit_candidates = self.emit == "candidates"
        canonical = self.canonical_ties
        dim = self.dim
        score_dtype = self.score_dtype
        use_mxu = self.score_mode == "mxu"

        def finish(st, tiles):
            # per-shard local top-k -> program output. Host merge: emit the
            # R partial candidate blocks (the host's stable sort finishes).
            # Device merge: reduce to the global top-k in-program and emit
            # this device's 1/R slice of the final (dists, idx) — the
            # fetched global arrays are exactly [qpad] + [qpad, k]. The
            # third output is this device's executed-tile count [1].
            if not device_merge:
                return st.dist2, st.idx, tiles
            dists, d2, idx = device_merge_final(st, num_shards)
            if emit_candidates:
                # routed serving: emit the full merged candidate rows
                # (dist2[Q, k]) instead of the kth distances — the front
                # end's cross-host partial fold needs every candidate, not
                # just the boundary (the unused dists slice is DCE'd)
                return d2, idx, tiles
            return dists, idx, tiles

        use_tiled = engine_name in ("tiled", "pallas_tiled")

        if use_tiled:
            tiled_update = _tiled_engine_fn(engine_name)
            s_q = qpad // qbuckets

            def body(*args):
                if use_mxu:
                    # the precomputed per-bucket ||p||^2 rides as an extra
                    # resident operand (computed once at upload)
                    bpts, bids, blo, bhi, bn2, q, qr = args
                else:
                    (bpts, bids, blo, bhi, q, qr), bn2 = args, None
                # q f32[qpad,3] is REPLICATED: every device traverses its own
                # resident shard for the same queries; its local top-k is
                # exact over that shard, and the merge of the R partial
                # candidate rows — host-side or in-program — is exact over
                # the union (the ring's merge-across-rounds argument, with
                # space instead of time). The batch rides as ``qbuckets``
                # CONTIGUOUS slices, each with its own tight AABB: dispatch
                # Morton-sorted the rows, so slice = neighborhood, and the
                # per-bucket prune radius is the max over ~qpad/B coherent
                # queries instead of the whole batch. All-pad tail buckets
                # get inverted (+inf/-inf) bounds — never visited, and
                # their -inf radius never keeps the traversal alive.
                valid = q[:, 0] < PAD_SENTINEL / 2
                qids = jnp.where(valid, jnp.arange(qpad, dtype=jnp.int32), -1)
                qg = q.reshape(qbuckets, s_q, dim)
                vg = valid.reshape(qbuckets, s_q, 1)
                lo = jnp.min(jnp.where(vg, qg, jnp.inf), axis=1)
                hi = jnp.max(jnp.where(vg, qg, -jnp.inf), axis=1)
                qb = BucketedPoints(qg, qids.reshape(qbuckets, s_q), lo, hi,
                                    qids.reshape(qbuckets, s_q))
                # qr f32[qpad] is the PER-QUERY init radius — a runtime
                # operand, so a seeded batch and an unseeded one run the
                # SAME compiled program (dispatch fills max_radius rows
                # for unseeded queries and pads; serve/qcache.py supplies
                # certified triangle-inequality seeds strictly above each
                # row's true kth distance, so strict-< adoption keeps the
                # answer bitwise identical while the prune starts tighter)
                heap = pvary(init_candidates(qpad, k, qr))
                resident = BucketedPoints(bpts, bids, blo, bhi, bids)
                kw = dict(with_stats=True, canonical_ties=canonical,
                          score_dtype=score_dtype, point_norms2=bn2)
                if plan_key is not None:
                    # recall-SLO program knobs (serve/recall.py
                    # RecallPlan.program_key()): trace-time statics, so
                    # this body is a DIFFERENT compiled program from the
                    # exact one — the executable keys carry plan_key.
                    # Only the XLA tiled engine understands them
                    # (_get_executable nulls plan_key otherwise).
                    skip_rescore, prune_shrink, visit_frac = plan_key
                    kw.update(skip_rescore=skip_rescore,
                              prune_shrink=prune_shrink,
                              visit_frac=visit_frac)
                if engine_name == "tiled":
                    # chunk = ONE query bucket: the lax.map cond skips at
                    # per-bucket granularity, so a finished bucket stops
                    # paying for stragglers — measured faster at every B
                    # on the serving shapes, and it is what makes the
                    # tile-skip counters bucket-granular
                    kw["chunk_buckets"] = 1
                st, tiles = tiled_update(heap, qb, resident, **kw)
                # counters ride in TILE-ROW units (one query row folded
                # against one [T]-lane point tile): raw tile counts are
                # [s_q, T]-shaped and s_q varies with B, so scaling by s_q
                # makes executed/possible comparable across bucketings
                return finish(st, jnp.reshape(tiles * s_q, (1,)))

            in_specs = (P(AXIS),) * (5 if use_mxu else 4) + (P(), P())
        else:

            def body(spts, sids, q, qr):
                heap = pvary(init_candidates(qpad, k, qr))
                st = knn_update_bruteforce(heap, q, spts, sids,
                                           score_dtype=score_dtype)
                # flat engines score every pair; no tiles to count
                return finish(st, pvary(jnp.zeros((1,), jnp.int32)))

            in_specs = (P(AXIS),) * 2 + (P(), P())

        check_vma = not engine_name.startswith("pallas")
        # donate the staged query + radius buffers: each dispatch stages a
        # fresh replicated batch, so the previous one's device memory is
        # dead the moment the executable reads it — donation lets XLA
        # reuse it for the outputs instead of growing the pipelined
        # working set. TPU only: the CPU PJRT client logs
        # unusable-donation warnings.
        donate = ((len(in_specs) - 2, len(in_specs) - 1)
                  if jax.default_backend() == "tpu" else ())
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=in_specs,
            out_specs=(P(AXIS), P(AXIS), P(AXIS)), check_vma=check_vma),
            donate_argnums=donate)

    def _resident_args(self, engine_name: str):
        if engine_name in ("tiled", "pallas_tiled"):
            b = self._buckets
            base = (b.pts, b.ids, b.lower, b.upper)
            if self.score_mode == "mxu":
                return base + (self._bucket_norms2,)
            return base
        return (self._flat_pts, self._flat_ids)

    def _tiles_possible(self, engine_name: str, qpad: int) -> int:
        """Static ceiling of one batch's traversal in TILE-ROW units
        (query row x [T]-lane point-tile visit), summed over shards — the
        ``tiles_skipped`` counter's denominator. Row units make the
        ceiling independent of the query bucketing (B buckets x qpad/B
        rows x slots == qpad x slots), so executed/skipped are directly
        comparable across ``query_buckets`` settings. The XLA twin counts
        every schedule slot of a non-pruned step (pad visits included);
        the Pallas kernel counts only KEPT buckets, so its ceiling is the
        exact bucket count (the two engines' counters are not comparable
        as pruning quality — parallel/ring.py ``_ring_stats``)."""
        from mpi_cuda_largescaleknn_tpu.ops.tiled import tile_schedule_slots

        if engine_name not in ("tiled", "pallas_tiled"):
            return 0
        num_pb = self._buckets.ids.shape[0] // self.num_shards
        per_row = (num_pb if engine_name == "pallas_tiled"
                   else tile_schedule_slots(num_pb))
        # pod mode: counters are per-host — the denominator covers only the
        # shards this process fetches counts from (_tiles_fetch)
        return len(self._my_pos) * qpad * per_row

    def _get_executable(self, qpad: int, plan=None):  # lsk: holds[_lock]
        """AOT executable for (active engine, qpad); compiles on miss.

        ``compile_count`` increments EXACTLY when XLA is invoked — the
        recompile-freedom contract the tests assert. A compiled executable
        rejects any other input shape instead of silently retracing.
        Device-merge programs are distinct HLO from host-merge ones, and so
        are different query bucketings, score dtypes and recall-plan
        program knobs, so all are part of the bucket key — the
        recompile-freedom discipline holds per (engine, merge, shape,
        query_buckets, score_dtype, plan) tuple. ``plan`` (serve/recall.py
        ``RecallPlan``, None = exact) appends its ``program_key()`` at the
        END of the key so the exact path's keys — and the qpad-at-index-2
        layout ``ExecutableCache.stats`` reads — stay byte-identical to
        the pre-tier engine. Program knobs need the XLA tiled traversal;
        on other engines the plan runs the exact program (recall can only
        exceed the claim).
        """
        import jax

        qb = self.query_buckets[qpad]
        with self._meta_lock:
            engine_name = self.engine_name
        plan_key = None
        if plan is not None and engine_name == "tiled":
            pk = plan.program_key()
            if pk != (False, 1.0, 1.0):
                plan_key = pk
        key = (engine_name, self.merge_mode, qpad, qb, self.score_dtype)
        if plan_key is not None:
            key = key + (plan_key,)
        exe = self._executables.get(key)
        if exe is not None:
            return exe
        shared_key = None
        if self._exec_cache is not None:
            # the shared key adds every remaining program-identity knob
            # plus the resident operands' SHAPE CLASS: a compiled program
            # binds shapes, not values, so any engine of the same class
            # (the pool pads all slabs to one) can run it on its own
            # resident arrays
            args = self._resident_args(engine_name)
            shared_key = key + (
                self.emit, self.k, self.max_radius, self.canonical_ties,
                self.dim,
                tuple((tuple(a.shape), str(a.dtype)) for a in args))
            exe = self._exec_cache.get(shared_key)
            if exe is not None:
                self._executables[key] = exe
                with self._meta_lock:
                    self._compiled_shapes.append(qpad)
                return exe
            # None = this engine CLAIMED the shared key: concurrent
            # misses (another slab's promotion) park in get() until the
            # put below — or the abort, if the compile fails
        try:
            with self.timers.phase(f"compile_q{qpad}"):
                fn = self._build_query_fn(engine_name, qpad, qb,
                                          plan_key=plan_key)
                q0 = self._stage_replicated(
                    np.full((qpad, self.dim), PAD_SENTINEL, np.float32))
                r0 = self._stage_replicated(
                    np.full(qpad, self.max_radius, np.float32))
                exe = fn.lower(*self._resident_args(engine_name),
                               q0, r0).compile()
        except BaseException:
            if self._exec_cache is not None:
                self._exec_cache.abort(shared_key)
            raise
        self._executables[key] = exe
        if self._exec_cache is not None:
            self._exec_cache.put(shared_key, exe)
        with self._meta_lock:
            self.compile_count += 1
            self._compiled_shapes.append(qpad)
        return exe

    def warmup(self) -> dict:
        """Compile (and once execute) every shape bucket. Returns
        ``{"per_bucket_s": {qpad: seconds}, "query_buckets": {qpad: B},
        "tiles_executed": int, "tiles_skipped": int}`` so the serving CLI
        can report what a cold start cost and show the tile counters live
        from the first line — after this, steady-state traffic never
        compiles. (The warmup batches are all padding, so their traversals
        prune everything: executed stays 0 and skipped counts each
        program's full schedule — an honest first datapoint for the
        counters.)"""
        import jax

        per_bucket = {}
        with self._lock:
            with self._meta_lock:
                engine_name = self.engine_name
            for qpad in self.shape_buckets:
                t0 = time.perf_counter()
                exe = self._get_executable(qpad)
                # run once on an all-padding batch: pays any lazy backend
                # init; the traversal early-exits (no real queries)
                q0 = self._stage_replicated(
                    np.full((qpad, self.dim), PAD_SENTINEL, np.float32))
                r0 = self._stage_replicated(
                    np.full(qpad, self.max_radius, np.float32))
                out = exe(*self._resident_args(engine_name), q0, r0)
                jax.block_until_ready(out)
                self._count_tiles(self._tiles_fetch(out[2]),
                                  self._tiles_possible(engine_name, qpad))
                per_bucket[qpad] = round(time.perf_counter() - t0, 3)
        return {"per_bucket_s": per_bucket,
                "query_buckets": dict(self.query_buckets),
                "tiles_executed": self.timers.counter("tiles_executed"),
                "tiles_skipped": self.timers.counter("tiles_skipped")}

    def _count_tiles(self, executed: int, possible: int) -> None:
        """Fold one batch's measured tile count into the cumulative
        executed/skipped counters (flat engines report 0/0). Counted twice:
        the aggregate (the stable /stats surface) and the per-score-mode
        twin (``tiles_executed_mxu`` vs ``tiles_executed_vpu``), so the
        MXU-vs-VPU attribution is a number on /stats and /metrics."""
        if possible <= 0 and executed <= 0:
            return
        self.timers.count("tiles_executed", executed)
        self.timers.count("tiles_skipped", max(0, possible - executed))
        self.timers.count(f"tiles_executed_{self.score_mode}", executed)
        self.timers.count(f"tiles_skipped_{self.score_mode}",
                          max(0, possible - executed))

    def _tiles_fetch(self, t) -> int:
        """Sum a program's per-shard tile counts. Pod mode: only this
        process's addressable shards contribute (per-host counters; the
        possible-tile denominator is scaled to match in
        ``complete_slices``)."""
        if self._multi:
            return int(np.sum([np.asarray(sh.data).sum()
                               for sh in t.addressable_shards]))
        return int(np.asarray(t).sum())

    # ----------------------------------------------------------------- degrade

    def can_degrade(self) -> bool:
        with self._meta_lock:
            return self.engine_name == "pallas_tiled"

    def degrade(self, reason: str) -> None:
        """Swap the Pallas traversal for its XLA twin after a runtime
        failure (identical results by the twin-engine contract — see
        tests/test_pallas_tiled.py). Compiled twin programs are cached under
        their own key, so repeated degradations never recompile.

        Takes the engine lock: ``dispatch`` reads ``engine_name`` and picks
        the matching executable under that lock, so a mid-dispatch
        degradation can never produce a handle whose recorded engine name
        disagrees with the executable it actually launched (the stale-handle
        replay in admission.GracefulQueryFn depends on that agreement)."""
        with self._lock:
            with self._meta_lock:
                if self.engine_name != "pallas_tiled":
                    raise RuntimeError(
                        f"engine '{self.engine_name}' has no fallback")
                self.degraded_reason = reason
                self.engine_name = "tiled"
            # the twin may want a different tuned bucket geometry, but the
            # index is already partitioned — keep the resident geometry,
            # stay exact

    # ------------------------------------------------------------------- query

    def set_launch_workers(self, n: int) -> None:
        """Resize the launch pool toward ``n`` concurrent program launches.

        The serving layer asks for its pipeline depth; the engine clamps to
        what concurrency can actually buy: on the CPU backend one program
        already spans ``num_shards`` device threads, so extra launches only
        help while programs leave cores idle (a second launch on a saturated
        host just thrashes caches — measured slower). With one worker the
        pool still pipelines: the next staged batch launches the instant the
        current one retires, with no host work in between. Futures already
        submitted to the old pool complete unaffected (their threads drain
        and exit); a no-op when the size is unchanged.
        """
        import jax

        n = max(1, int(n))
        if jax.default_backend() != "tpu":
            cores = os.cpu_count() or 1
            n = max(1, min(n, cores // max(1, self.num_shards)))
        with self._lock:
            if n == self._launch_workers:
                return
            old = self._launch
            self._launch_workers = n
            self._launch = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="knn-launch")
            old.shutdown(wait=False)

    def dispatch(self, queries: np.ndarray, plan=None,
                 seed_radius=None) -> _InFlightBatch:
        """Issue a batch's device traversal WITHOUT blocking on the result.

        Morton-sorts (when enabled), stages + pads the batch, replicates
        it, and hands the AOT executable call to the engine's single launch
        thread; the returned ``_InFlightBatch`` wraps the launch future.
        Between ``dispatch`` and ``complete`` the device crunches while the
        host is free to merge an earlier batch (the batcher's pipelined
        mode) or stage the next one. The admission sort happens OUTSIDE the
        staged buffer's lifetime: ``queries`` is retained unsorted for
        degradation replay, and the permutation rides the handle for
        ``complete``'s demux. The lock serializes executable lookup,
        staging, and launch-queue order with ``degrade``; it is NOT held
        while the device computes or the host merges.

        ``plan`` (serve/recall.py ``RecallPlan``, None = exact) selects
        the plan-keyed approximate executable and rides the handle so a
        degradation replay re-runs the same plan.

        ``seed_radius`` (f32[n] or None) tightens individual rows' heap
        init radius below ``max_radius`` — the certified query cache's
        triangle-inequality seeds (serve/qcache.py). The radius is a
        RUNTIME operand of the same compiled program (no new AOT keys);
        rows at +inf / unseeded batches behave exactly as before. A seed
        must sit STRICTLY above the row's true kth-neighbor distance
        (rounding up in f32) or candidates at the boundary would be lost
        to the strict-< adoption; values above ``max_radius`` clamp to
        it. Degradation replays (serve/admission.py) rerun unseeded —
        sound, because seeds never change answers, only pruning."""
        import jax

        from mpi_cuda_largescaleknn_tpu.utils.math import morton_argsort

        queries = np.asarray(queries, np.float32).reshape(-1, self.dim)
        n = len(queries)
        if n == 0:
            with self._meta_lock:
                name = self.engine_name
            return _InFlightBatch(queries, 0, 0, name,
                                  self.merge_mode, None, time.perf_counter(),
                                  plan=plan)
        qpad = self.bucket_for(n)
        perm = None
        if self.sort_queries and n > 1:
            with self.timers.phase("morton_sort"):
                perm = morton_argsort(queries, self._index_lo,
                                      self._index_hi)
        staged = queries if perm is None else queries[perm]
        # per-row init radii: max_radius everywhere (pad rows included),
        # seeded rows clamped below it. The seed vector rides the SAME
        # Morton permutation as the queries, so staged row i keeps the
        # radius of the query it carries.
        r = np.full(qpad, self.max_radius, np.float32)
        if seed_radius is not None:
            sr = np.asarray(seed_radius, np.float32).reshape(-1)
            if len(sr) != n:
                raise ValueError(
                    f"seed_radius has {len(sr)} rows for {n} queries")
            sr = np.minimum(sr, np.float32(self.max_radius))
            r[:n] = sr if perm is None else sr[perm]
            self.timers.count(
                "seeded_rows", int(np.sum(sr < self.max_radius)))
        with self._lock:
            exe = self._get_executable(qpad, plan=plan)
            with self._meta_lock:
                # consistent with the key _get_executable compiled under:
                # degrade() needs _lock, which this region holds
                engine_name = self.engine_name
            args = self._resident_args(engine_name)
            q = np.full((qpad, self.dim), PAD_SENTINEL, np.float32)
            q[:n] = staged
            t0 = time.perf_counter()
            q_dev = self._stage_replicated(q)
            r_dev = self._stage_replicated(r)
            fut = self._launch.submit(exe, *args, q_dev, r_dev)
            possible = self._tiles_possible(engine_name, qpad)
        if plan is not None:
            self.timers.count("approx_batches")
        return _InFlightBatch(queries, n, qpad, engine_name,
                              self.merge_mode, fut, t0, perm=perm,
                              tiles_possible=possible, plan=plan)

    def complete(self, batch: _InFlightBatch):
        """Block on a dispatched batch and finish its cross-shard top-k.

        ``merge="host"``: fetch the R partial [Q, k] candidate blocks and
        merge them in numpy. ``merge="device"``: the reduction already ran
        in-program, so this fetches ONE final [Q] + [Q, k] pair — R x fewer
        result bytes over the host link, no merge work at all.
        ``fetch_bytes`` / ``result_rows`` count what actually crossed; the
        per-shard tile counts ride along as an [R] i32 and feed the
        ``tiles_executed`` / ``tiles_skipped`` counters. Finally the
        Morton admission sort (if any) is undone, so rows come back in the
        caller's order.

        The future resolution + np.asarray fetches are where async dispatch
        errors surface (a Pallas runtime failure raises HERE, not in
        ``dispatch``) — the graceful wrapper replays the handle's retained
        queries on the twin. ``engine_batch_seconds`` measures
        dispatch->fetch wall-clock, which under pipelining includes time
        queued behind the previous batch.
        """
        if batch.n == 0:
            return (np.zeros(0, np.float32),
                    np.zeros((0, self.k), np.int32))
        if self._multi:
            raise RuntimeError(
                "pod-mode engines emit per-host row slices — use "
                "complete_slices (the front end assembles the full batch)")
        if self.emit == "candidates":
            raise RuntimeError(
                "emit='candidates' engines return full candidate rows — "
                "use complete_candidates (the routed front end's fold)")
        a, b, t = batch.fut.result()
        a = np.asarray(a)
        b = np.asarray(b)
        self.timers.hist("engine_batch_seconds").record(
            time.perf_counter() - batch.t0)
        # fetch accounting covers RESULT bytes only (the PR-3 merge
        # placement contract); the [R] i32 tile counter is observability,
        # not payload
        self.timers.count("fetch_bytes", a.nbytes + b.nbytes)
        self.timers.count("result_rows", batch.n)
        self._count_tiles(self._tiles_fetch(t), batch.tiles_possible)
        if batch.merge_mode == "device":
            dists, nbrs = a, b  # final already: [qpad], [qpad, k]
        else:
            with self.timers.phase("host_merge"):
                dists, nbrs = _merge_shard_candidates(
                    a, b, self.num_shards, batch.qpad, self.k)
        dists, nbrs = dists[:batch.n], nbrs[:batch.n]
        if batch.perm is not None:
            # undo the Morton admission sort: staged row i answers original
            # row perm[i], so a scatter through perm restores caller order
            # (bit-identical to the unsorted path — rows are independent
            # and the traversal's tie order is canonical)
            out_d = np.empty_like(dists)
            out_n = np.empty_like(nbrs)
            out_d[batch.perm] = dists
            out_n[batch.perm] = nbrs
            dists, nbrs = out_d, out_n
        return dists, nbrs

    def complete_candidates(self, batch: _InFlightBatch):
        """Routed-host ``complete``: block on a dispatched batch and return
        the full merged candidate rows ``(dist2 f32[n, k], idx i32[n, k])``
        over THIS engine's points — ascending (dist2, id) per row, -1 ids /
        radius**2 distances in unfilled slots.

        This is the partial a routed pod host serves (POST /route_knn):
        the front end folds the per-host rows with the same canonical
        (dist2, id) discipline (serve/frontend.py ``RoutedPodFanout``), so
        the folded result is bit-identical to one engine over the union of
        the hosts' points. Works under both merge placements: the device
        merge emits the candidate rows in-program (``emit='candidates'``),
        the host merge keeps the full-width variant of the PR-3 fold.
        """
        if batch.n == 0:
            return (np.full((0, self.k), np.inf, np.float32),
                    np.full((0, self.k), -1, np.int32))
        if self._multi:
            raise RuntimeError(
                "pod-mode engines emit per-host row slices — routed "
                "(independent-host) serving never joins a global mesh")
        if batch.merge_mode == "device" and self.emit != "candidates":
            raise RuntimeError(
                "engine was built with emit='final': its device-merge "
                "programs emit kth distances, not candidate rows — "
                "construct the engine with emit='candidates'")
        a, b, t = batch.fut.result()
        a = np.asarray(a)
        b = np.asarray(b)
        self.timers.hist("engine_batch_seconds").record(
            time.perf_counter() - batch.t0)
        self.timers.count("fetch_bytes", a.nbytes + b.nbytes)
        self.timers.count("result_rows", batch.n)
        self._count_tiles(self._tiles_fetch(t), batch.tiles_possible)
        if batch.merge_mode == "device":
            d2, idx = a, b  # already the merged [qpad, k] candidate rows
        else:
            with self.timers.phase("host_merge"):
                d2, idx = _merge_shard_candidates(
                    a, b, self.num_shards, batch.qpad, self.k, full=True)
        d2, idx = d2[:batch.n], idx[:batch.n]
        if batch.perm is not None:
            out_d = np.empty_like(d2)
            out_i = np.empty_like(idx)
            out_d[batch.perm] = d2
            out_i[batch.perm] = idx
            d2, idx = out_d, out_i
        return d2, idx

    def refetch_exact(self, queries):
        """Survivor re-fetch hook (PR-17 quantized wire): exact f32
        candidate rows for ``queries``, byte-equal to any earlier batch
        that contained these rows. Candidate rows are batch-composition
        INDEPENDENT — each row's top-k over this engine's points is a
        function of the query row alone — which is the property the
        ``?wire=x32`` re-fetch (and the routed escalation waves before
        it) relies on: re-asking costs a round trip, never bits."""
        return self.complete_candidates(self.dispatch(queries))

    def complete_slices(self, batch: _InFlightBatch):
        """Pod-mode ``complete``: fetch ONLY this process's addressable row
        slices of the pod-final answer.

        Under ``merge="device"`` on the global mesh, device at mesh
        position r holds rows [r*qpad/R, (r+1)*qpad/R) of the final
        [qpad] + [qpad, k] arrays — so each host's fetch moves 1/R of the
        result per owned position and the POD's total fetched bytes equal
        ONE final result, not one per host (the acceptance arithmetic of
        ``serve_smoke.py --multihost-bench``). Returns
        ``(rows i32[m], dists f32[m], nbrs i32[m, k])`` where ``rows`` are
        CALLER-order row indices (the Morton admission sort already undone
        per row via ``batch.perm``) and ``m`` counts only real (non-pad)
        rows this host owns. The front end scatters each host's triple into
        the full batch — bit-identical to a single-process ``complete`` of
        the same program, ties included.
        """
        if batch.n == 0:
            return (np.zeros(0, np.int32), np.zeros(0, np.float32),
                    np.zeros((0, self.k), np.int32))
        a, b, t = batch.fut.result()
        self.timers.hist("engine_batch_seconds").record(
            time.perf_counter() - batch.t0)
        rp = batch.qpad // self.num_shards
        rows_l, d_l, n_l = [], [], []
        fetched = 0
        nbrs_by_row = {int(sh.index[0].start): np.asarray(sh.data)
                       for sh in b.addressable_shards}
        for sh in a.addressable_shards:
            lo = int(sh.index[0].start)
            d = np.asarray(sh.data)
            nb = nbrs_by_row[lo]
            fetched += d.nbytes + nb.nbytes
            staged = np.arange(lo, lo + rp)
            real = staged < batch.n  # pad rows sort/stay last
            if not np.any(real):
                continue
            staged = staged[real]
            rows_l.append(batch.perm[staged] if batch.perm is not None
                          else staged.astype(np.int32))
            d_l.append(d[real])
            n_l.append(nb[real])
        self.timers.count("fetch_bytes", fetched)
        self._count_tiles(self._tiles_fetch(t), batch.tiles_possible)
        if not rows_l:
            return (np.zeros(0, np.int32), np.zeros(0, np.float32),
                    np.zeros((0, self.k), np.int32))
        rows = np.concatenate(rows_l).astype(np.int32)
        self.timers.count("result_rows", len(rows))
        return rows, np.concatenate(d_l), np.concatenate(n_l)

    def query(self, queries: np.ndarray, plan=None, seed_radius=None):
        """f32[n,3] -> (f32[n] k-th-NN distances, i32[n,k] neighbor ids).

        Serialized ``dispatch`` + ``complete``. ``n`` may be anything in
        [0, max_batch]; the batch is padded to its shape bucket. Larger
        batches are the batcher's/admission's job to split. Distances follow
        the reference contract: sqrt of the k-th smallest squared distance,
        inf (or the ``-r`` radius) when fewer than k neighbors exist.
        Neighbor ids are global point indices, ascending by distance, -1 for
        unfilled slots. With a recall ``plan``, distances/sets are the
        plan's approximation instead (still sorted, -1-padded).
        ``seed_radius`` (serve/qcache.py certified seeds) tightens
        individual rows' heap-init radius without changing any answer bit.
        """
        return self.complete(self.dispatch(queries, plan=plan,
                                           seed_radius=seed_radius))

    def stats(self) -> dict:
        # the mutable identity (engine_name / degraded_reason /
        # compile_count / compiled shapes) is snapshotted under the small
        # metadata lock: a scrape may race a compile or a degradation on
        # the query path (--no-warmup, post-degrade) and must NOT queue
        # behind _lock while _get_executable compiles a cold bucket
        with self._meta_lock:
            engine_name = self.engine_name
            degraded_reason = self.degraded_reason
            compile_count = self.compile_count
            compiled_shapes = sorted(self._compiled_shapes)
        return {
            "engine": engine_name,
            "merge": self.merge_mode,
            "score_dtype": self.score_dtype,
            "score_mode": self.score_mode,
            "dim": self.dim,
            "degraded_reason": degraded_reason,
            "n_points": self.n_points,
            "k": self.k,
            "num_shards": self.num_shards,
            # pod-mode surface: which slice of the global mesh this process
            # owns (the front end sanity-checks coverage across hosts)
            "multihost": self._multi,
            "process_index": self.process_index,
            "process_count": self.process_count,
            "my_positions": list(self._my_pos),
            # routed-serving surface: which global rows this engine owns,
            # what its completions emit, whether its tie order is the
            # canonical (dist2, id) one the cross-host fold assumes, the
            # radius cap (None = inf; /stats stays strict JSON), and the
            # per-shard AABB + count table the front end routes on
            "row_offset": self.id_offset,
            "emit": self.emit,
            "canonical_ties": self.canonical_ties,
            "max_radius": (None if math.isinf(self.max_radius)
                           else self.max_radius),
            "shard_bounds": self.shard_bounds,
            # per-slab device byte footprint (flat + bucketed + norms):
            # what the tiered slab pool's --device-slab-budget counts
            "device_bytes": self.device_bytes,
            "max_batch": self.max_batch,
            "bucket_size": self.bucket_size,
            "shape_buckets": list(self.shape_buckets),
            "compiled_shapes": compiled_shapes,
            "compile_count": compile_count,
            # query-locality surface: per-shape bucket counts, whether the
            # Morton admission sort is on, and the traversal's cumulative
            # tile-skip accounting (the prune's win as a number)
            "query_buckets": {str(q): b
                              for q, b in sorted(self.query_buckets.items())},
            "sort_queries": self.sort_queries,
            "tiles_executed": self.timers.counter("tiles_executed"),
            "tiles_skipped": self.timers.counter("tiles_skipped"),
            # per-score-mode twins: which scorer (MXU matmul-form vs VPU
            # elementwise) actually burned the executed tiles
            "tiles_executed_mxu": self.timers.counter("tiles_executed_mxu"),
            "tiles_skipped_mxu": self.timers.counter("tiles_skipped_mxu"),
            "tiles_executed_vpu": self.timers.counter("tiles_executed_vpu"),
            "tiles_skipped_vpu": self.timers.counter("tiles_skipped_vpu"),
            # headline copies of the timers' counters: the stable /stats
            # API surface loadgen + serve_smoke bind to (timers.report()
            # nests the same values among phases/histograms for --timings)
            "fetch_bytes": self.timers.counter("fetch_bytes"),
            "result_rows": self.timers.counter("result_rows"),
            # recall-SLO tier: batches dispatched under an approximate plan
            # (serve/recall.py) — 0 on an exact-only deployment
            "approx_batches": self.timers.counter("approx_batches"),
            "timers": self.timers.report(),
        }


def load_slab_rows(path: str, host_id: int, num_hosts: int):
    """Load row slab ``[N*i/H, N*(i+1)/H)`` of ``path``; returns
    ``(points f32[n, D], begin, n_total)``.

    The ONE slab-split read every slab consumer shares —
    ``materialize_slab_engine`` (routed hosts + /adopt_slab handoff), the
    routed streaming path (serve_main ``--num-slabs`` on a routed host),
    and the slab pool's cold tier (serve/slabpool.py ``SlabSource``): the
    reference's ``read_file_portion`` integer split for ``.float3``
    (identical arithmetic to ``slab_bounds``), an mmap slice for
    ``.npy`` — so every consumer materializes byte-identical rows."""
    if path.endswith(".npy"):
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds

        arr = np.load(path, mmap_mode="r")
        n_total = len(arr)
        begin, end = slab_bounds(n_total, num_hosts)[host_id]
        return np.asarray(arr[begin:end], np.float32), begin, n_total
    from mpi_cuda_largescaleknn_tpu.io.reader import read_file_portion

    return read_file_portion(path, host_id, num_hosts)


def materialize_slab_engine(path, host_id: int, num_hosts: int, *, k: int,
                            shards=None, engine: str = "auto",
                            merge: str = "auto", bucket_size: int = 0,
                            max_radius: float = math.inf,
                            max_batch: int = 1024, min_batch: int = 8,
                            query_buckets: int = 0,
                            score_dtype: str = "f32", points=None,
                            id_offset: int | None = None,
                            warmup: bool = False):
    """Load row slab ``[N*i/H, N*(i+1)/H)`` and build its routed engine.

    The ONE slab-upload + AOT-warmup path shared by ``serve_main
    --routing bounds`` hosts at launch and by the standby's
    ``POST /adopt_slab`` handoff (serve/frontend.py): both must
    materialize byte-identical slabs — the reference's
    ``read_file_portion`` split for ``.float3`` (identical integer
    arithmetic to ``slab_bounds``, so the adopted rows equal the lost
    host's exactly), an mmap slice for ``.npy``. Pass ``points`` +
    ``id_offset`` to skip the file read (the pull-from-replica path —
    serve/replica.py ``pull_slab_rows``). Returns
    ``(engine, id_offset, n_total)`` with ``n_total`` None when the rows
    came pre-loaded."""
    from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh

    if points is None:
        if not path:
            raise ValueError("need an input path or pre-loaded slab rows")
        points, id_offset, n_total = load_slab_rows(path, host_id,
                                                    num_hosts)
    else:
        if id_offset is None:
            raise ValueError("pre-loaded slab rows need their id_offset "
                             "(the slab's global row origin)")
        n_total = None
    eng = ResidentKnnEngine(
        points, k, mesh=get_mesh(shards), engine=engine, merge=merge,
        bucket_size=bucket_size, max_radius=max_radius,
        max_batch=max_batch, min_batch=min_batch,
        query_buckets=query_buckets, score_dtype=score_dtype,
        id_offset=int(id_offset), emit="candidates")
    if warmup:
        eng.warmup()
    return eng, int(id_offset), n_total


def _merge_shard_candidates(d2, idx, num_shards, qpad, k, full=False):
    """Merge R per-shard top-k candidate blocks into the global top-k.

    ``d2``/``idx`` are [R*qpad, k] shard-major. The tie discipline is the
    one a stable ascending sort over the shard-rank-ordered concatenation
    produces (earlier shard, then earlier slot, wins at equal distance —
    ops/candidates.py merge_candidates), but the full width-R*k stable sort
    is avoided: ``np.argpartition`` selects the k smallest per row in
    O(R*k), a column-ordered tie-fix picks the boundary ties the stable
    sort would have picked, and only the k survivors see a sort. Identical
    output, measurably less host CPU at serving batch sizes — this runs on
    the completion worker's critical path whenever the host path is
    selected (or degraded to).

    ``full=True`` returns the whole merged candidate rows
    ``(dist2[qpad, k], idx[qpad, k])`` instead of (sqrt-kth, idx) — the
    routed serving path's partial (``complete_candidates``).
    """
    d2 = d2.reshape(num_shards, qpad, k).transpose(1, 0, 2).reshape(qpad, -1)
    idx = idx.reshape(num_shards, qpad, k).transpose(1, 0, 2).reshape(qpad, -1)
    if num_shards == 1:
        # a single shard's block is already the sorted global top-k
        if full:
            return d2, idx
        return np.sqrt(d2[:, k - 1]), idx
    # SOME k smallest per row (boundary ties arbitrary), then the k-th value
    part = np.argpartition(d2, k - 1, axis=1)[:, :k]
    kth = np.take_along_axis(d2, part, axis=1).max(axis=1, keepdims=True)
    # every strictly-closer column is in; of the columns tied AT the k-th
    # value, the stable sort would keep the first (k - m) in column order
    below = d2 < kth
    m = below.sum(axis=1, keepdims=True)
    # lsk: allow[float-eq] the boundary tie-fix IS bitwise: kth is an element
    tied = d2 == kth  # of d2, so exact equality finds exactly the tied class
    mask = below | (tied & (np.cumsum(tied, axis=1) <= k - m))
    # exactly k selected per row; recover them in ascending column order
    # with an O(R*k) boolean partition + an O(k log k) sort, never a full
    # stable argsort over all R*k columns
    sel_cols = np.sort(np.argpartition(~mask, k - 1, axis=1)[:, :k], axis=1)
    sel_d2 = np.take_along_axis(d2, sel_cols, axis=1)
    order = np.argsort(sel_d2, axis=1, kind="stable")
    top_d2 = np.take_along_axis(sel_d2, order, axis=1)
    top_idx = np.take_along_axis(
        idx, np.take_along_axis(sel_cols, order, axis=1), axis=1)
    if full:
        return top_d2, top_idx
    return np.sqrt(top_d2[:, k - 1]), top_idx
