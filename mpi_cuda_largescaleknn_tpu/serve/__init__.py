"""Online kNN serving layer.

The batch pipelines (models/) pay data load + tree build + XLA compile on
every process launch — ~220s of compile alone at the 250K config
(utils/compile_cache.py). This subsystem keeps all of it resident and
amortizes it across a request stream:

- ``engine``   — loads points once, builds the sharded spatial index once,
                 AOT-compiles one query program per shape bucket (powers of
                 two up to ``max_batch``) so steady-state traffic can never
                 recompile.
- ``batcher``  — dynamic micro-batching: queued queries coalesce into the
                 smallest covering shape bucket, flushing on max-batch or a
                 latency deadline, with per-request demux. With
                 ``pipeline_depth > 1`` a dispatch worker keeps the next
                 batch's device traversal in flight while a completion
                 worker merges/demuxes the previous one (the engine's
                 ``dispatch``/``complete`` split).
- ``admission``— bounded queue + backpressure (explicit overload errors, not
                 unbounded growth), per-request deadlines, and graceful
                 degradation from the Pallas engine to the XLA twin.
- ``server``   — stdlib-HTTP JSON/binary endpoint: /knn, /healthz, /stats,
                 Prometheus-text /metrics.
- ``frontend`` — pod-mesh serving: per-host slice servers (one engine per
                 host over ONE global mesh, ``merge=device`` reduction on
                 the global axis, strict-seq collective dispatch) + the
                 fan-out front end that replicates each admitted batch,
                 assembles per-host row slices, and re-exposes the same
                 public contract with per-host health and straggler
                 accounting.
- ``health``   — the fault-tolerance supervisor: per-host
                 healthy/suspect/drained/rejoining lifecycle, capped
                 exponential backoff with deterministic jitter, and the
                 background monitor that drains failing hosts and gates
                 rejoin on a config/bounds fingerprint match.
- ``faults``   — deterministic fault injection (seeded latency / error /
                 drop / close-mid-body injectors on every serving
                 handler; ``KNN_FAULTS`` env or POST /faults) so every
                 failure path is testable without real process kills.
- ``slabpool`` — beyond-HBM tiered slab index: a device-budget-bounded
                 working set of slab engines over a host-RAM row pool
                 over the mmap'd source file, LRU-with-pin eviction,
                 async bounds-driven prefetch, and an engine-shaped
                 streaming facade — bit-identical to fully-resident at
                 every pool size (a miss stalls, never approximates).

TPU-KNN (arXiv:2206.14286) reaches peak FLOP/s only with large fixed-shape
query batches; PANDA (arXiv:1607.08220) frames distributed kNN as a
long-lived service over a partitioned index. This layer is both arguments
implemented: fixed shapes via bucketing, residency via the process.
"""
