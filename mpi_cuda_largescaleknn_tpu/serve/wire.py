"""Wire codecs for the byte-heavy serving paths (quantized exchange).

At pod scale the system is DCN-bound: every routed wave ships full f32
candidate rows (``d2[Q,k]`` f32 + ``idx[Q,k]`` i32 per visited host), and
slab handoff / cold-tier reads pull raw f32 rows. EQuARX (PAPERS.md,
arxiv 2506.17615) compresses XLA collectives ~4x by quantizing; because
candidate distances are monotone *scores*, we can go further and keep the
served results **bitwise exact** by applying the PR-6 bf16-score /
f32-rescore pattern to the network: quantize the wire, re-merge the
survivors in exact f32 (serve/frontend.py threads the re-merge).

Two codecs, both negotiated per endpoint via the /stats ``wire`` caps
block (absent caps = an old binary = f32 — mixed pods interop):

``q16`` — candidate exchange (``POST /route_knn?wire=q16``)
    Per row: ``n_valid`` (slots with idx >= 0), a per-row f32 **anchor**
    (the last valid distance — the kth — transmitted exact as a varint
    ulp-delta down the batch), the *interior* distances as monotone
    uint16 levels ``u = ceil(d2 / anchor * 65535)`` stored as slot-major
    byte planes (the anchor slot always decodes to level 65535 so its
    column is elided entirely), and the valid ids as one flat zigzag
    varint delta stream in distance order (Morton-sorted indexes make
    neighbor ids cluster, so the deltas stay short). The whole body is
    zlib'd; encode/decode stay vectorized numpy (the varint coder is a
    byte-position scatter, not a per-value loop). Decode returns bounds:
    ``hi = anchor * u / 65535`` rounded UP into f32 and ``lo`` rounded
    down, with the anchor slot and every pad slot exact (``lo == hi``).
    Quantization therefore ceils, never floors: a conservative fold over
    ``hi`` can widen the certified escalation radius but can never prune
    a true neighbor or certify away a host a full-precision fold would
    have visited, and ``lo`` lets the frontend prove when a re-fetch
    cannot change the served row.

``d16`` — slab transfer (``GET /slab_rows?wire=d16``) and cold reads
    Rows are Morton-sorted (the io partitioner's production order), so
    consecutive rows are spatial neighbors. Each coordinate column is
    mapped to the total-order u32 space (sign-flip transform: float
    compare == unsigned compare), delta-coded row-to-row, zigzag'd, and
    stored as byte planes: 16-bit deltas when the chunk's steps fit
    (tight Morton runs), 32-bit next, 64-bit when steps cross zero at
    magnitude > ~1 (zigzag can reach 2^33), raw f32 when the transform
    does not pay — then zlib. The transform is pure integer arithmetic
    in ulp space: **lossless always**, verified by a crc32 fingerprint
    of the raw f32 bytes after decode (torn / corrupt transfers raise
    ``WireError`` instead of materializing a wrong slab).

Shared negotiation state (``WireNegotiator``) and the byte accounting
behind ``knn_wire_bytes_total{path=,codec=}`` (``WireStats``) live here
so every surface (host handler, routed fan-out, replica pull, slab pool)
counts bytes the same way. Determinism: no wallclock, no RNG — codecs
are pure functions of their input bytes.
"""

from __future__ import annotations

import struct
import threading
import zlib

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

#: codec names by path, in preference order (index 0 = the compressed
#: codec ``wire=auto`` negotiates when both sides support it)
CANDIDATE_CODECS = ("q16", "f32")
SLAB_CODECS = ("d16", "f32")

#: zlib effort for wire bodies: level 1 is ~5x faster than default-6 and
#: within a few percent of its ratio on byte-plane input
_ZLIB_LEVEL = 1

_Q16_MAGIC = b"Kq"
_D16_MAGIC = b"Kd"


class WireError(ValueError):
    """Malformed / torn / fingerprint-mismatched wire payload."""


def wire_caps(mode: str = "auto") -> dict:
    """The capability block a new host advertises at the /stats ROOT
    (deliberately outside the ``engine`` sub-dict: replica fingerprints
    must not change when a codec is added, or mixed old/new pods could
    never bind a handoff). ``mode="f32"`` (host ``--wire f32``)
    advertises — and serves — only the uncompressed codec: the supported
    way to emulate an old binary in a mixed pod, and the kill switch if
    a codec ever misbehaves in production."""
    if mode == "f32":
        return {"candidates": ["f32"], "slab_rows": ["f32"]}
    return {"candidates": list(CANDIDATE_CODECS),
            "slab_rows": list(SLAB_CODECS)}


def negotiate(mode: str, caps: dict | None, path: str) -> str:
    """Pick the codec for one endpoint: ``mode`` is the frontend knob
    (``auto`` | ``f32`` | the compressed codec name); ``caps`` is the
    host's advertised table (None/empty = old binary). Negotiation can
    only ever *fall back* to f32 — a mismatch is never an error."""
    if mode == "f32" or not caps:
        return "f32"
    offered = caps.get(path) or []
    preferred = CANDIDATE_CODECS[0] if path == "candidates" \
        else SLAB_CODECS[0]
    if mode in ("auto", preferred) and preferred in offered:
        return preferred
    return "f32"


# --------------------------------------------------------------- helpers


def _zigzag(v: np.ndarray) -> np.ndarray:
    v = v.astype(np.int64)
    return ((v << 1) ^ (v >> 63)).astype(np.uint64)


def _unzigzag(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)
            ^ -(u & np.uint64(1)).astype(np.int64))


def _planes(a: np.ndarray, width: int) -> bytes:
    """Slot-major byte planes: transpose so same-position values across
    rows are adjacent, then split into little-endian byte planes (plane
    0 = all low bytes, ...). High planes of deltas/levels are near
    constant, which is what zlib's window actually finds."""
    dt = {1: "<u1", 2: "<u2", 4: "<u4", 8: "<u8"}[width]
    b = np.ascontiguousarray(a.T).astype(dt).view(np.uint8)
    b = b.reshape(*a.T.shape, width)
    return b"".join(np.ascontiguousarray(b[..., i]).tobytes()
                    for i in range(width))


def _unplanes(raw: bytes, shape: tuple, width: int) -> np.ndarray:
    """Inverse of ``_planes``; returns an array of ``shape`` (row-major
    view of the original, i.e. transposed back)."""
    n = int(np.prod(shape, dtype=np.int64))
    if len(raw) != n * width:
        raise WireError(f"plane section is {len(raw)} bytes, "
                        f"want {n * width}")
    planes = np.frombuffer(raw, np.uint8).reshape(width, n)
    out = np.zeros(n, np.uint64)
    for i in range(width):
        out |= planes[i].astype(np.uint64) << np.uint64(8 * i)
    shape_t = tuple(reversed(shape))
    return out.reshape(shape_t).T


def _varint_encode(u: np.ndarray) -> bytes:
    """LEB128 varints for a u64 array, vectorized: compute each value's
    byte length, then scatter byte position p of every value with >= p+1
    bytes in one masked assignment per position (10 positions max)."""
    u = np.ascontiguousarray(u, np.uint64).ravel()
    if u.size == 0:
        return b""
    nbits = np.zeros(u.shape, np.int64)
    tmp = u.copy()
    for shift in (32, 16, 8, 4, 2, 1):
        big = tmp >= (np.uint64(1) << np.uint64(shift))
        nbits += np.where(big, shift, 0)
        tmp = np.where(big, tmp >> np.uint64(shift), tmp)
    nbytes = np.maximum((nbits + 7) // 7, 1)
    ends = np.cumsum(nbytes)
    out = np.zeros(int(ends[-1]), np.uint8)
    starts = ends - nbytes
    for p in range(10):
        sel = nbytes > p
        if not sel.any():
            break
        chunk = (u[sel] >> np.uint64(7 * p)) & np.uint64(0x7F)
        cont = np.where(nbytes[sel] > p + 1, 0x80, 0).astype(np.uint8)
        out[starts[sel] + p] = chunk.astype(np.uint8) | cont
    return out.tobytes()


def _varint_decode(raw: bytes, count: int) -> tuple[np.ndarray, int]:
    """Decode ``count`` LEB128 varints; returns ``(values u64[count],
    bytes_consumed)`` so variable-length sections can be parsed in
    sequence. Truncated / overlong streams raise ``WireError``."""
    if count == 0:
        return np.zeros(0, np.uint64), 0
    b = np.frombuffer(raw, np.uint8)
    ends = np.nonzero((b & 0x80) == 0)[0]
    if len(ends) < count:
        raise WireError(f"varint section truncated: {len(ends)} values, "
                        f"want {count}")
    ends = ends[:count]
    starts = np.concatenate([[0], ends[:-1] + 1])
    lens = ends - starts + 1
    if (lens > 10).any():
        raise WireError("overlong varint")
    out = np.zeros(count, np.uint64)
    for p in range(int(lens.max())):
        sel = lens > p
        out[sel] |= ((b[starts[sel] + p] & np.uint64(0x7F))
                     << np.uint64(7 * p))
    return out, int(ends[-1]) + 1


def float_to_ordered_u32(x: np.ndarray) -> np.ndarray:
    """Map f32 bit patterns to u32 so unsigned integer order == float
    total order (negatives flipped entirely, positives sign-flipped).
    Pure bit transform — exactly invertible for every finite value."""
    bits = np.ascontiguousarray(x, "<f4").view(np.uint32)
    neg = (bits & np.uint32(0x80000000)) != 0
    return np.where(neg, ~bits, bits | np.uint32(0x80000000))


def ordered_u32_to_float(u: np.ndarray) -> np.ndarray:
    neg = (u & np.uint32(0x80000000)) == 0
    bits = np.where(neg, ~u, u & np.uint32(0x7FFFFFFF)).astype("<u4")
    return bits.view("<f4")


# ---------------------------------------------------- q16 candidate codec


def encode_candidates_q16(d2: np.ndarray, idx: np.ndarray) -> bytes | None:
    """Encode one /route_knn response body. Returns None when the rows
    don't fit the codec's preconditions (k > 255, non-prefix pad layout,
    non-uniform pad value, NaN) — the caller then answers f32; the codec
    never guesses."""
    d2 = np.ascontiguousarray(d2, "<f4")
    idx = np.ascontiguousarray(idx, "<i4")
    if d2.ndim != 2 or d2.shape != idx.shape:
        return None
    m, k = d2.shape
    if k > 255 or np.isnan(d2).any():
        return None
    valid = idx >= 0
    n_valid = valid.sum(axis=1).astype(np.uint8)
    # pads must be a suffix of every row (the engine contract) and carry
    # one uniform distance (radius^2, or +inf when unbounded)
    slots = np.arange(k, dtype=np.int64)[None, :]
    if not (valid == (slots < n_valid[:, None])).all():
        return None
    pad_value = np.float32(np.inf)
    if (~valid).any():
        pads = d2[~valid]
        pad_value = pads.flat[0]
        if not (pads == pad_value).all():
            return None
    anchors = np.zeros(m, "<f4")
    has = n_valid > 0
    if has.any():
        rows = np.nonzero(has)[0]
        anchors[rows] = d2[rows, n_valid[rows].astype(np.int64) - 1]
    # monotone uint16 levels against the per-row anchor, computed so the
    # decoder's EXACT f64 expression anchor*u/65535 is >= the true d2
    a64 = anchors.astype(np.float64)[:, None]
    d64 = d2.astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        u = np.ceil(d64 * 65535.0 / a64)
    u = np.where(np.isfinite(u), u, 0.0)
    u = np.clip(u, 0.0, 65535.0).astype(np.int64)
    live = valid & (anchors[:, None] > 0)
    for _ in range(2):
        with np.errstate(invalid="ignore"):
            low = live & (a64 * u / 65535.0 < d64)
        if not low.any():
            break
        u = np.minimum(u + low.astype(np.int64), 65535)
    else:
        if (live & (a64 * u / 65535.0 < d64)).any():
            return None  # pathological rounding: serve f32 instead
    # only the interior slots (before the anchor) carry levels; the
    # anchor column always decodes to 65535, so it is elided entirely
    interior = slots[:, :k - 1] < (n_valid[:, None].astype(np.int64) - 1)
    u_int = np.where(interior, u[:, :k - 1], 0) if k > 1 \
        else np.zeros((m, 0), np.int64)
    # anchors as zigzag-varint ulp deltas down the batch (consecutive
    # rows of a clustered batch have near-equal kth distances)
    a_ulp = float_to_ordered_u32(anchors).astype(np.int64)
    a_delta = np.diff(a_ulp, prepend=np.int64(0))
    # valid ids as ONE flat zigzag-varint delta stream in distance order
    # (cross-row deltas included: neighbor lists of adjacent queries
    # overlap, which keeps even the row-boundary deltas short)
    flat_ids = idx[valid].astype(np.int64)
    id_delta = np.diff(flat_ids, prepend=np.int64(0))
    body = b"".join([
        _Q16_MAGIC, struct.pack("<BBIf", 1, k, m, pad_value),
        n_valid.tobytes(),
        _planes(u_int.astype(np.uint16), 2),
        _varint_encode(_zigzag(a_delta)),
        _varint_encode(_zigzag(id_delta)),
    ])
    return zlib.compress(body, _ZLIB_LEVEL)


def decode_candidates_q16(
        payload: bytes, m: int,
        k: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode to ``(d2_hi f32[m,k], d2_lo f32[m,k], idx i32[m,k])``.
    ``d2_hi`` / ``d2_lo`` bracket the true f32 distance per slot; the
    anchor slot (the row's kth valid distance), every pad slot, and
    exact zeros are bit-exact (``lo == hi``), so a single-contributor
    query's served row needs no re-fetch, and a contribution whose best
    ``lo`` exceeds another's exact kth provably cannot change the fold.
    """
    try:
        body = zlib.decompress(payload)
    except zlib.error as e:
        raise WireError(f"q16 body does not inflate: {e}") from e
    head = 2 + struct.calcsize("<BBIf")
    if len(body) < head or body[:2] != _Q16_MAGIC:
        raise WireError("q16 body missing magic")
    ver, kk, mm, pad_value = struct.unpack("<BBIf", body[2:head])
    if ver != 1 or kk != k or mm != m:
        raise WireError(f"q16 header mismatch: ver={ver} k={kk} (want {k}) "
                        f"m={mm} (want {m})")
    lev_bytes = 2 * m * (k - 1)
    if len(body) < head + m + lev_bytes:
        raise WireError(f"q16 body is {len(body)} bytes, want at least "
                        f"{head + m + lev_bytes}")
    n_valid = np.frombuffer(body[head:head + m], np.uint8).astype(np.int64)
    if (n_valid > k).any():
        raise WireError("q16 n_valid exceeds k")
    off = head + m
    u = np.zeros((m, k), np.int64)
    if k > 1:
        u[:, :k - 1] = _unplanes(body[off:off + lev_bytes],
                                 (m, k - 1), 2).astype(np.int64)
    off += lev_bytes
    a_zz, used = _varint_decode(body[off:], m)
    off += used
    id_zz, used = _varint_decode(body[off:], int(n_valid.sum()))
    off += used
    if off != len(body):
        raise WireError(f"q16 body has {len(body) - off} trailing bytes")
    anchors = ordered_u32_to_float(
        np.cumsum(_unzigzag(a_zz)).astype(np.uint32))
    slots = np.arange(k, dtype=np.int64)[None, :]
    mask = slots < n_valid[:, None]
    # the elided anchor column: level 65535 exactly (0 for a zero anchor)
    has = n_valid > 0
    rows = np.nonzero(has)[0]
    u[rows, n_valid[rows] - 1] = np.where(anchors[rows] > 0, 65535, 0)
    flat_ids = np.cumsum(_unzigzag(id_zz))
    ids = np.full((m, k), -1, np.int64)
    ids[mask] = flat_ids
    # bounds: the exact f64 expression the encoder certified against,
    # rounded outward into f32. Only the row's ACTUAL anchor slot (and
    # level 0 = an exact zero) carries lo == hi: an interior distance
    # within 1/65535 of the anchor also ceils to level 65535, and
    # handing it lo == anchor would overstate its lower bound above the
    # true d2 — the frontend's strict lo > kth test would then serve a
    # row verbatim that an exact fold could still change.
    anchor_slot = np.zeros((m, k), bool)
    anchor_slot[rows, n_valid[rows] - 1] = True
    a64 = anchors.astype(np.float64)[:, None]
    hi64 = a64 * u / 65535.0
    hi32 = hi64.astype(np.float32)
    lift = hi32.astype(np.float64) < hi64
    hi32 = np.where(lift, np.nextafter(hi32, np.float32(np.inf)), hi32)
    hi32 = np.where(u == 65535, anchors[:, None], hi32)
    # lower bound: encode guarantees u < d2*65535/anchor + 1, so the
    # true d2 strictly exceeds anchor*(u-1)/65535 in real arithmetic;
    # round down and shave one extra ulp to absorb the f64 slop
    lo64 = a64 * np.maximum(u - 1, 0) / 65535.0
    lo32 = lo64.astype(np.float32)
    drop = lo32.astype(np.float64) > lo64
    lo32 = np.where(drop, np.nextafter(lo32, np.float32(-np.inf)), lo32)
    lo32 = np.maximum(np.nextafter(lo32, np.float32(-np.inf)),
                      np.float32(0.0))
    exact = anchor_slot | (u == 0)
    lo32 = np.where(exact, hi32, lo32)
    d2_hi = np.where(mask, hi32, np.float32(pad_value)).astype("<f4")
    d2_lo = np.where(mask, lo32, np.float32(pad_value)).astype("<f4")
    idx = np.where(mask, ids, -1).astype("<i4")
    return d2_hi, d2_lo, idx


# -------------------------------------------------------- d16 slab codec


def encode_slab_chunk(pts: np.ndarray, level: int = 6) -> bytes:
    """Encode one chunk of Morton-sorted f32 rows, losslessly. Ladder:
    16-bit zigzag ulp deltas when every step fits, then 32-bit, then
    64-bit (sign-crossing steps zigzag up to 2^33), raw f32 when the
    transform + zlib does not actually shrink the chunk. Default zlib
    level 6 (not the wire default 1): slab pulls are bandwidth-bound,
    not encode-bound, so the extra effort pays."""
    pts = np.ascontiguousarray(pts, "<f4")
    m, dim = pts.shape
    if m == 0:
        return b"\x00"
    raw = memoryview(pts).cast("B")
    u = float_to_ordered_u32(pts).astype(np.int64)
    deltas = np.diff(u, axis=0)
    zz = _zigzag(deltas) if m > 1 else np.zeros((0, dim), np.uint64)
    # zigzag'd steps between ordered-u32 values span [0, 2^33): rows
    # that cross zero with |coord| > ~1 overflow a u32, so the ladder
    # tops out at 8-byte planes (the high planes are near-constant
    # zeros and vanish under zlib; the raw-f32 escape below still
    # catches chunks where the transform does not pay)
    zmax = 0 if zz.size == 0 else int(zz.max())
    width = 2 if zmax < 2 ** 16 else 4 if zmax < 2 ** 32 else 8
    # only the first row rides raw; zigzag ulp deltas carry the rest
    body = (_D16_MAGIC + struct.pack("<BBIH", 1, width, m, dim)
            + u[0].astype("<u4").tobytes()
            + _planes(zz.astype({2: np.uint16, 4: np.uint32,
                                 8: np.uint64}[width]), width))
    enc = zlib.compress(body, level)
    if len(enc) + 1 >= len(raw):
        return b"\x00" + bytes(raw)
    return b"\x01" + enc


def decode_slab_chunk(payload: bytes, m: int, dim: int) -> np.ndarray:
    """Inverse of ``encode_slab_chunk``; returns f32[m, dim] rows."""
    if not payload:
        raise WireError("empty slab chunk")
    flag, payload = payload[0], payload[1:]
    if flag == 0:
        if len(payload) != 4 * m * dim:
            raise WireError(f"raw slab chunk is {len(payload)} bytes, "
                            f"want {4 * m * dim}")
        return np.frombuffer(payload, "<f4").reshape(m, dim).copy()
    if flag != 1:
        raise WireError(f"unknown slab chunk flag {flag}")
    try:
        body = zlib.decompress(payload)
    except zlib.error as e:
        raise WireError(f"d16 chunk does not inflate: {e}") from e
    head = 2 + struct.calcsize("<BBIH")
    if len(body) < head or body[:2] != _D16_MAGIC:
        raise WireError("d16 chunk missing magic")
    ver, width, mm, dd = struct.unpack("<BBIH", body[2:head])
    if ver != 1 or mm != m or dd != dim or width not in (2, 4, 8):
        raise WireError(f"d16 header mismatch: ver={ver} width={width} "
                        f"rows={mm} (want {m}) dim={dd} (want {dim})")
    first_end = head + 4 * dim
    first = np.frombuffer(body[head:first_end], "<u4").astype(np.int64)
    zz = _unplanes(body[first_end:], (max(m - 1, 0), dim), width)
    deltas = _unzigzag(zz)
    u = np.concatenate([first[None, :], deltas], axis=0).cumsum(axis=0)
    if m == 0:
        return np.zeros((0, dim), "<f4")
    return np.ascontiguousarray(
        ordered_u32_to_float(u.astype(np.uint32)))


# ------------------------------------------------- chunked slab framing


def frame_chunk(rows: int, payload: bytes) -> bytes:
    """8-byte frame header for one /slab_rows chunk: the stream is sent
    with HTTP chunked transfer encoding (http.client hides the HTTP
    chunk boundaries), so the application re-frames: u32 payload bytes +
    u32 row count, then the payload."""
    return struct.pack("<II", len(payload), rows) + payload


def read_frames(read, total_rows: int):
    """Yield ``(rows, payload)`` frames from a ``read(n)`` callable until
    ``total_rows`` are consumed. Short reads raise ``WireError`` — a torn
    transfer surfaces as an error, never as a silently-short slab."""
    def read_exact(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = read(n - len(buf))
            if not got:
                raise WireError(
                    f"torn slab stream: wanted {n} more bytes, got EOF "
                    f"({total_rows - seen} rows still missing)")
            buf += got
        return buf

    seen = 0
    while seen < total_rows:
        nbytes, rows = struct.unpack("<II", read_exact(8))
        if rows == 0 or seen + rows > total_rows:
            raise WireError(f"bad slab frame: rows={rows} at {seen}"
                            f"/{total_rows}")
        payload = read_exact(nbytes)
        seen += rows
        yield rows, payload


# -------------------------------------------------- shared mutable state


class WireNegotiator:
    """Per-endpoint negotiated-codec table — the pod's shared negotiation
    state. The fan-out reads it on every dispatch; the health monitor /
    replica manager write it when hosts are scraped, adopted, or rebound,
    so access is lock-disciplined (lskcheck-proved via ``guarded_by``).
    """

    def __init__(self, mode: str = "auto"):
        if mode not in ("auto", "f32") + CANDIDATE_CODECS + SLAB_CODECS:
            raise ValueError(f"wire mode must be auto|f32|q16|d16, "
                             f"got {mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        #: url -> caps dict as advertised at the host's /stats root
        self.caps: guarded_by("_lock") = {}
        #: url -> {path: codec} resolved table
        self.negotiated: guarded_by("_lock") = {}

    def set_caps(self, url: str, caps: dict | None) -> None:
        url = url.rstrip("/")
        table = {path: negotiate(self.mode, caps, path)
                 for path in ("candidates", "slab_rows")}
        with self._lock:
            self.caps[url] = dict(caps or {})
            self.negotiated[url] = table

    def codec_for(self, url: str, path: str = "candidates") -> str:
        with self._lock:
            return self.negotiated.get(url.rstrip("/"), {}).get(path,
                                                                "f32")

    def snapshot(self) -> dict:
        with self._lock:
            return {"mode": self.mode,
                    "negotiated": {u: dict(t)
                                   for u, t in self.negotiated.items()}}


class WireStats:
    """Byte/row accounting per (path, codec) — the single source behind
    ``knn_wire_bytes_total{path=,codec=}`` on every surface. Handler
    threads and fan-out workers increment concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (path, codec) -> [bytes, rows]
        self.traffic: guarded_by("_lock") = {}

    def add(self, path: str, codec: str, nbytes: int,
            rows: int = 0) -> None:
        with self._lock:
            cell = self.traffic.setdefault((path, codec), [0, 0])
            cell[0] += int(nbytes)
            cell[1] += int(rows)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self.traffic.items())
        out: dict = {}
        for (path, codec), (nbytes, rows) in items:
            cell = out.setdefault(path, {})
            cell[codec] = {"bytes": nbytes, "rows": rows}
            if rows:
                cell[codec]["bytes_per_row"] = round(nbytes / rows, 2)
        return out

    def prometheus_lines(self) -> list[str]:
        from mpi_cuda_largescaleknn_tpu.obs.timers import (
            labeled_metric_lines,
        )

        snap = self.snapshot()
        cells = [({"path": path, "codec": codec}, cell)
                 for path, codecs in snap.items()
                 for codec, cell in codecs.items()]
        return (
            labeled_metric_lines(
                "knn_wire_bytes_total",
                ((lab, cell["bytes"]) for lab, cell in cells))
            + labeled_metric_lines(
                "knn_wire_rows_total",
                ((lab, cell["rows"]) for lab, cell in cells))
            + labeled_metric_lines(
                "knn_wire_bytes_per_row",
                ((lab, cell["bytes_per_row"]) for lab, cell in cells
                 if "bytes_per_row" in cell), kind="gauge"))
