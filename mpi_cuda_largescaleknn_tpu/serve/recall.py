"""Recall-SLO approximate tier: per-request recall targets -> cheap plans.

Every PR before this one kept the serving stack exhaustively exact; this
module is the quality-vs-cost axis. A request may carry
``"recall": 0.95`` and the server trades a measured, calibrated epsilon
of recall for throughput by selecting a cheaper execution plan:

- ``skip_rescore`` — one-pass bf16 MXU scoring with the exact-rescore
  pass dropped (ops/distance.py ``score_tile``; only engages at
  D >= ``mxu_min_dim``, below which elementwise-exact IS the fast path);
- ``prune_shrink`` — tighten the traversal's kth-distance early exit so
  border buckets are skipped (ops/tiled.py ``knn_update_tiled``);
- ``visit_frac`` — hard-cap the nearest-first bucket schedule at a
  fraction of its visit steps (the aggressive truncation lever: the
  nearest buckets are walked first, so the cut lands on the candidate
  tail);
- ``route_slack`` — in routed pods, escalate to an unvisited host only
  when its bound beats the kth distance by the slack margin
  (serve/frontend.py ``RoutedPodFanout``), shaving escalation waves;
- ``stream_skip_cold`` — in streaming mode, serve from already
  device-resident slabs and skip cold promotions whose bounds cannot
  beat the plan-scaled kth distance (serve/slabpool.py) — turning
  promotion stalls into recall, a knob no exact system has.

All program-shaped knobs are trace-time statics, so each plan is its own
AOT executable (the engine keys its caches on ``program_key()``) and the
exact default path's compiled program is byte-identical to the pre-tier
engine. ``RecallPolicy`` maps a target to the CHEAPEST plan whose
CALIBRATED recall meets it; calibration comes from
``tools/recall_harness.py`` (oracle sampling against the exact engine
per workload shape), whose measured curves also gate the claimed targets
in CI (``serve_smoke.py --recall-bench`` -> ``recall_compare``).

Exact stays the default: no ``recall`` field (or any target >= 1.0)
means ``plan_for`` returns ``None`` and every downstream layer takes the
pre-existing exact code path, bit for bit.
"""

from __future__ import annotations

import json
import threading
from collections import Counter
from dataclasses import asdict, dataclass, replace

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by


@dataclass(frozen=True)
class RecallPlan:
    """One approximate execution plan: the knob vector plus its
    calibrated recall. Frozen — a plan is a value; per-request targets
    ride a ``replace(plan, recall_target=...)`` copy so concurrent
    requests can never mutate a shared plan."""

    name: str = "exact"
    #: (a) one-pass bf16 score, exact rescore skipped (D >= mxu_min_dim)
    skip_rescore: bool = False
    #: (b) kth-distance early-exit radius factor, (0, 1]; 1.0 = exact
    prune_shrink: float = 1.0
    #: (b) nearest-first visit-schedule cap, (0, 1]; 1.0 = full schedule
    visit_frac: float = 1.0
    #: (c) routed escalation slack, [0, 1); escalate only when
    #: lb_safe <= kth2 * (1 - route_slack). 0.0 = exact certification
    route_slack: float = 0.0
    #: (d) streaming: serve resident slabs, skip bounds-beaten cold
    #: promotions instead of stalling on them
    stream_skip_cold: bool = False
    #: the SLO that selected this plan (echoed in the response)
    recall_target: float = 1.0
    #: calibrated recall claim (min over calibrated workloads, margin
    #: applied by the harness); gates plan selection AND the CI bench
    recall_estimated: float = 1.0

    def __post_init__(self):
        if not 0.0 < self.prune_shrink <= 1.0:
            raise ValueError(f"prune_shrink in (0, 1], got "
                             f"{self.prune_shrink}")
        if not 0.0 < self.visit_frac <= 1.0:
            raise ValueError(f"visit_frac in (0, 1], got {self.visit_frac}")
        if not 0.0 <= self.route_slack < 1.0:
            raise ValueError(f"route_slack in [0, 1), got "
                             f"{self.route_slack}")
        if not 0.0 < self.recall_estimated <= 1.0:
            raise ValueError(f"recall_estimated in (0, 1], got "
                             f"{self.recall_estimated}")

    @property
    def is_exact(self) -> bool:
        """True iff every knob is inert — the plan cannot change any bit
        of the exact path's answer."""
        return (not self.skip_rescore and self.prune_shrink >= 1.0
                and self.visit_frac >= 1.0 and self.route_slack <= 0.0
                and not self.stream_skip_cold)

    def program_key(self) -> tuple:
        """The trace-time knobs that change the COMPILED program — this
        tuple joins the engine's AOT executable-cache keys (both the
        local table and the shared ``ExecutableCache``), so plans can
        never collide on an executable and slab churn per plan still
        compiles once per shape class."""
        return (bool(self.skip_rescore), float(self.prune_shrink),
                float(self.visit_frac))

    def batch_key(self) -> tuple:
        """Everything that forbids coalescing two requests into one
        engine batch (the batcher's plan-keyed sub-batching): the
        program knobs plus the dispatch-time routing/streaming knobs.
        ``recall_target`` is deliberately absent — two requests on the
        same plan at different targets share every executed bit."""
        return self.program_key() + (float(self.route_slack),
                                     bool(self.stream_skip_cold))

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, obj: dict) -> "RecallPlan":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in obj.items() if k in known})


#: the inert plan — selecting it is equivalent to no plan at all
EXACT_PLAN = RecallPlan()

#: built-in conservative defaults, CHEAPEST FIRST (the policy scans in
#: order and takes the first plan meeting the target). The knob budget
#: is deliberately prune-heavy: shrinking the kth-radius early exit cuts
#: the CERTIFICATION tail (bound-checking buckets that rarely hold a
#: winner — sparse big-box buckets the kth radius sweeps through) while
#: the nearest-first schedule keeps the dense buckets where true
#: neighbors live, so deep shrinks trade far less recall per saved visit
#: than deep visit caps do. The recall_estimated claims are deliberate
#: FLOORS beneath what tools/recall_harness.py measures on the
#: uniform/clustered/sweep workload shapes at the reference fixture
#: (D=3, k=16, bucket_size 64: worst-workload measured recall 0.91 /
#: 0.97 / 0.999 for the three plans below, the worst always uniform),
#: so the claims survive index shapes rougher than the fixture; CI
#: re-measures them end to end (serve_smoke --recall-bench). The floors
#: do NOT survive k far above the reference (k=64 halves approx-fast's
#: uniform recall — a deep heap needs far more of the visit schedule to
#: fill its tail), which is why the table is K-CONDITIONED: servers
#: select ``default_plans_for_k(engine.k)``, and deep-k fixtures get the
#: conservative knob vectors below. For calibrated, fixture-specific
#: claims run tools/recall_harness.py at YOUR fixture's k and load its
#: output via --recall-policy (docs/SERVING.md "Recall-SLO tier",
#: docs/TUNING.md "Recall plans vs k").
DEFAULT_PLANS = (
    RecallPlan(name="approx-fast", skip_rescore=True, prune_shrink=0.10,
               visit_frac=0.25, route_slack=0.30, stream_skip_cold=True,
               recall_estimated=0.85),
    RecallPlan(name="approx-balanced", skip_rescore=True,
               prune_shrink=0.30, visit_frac=0.50, route_slack=0.15,
               stream_skip_cold=True, recall_estimated=0.95),
    RecallPlan(name="approx-near", skip_rescore=True, prune_shrink=0.60,
               visit_frac=0.85, route_slack=0.05, stream_skip_cold=True,
               recall_estimated=0.99),
)

#: deep-k (k >= DEEP_K_THRESHOLD) defaults: the kth distance of a deep
#: heap is far out in the candidate tail, so the same prune/visit cuts
#: that cost ~0.1 recall at k=16 amputate half the true set at k=64+.
#: Every knob here is the shallow table's NEXT step up (approx-fast
#: inherits approx-balanced's knob vector claimed a tier lower, and so
#: on), keeping the same three-target ladder at honest floors. Measured
#: at the reference fixture scaled to k=64: 0.88 / 0.97 / 0.995
#: worst-workload (uniform) — the claims below stay beneath that
DEFAULT_PLANS_DEEP_K = (
    RecallPlan(name="approx-fast", skip_rescore=True, prune_shrink=0.30,
               visit_frac=0.50, route_slack=0.15, stream_skip_cold=True,
               recall_estimated=0.85),
    RecallPlan(name="approx-balanced", skip_rescore=True,
               prune_shrink=0.50, visit_frac=0.70, route_slack=0.10,
               stream_skip_cold=True, recall_estimated=0.95),
    RecallPlan(name="approx-near", skip_rescore=True, prune_shrink=0.75,
               visit_frac=0.92, route_slack=0.03, stream_skip_cold=True,
               recall_estimated=0.99),
)

#: k at which the deep-k table takes over for built-in defaults
DEEP_K_THRESHOLD = 64


def default_plans_for_k(k: int | None) -> tuple:
    """The built-in plan table conditioned on the fixture's k. ``None``
    (k unknown — e.g. a custom query_fn with no engine) stays on the
    shallow table: it only changes which UNCALIBRATED floor applies, and
    the shallow floors are the documented legacy behavior."""
    if k is not None and k >= DEEP_K_THRESHOLD:
        return DEFAULT_PLANS_DEEP_K
    return DEFAULT_PLANS


class RecallPolicy:
    """Target -> plan mapping with selection accounting.

    ``plans`` is an ordered cheapest-first tuple; ``plan_for(target)``
    returns the first plan whose calibrated ``recall_estimated`` meets
    the target (as a copy carrying the request's target), or ``None``
    when the target is absent / >= 1.0 / unmeetable — ``None`` IS the
    exact tier, and callers must treat it as "take the pre-existing
    path". The policy itself is immutable after construction; only the
    selection counters mutate, under ``_lock``.
    """

    def __init__(self, plans=DEFAULT_PLANS, source: str = "builtin"):
        plans = tuple(plans)
        for p in plans:
            if p.is_exact:
                raise ValueError(f"plan {p.name!r} is exact — the exact "
                                 "tier is plan_for()'s None, not a table "
                                 "entry")
        if list(plans) != sorted(plans, key=lambda p: p.recall_estimated):
            raise ValueError("plans must be ordered cheapest "
                             "(lowest recall_estimated) first")
        self.plans = plans
        self.source = source
        self._lock = threading.Lock()
        #: selections per plan name ("exact" = target absent/unmeetable)
        self.selected: guarded_by("_lock") = Counter()

    def plan_for(self, target: float | None) -> RecallPlan | None:
        if target is not None and not 0.0 < target <= 1.0:
            raise ValueError(f"recall target must be in (0, 1], "
                             f"got {target}")
        chosen = None
        if target is not None and target < 1.0:
            for plan in self.plans:
                if plan.recall_estimated >= target:
                    chosen = replace(plan, recall_target=float(target))
                    break
        with self._lock:
            self.selected[chosen.name if chosen else "exact"] += 1
        return chosen

    def stats(self) -> dict:
        with self._lock:
            selected = dict(self.selected)
        return {
            "source": self.source,
            "plans": [{"name": p.name,
                       "recall_estimated": p.recall_estimated,
                       "skip_rescore": p.skip_rescore,
                       "prune_shrink": p.prune_shrink,
                       "visit_frac": p.visit_frac,
                       "route_slack": p.route_slack,
                       "stream_skip_cold": p.stream_skip_cold}
                      for p in self.plans],
            "selected": selected,
        }

    # ------------------------------------------------------------- loading

    @classmethod
    def from_dict(cls, obj: dict, source: str = "dict") -> "RecallPolicy":
        """Load a calibration table (tools/recall_harness.py output or a
        hand-written equivalent): ``{"plans": [{...knobs...,
        "recall_estimated": r}, ...]}``. Plans are re-sorted cheapest
        first so a harness sweep can be dumped in any order."""
        plans = [RecallPlan.from_json(p) for p in obj.get("plans", [])]
        plans.sort(key=lambda p: p.recall_estimated)
        return cls(tuple(plans), source=source)

    @classmethod
    def from_file(cls, path: str) -> "RecallPolicy":
        with open(path) as f:
            return cls.from_dict(json.load(f), source=path)

    @classmethod
    def for_k(cls, k: int | None) -> "RecallPolicy":
        """Built-in defaults conditioned on the served fixture's k —
        what the servers construct when no --recall-policy table is
        loaded. Deep k (>= DEEP_K_THRESHOLD) switches to the
        conservative knob ladder; see DEFAULT_PLANS_DEEP_K."""
        deep = k is not None and k >= DEEP_K_THRESHOLD
        return cls(default_plans_for_k(k),
                   source="builtin:deep-k" if deep else "builtin")


def measured_recall(approx_idx, exact_idx) -> float:
    """Mean per-query recall of an approximate id matrix against the
    exact one: |approx ∩ exact| / k averaged over rows. The one recall
    definition shared by the harness, the bench, and the tests (numpy
    arrays [n, k]; -1 pad ids in the approximate rows never match)."""
    import numpy as np

    a = np.asarray(approx_idx)
    e = np.asarray(exact_idx)
    if a.shape != e.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {e.shape}")
    n, k = a.shape
    hits = 0
    for i in range(n):
        hits += len(set(a[i].tolist()) & set(e[i].tolist()))
    return hits / float(n * k) if n and k else 1.0
