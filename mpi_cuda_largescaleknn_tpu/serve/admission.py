"""Admission control: bounded queue, backpressure, graceful degradation.

A serving process that queues without bound converts overload into
unbounded latency for everyone (and eventually OOM). This layer rejects at
the door instead: ``AdmissionController`` tracks in-flight rows against a
hard cap and raises ``OverloadError`` — the HTTP layer maps it to a
429-style response with Retry-After, so clients shed load and the resident
engine keeps serving at its max throughput.

``GracefulQueryFn`` wraps the engine with the runtime fallback the ISSUE
requires: if the Pallas kernel raises at runtime (driver regression, lowering
bug on a new shape), the engine degrades to the XLA twin — identical results
by the twin-engine contract — and the failure is recorded in stats rather
than taking the service down.
"""

from __future__ import annotations

import threading

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by


class OverloadError(RuntimeError):
    """Server at capacity — client should retry after ``retry_after_s``."""

    def __init__(self, msg: str, retry_after_s: float = 0.05):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before (or while) it executed."""


class AdmissionController:
    """Row-granular bounded admission.

    Rows, not requests: one 1024-row request costs the engine what 1024
    singletons do, so the cap must count what the engine pays for.

    The cap covers QUEUED + IN-FLIGHT rows by construction: ``admit``
    reserves before a request enters the batcher queue and ``release``
    fires only after its response demuxes, so rows dispatched on the device
    under a deep pipeline stay counted the whole way. Sizing note for
    pipelined serving: the device pipeline can hold up to
    ``pipeline_depth * max_batch`` rows beyond the waiting queue, so
    ``max_queue_rows`` below ``(pipeline_depth + 1) * max_batch`` caps
    pipeline occupancy before the admission cap ever matters.
    ``pipeline_rows_fn`` (wired by the server to the batcher) splits the
    aggregate into its dispatched-on-device component for stats/metrics.
    """

    def __init__(self, max_queue_rows: int = 4096,
                 default_timeout_s: float = 5.0):
        self.max_queue_rows = int(max_queue_rows)
        self.default_timeout_s = float(default_timeout_s)
        self._lock = threading.Lock()
        # shared across every handler thread: lskcheck proves each access
        # happens under the declared lock (docs/ANALYSIS.md)
        self._inflight_rows: guarded_by("_lock") = 0
        self.admitted: guarded_by("_lock") = 0
        self.rejected: guarded_by("_lock") = 0
        #: optional () -> int: rows currently dispatched on the device
        #: (batcher.inflight_rows); reported in stats, not used for capping
        self.pipeline_rows_fn = None

    def admit(self, n_rows: int) -> None:
        """Reserve ``n_rows`` of queue budget or raise ``OverloadError``.
        Callers MUST pair with ``release`` (use ``admitted_rows``)."""
        with self._lock:
            if self._inflight_rows + n_rows > self.max_queue_rows:
                self.rejected += 1
                raise OverloadError(
                    f"queue full ({self._inflight_rows}/"
                    f"{self.max_queue_rows} rows in flight)")
            self._inflight_rows += n_rows
            self.admitted += 1

    def release(self, n_rows: int) -> None:
        with self._lock:
            self._inflight_rows -= n_rows

    def admitted_rows(self, n_rows: int):
        """Context manager form of admit/release."""
        return _Admitted(self, n_rows)

    def inflight_rows(self) -> int:
        with self._lock:
            return self._inflight_rows

    def stats(self) -> dict:
        with self._lock:
            out = {"inflight_rows": self._inflight_rows,
                   "max_queue_rows": self.max_queue_rows,
                   "admitted": self.admitted,
                   "rejected": self.rejected}
        if self.pipeline_rows_fn is not None:
            out["pipeline_inflight_rows"] = int(self.pipeline_rows_fn())
        return out


class _Admitted:
    def __init__(self, ctrl: AdmissionController, n_rows: int):
        self._ctrl = ctrl
        self._n = n_rows

    def __enter__(self):
        self._ctrl.admit(self._n)
        return self

    def __exit__(self, *exc):
        self._ctrl.release(self._n)
        return False


class GracefulQueryFn:
    """Engine call with one-shot degradation to the XLA twin.

    On the first non-overload exception from a degradable engine
    (``pallas_tiled``), swap to ``tiled`` and retry the same batch once.
    The twin compiles per shape bucket on first use after degradation
    (counted in ``compile_count`` like any compile); results are identical
    by the twin-engine contract, so callers never observe the swap except
    through stats.

    The ``dispatch``/``complete`` pair mirrors the engine's pipelined
    split. Async dispatch moves failure to where the result is FETCHED, so
    a mid-stream Pallas failure surfaces in ``complete`` for a batch whose
    dispatch already succeeded — the in-flight handle retains its host
    queries and is replayed synchronously on the (now degraded) engine. A
    stale handle that was dispatched on the old engine but fails after a
    concurrent batch already triggered the degradation is replayed without
    counting a second degradation; every queued batch therefore drains to a
    correct answer, never an error, as long as the twin works.
    """

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self.failures: guarded_by("_lock") = 0

    def _degrade_or_raise(self, e: Exception, handle=None) -> None:
        """Record a failure; degrade if possible, else re-raise ``e``.

        Returns (instead of raising) when a replay can succeed: either this
        failure triggered the degradation, or the engine was ALREADY
        degraded after ``handle`` was dispatched (its recorded engine name
        differs from the current one).
        """
        with self._lock:
            self.failures += 1
            if self.engine.can_degrade():
                self.engine.degrade(f"{type(e).__name__}: {e}")
            elif (handle is None or getattr(handle, "engine_name", None)
                    == self.engine.engine_name):
                raise e

    def _query(self, queries, plan, tenant=None, seed_radius=None):
        # exact single-index requests use the legacy single-arg form so
        # engines (and test doubles) without a plan/tenant kwarg keep
        # working — the batcher's compatibility rule, applied to the
        # degradation shim too. Certified radius seeds (serve/qcache.py)
        # ride the same conditional-kwarg rule: only engines actually
        # handed seeds need to understand ``seed_radius``.
        kw = {} if seed_radius is None else {"seed_radius": seed_radius}
        if tenant is not None:
            return self.engine.query(queries, plan=plan, tenant=tenant, **kw)
        return (self.engine.query(queries, **kw) if plan is None
                else self.engine.query(queries, plan=plan, **kw))

    def __call__(self, queries, plan=None, tenant=None, seed_radius=None):
        try:
            return self._query(queries, plan, tenant, seed_radius)
        except Exception as e:  # noqa: BLE001 - re-raised unless degradable
            self._degrade_or_raise(e)
            # the degraded replay runs UNSEEDED: seeds never change the
            # answer, so dropping them is sound — and it keeps the replay
            # maximally conservative while the engine is already hurt
            return self._query(queries, plan, tenant)

    def _dispatch(self, queries, plan, tenant=None, seed_radius=None):
        kw = {} if seed_radius is None else {"seed_radius": seed_radius}
        if tenant is not None:
            return self.engine.dispatch(queries, plan=plan, tenant=tenant,
                                        **kw)
        return (self.engine.dispatch(queries, **kw) if plan is None
                else self.engine.dispatch(queries, plan=plan, **kw))

    def dispatch(self, queries, plan=None, tenant=None, seed_radius=None):
        try:
            return self._dispatch(queries, plan, tenant, seed_radius)
        except Exception as e:  # noqa: BLE001 - re-raised unless degradable
            self._degrade_or_raise(e)
            return self._dispatch(queries, plan, tenant)

    def complete(self, handle):
        try:
            return self.engine.complete(handle)
        except Exception as e:  # noqa: BLE001 - re-raised unless degradable
            self._degrade_or_raise(e, handle)
            # replay the retained host queries synchronously on the current
            # (degraded) engine — exact by the twin-engine contract, under
            # the SAME recall plan (and tenant namespace) the handle was
            # dispatched with
            return self._query(handle.queries,
                               getattr(handle, "plan", None),
                               getattr(handle, "tenant", None))
