"""Stdlib-HTTP serving front end: /knn, /healthz, /stats, /metrics.

No web framework (the container bakes no deps beyond the jax toolchain):
``http.server.ThreadingHTTPServer`` with one handler thread per connection.
Handler threads only parse, admit, and block on the batcher's demux event —
all engine dispatch happens on the batcher's dispatch worker (plus one
completion worker for the host merge when ``pipeline_depth > 1``), so JAX
dispatch stays single-threaded no matter how many clients connect.

Request formats on POST /knn:
- JSON  (default): ``{"queries": [[x,y,z], ...], "neighbors": true?,
  "timeout_ms": 250?}`` -> ``{"dists": [...], "neighbors": [[...], ...]?}``
- binary (Content-Type: application/octet-stream): little-endian f32
  x,y,z triples; response is raw f32 distances. Options ride the query
  string (``/knn?neighbors=1&timeout_ms=250`` — neighbors only in JSON).

Multi-index tenancy (serve/tenancy.py): when the engine carries a tenant
registry, ``POST /v1/<tenant>/knn`` (or a ``"tenant"`` JSON field /
``X-Knn-Tenant`` header for the binary codec) routes to that tenant's
index; legacy ``/knn`` resolves to the default tenant, unknown tenants
404, and per-tenant admission quotas 429 with Retry-After. Single-index
servers are byte-identical to the pre-tenancy wire.

Error mapping: queue full -> 429 + Retry-After (admission backpressure),
deadline -> 504, batch wider than max_batch -> 413, bad input -> 400.
/metrics is Prometheus text fed by obs/timers.py's LatencyHistogram.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.obs.timers import LatencyHistogram
from mpi_cuda_largescaleknn_tpu.serve.admission import (
    AdmissionController,
    DeadlineExceeded,
    GracefulQueryFn,
    OverloadError,
)
from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher
from mpi_cuda_largescaleknn_tpu.serve.engine import UnservableShapeError
from mpi_cuda_largescaleknn_tpu.serve.faults import (
    FaultInjector,
    apply_http_fault,
)
from mpi_cuda_largescaleknn_tpu.serve.qcache import QueryCache
from mpi_cuda_largescaleknn_tpu.serve.recall import RecallPolicy
from mpi_cuda_largescaleknn_tpu.serve.tenancy import TenantQuotas


def parse_knn_body(path: str, headers, rfile, dim: int = 3):
    """Parse one POST /knn request (shared with the pod front end).

    ``dim`` is the serving index's point dimensionality (the engine's
    ``dim`` attribute — the stack is D-generic; 3 is just the default).
    -> (queries f32[n,dim], want_neighbors, timeout_s, recall, tenant,
    binary).

    ``recall`` is the request's recall-SLO target (serve/recall.py): the
    JSON body's ``"recall": 0.95`` key, or ``recall=0.95`` on the query
    string (the binary codec's only option channel). ``None`` — the
    default — means exact; values outside (0, 1] are a 400.

    ``tenant`` is the request's index namespace (serve/tenancy.py): the
    JSON body's ``"tenant"`` key, or the ``X-Knn-Tenant`` header — the
    binary codec's channel. A tenant in the URL (``/v1/<t>/knn``) is
    resolved by the caller and takes precedence over both. ``None`` on a
    multi-tenant server means the default tenant; on a single-index
    server the field is ignored (the pre-tenancy wire is unchanged)."""
    qs = parse_qs(urlparse(path).query)
    length = int(headers.get("Content-Length", 0))
    raw = rfile.read(length)
    ctype = (headers.get("Content-Type") or "").split(";")[0].strip()
    timeout_ms = float(qs.get("timeout_ms", [0])[0] or 0)
    neighbors = qs.get("neighbors", ["0"])[0] not in ("0", "", "false")
    recall_qs = qs.get("recall", [None])[0]
    recall = float(recall_qs) if recall_qs not in (None, "") else None
    tenant = headers.get("X-Knn-Tenant") or None
    if ctype == "application/octet-stream":
        if len(raw) % (4 * dim):
            raise ValueError(
                f"binary body must be n*{4 * dim} bytes (f32 x{dim})")
        q = np.frombuffer(raw, "<f4").reshape(-1, dim)
        return (q, neighbors, timeout_ms / 1e3, _check_recall(recall),
                tenant, True)
    obj = json.loads(raw.decode() or "{}")
    q = np.asarray(obj.get("queries", []), np.float32)
    if q.size == 0:
        q = q.reshape(0, dim)
    if q.ndim != 2 or q.shape[1] != dim:
        raise ValueError(f"queries must be [n, {dim}], got {list(q.shape)}")
    if not np.all(np.isfinite(q)):
        raise ValueError("queries must be finite")
    timeout_ms = float(obj.get("timeout_ms", timeout_ms) or 0)
    if obj.get("recall") is not None:
        recall = float(obj["recall"])
    if obj.get("tenant"):
        tenant = str(obj["tenant"])
    return (q, bool(obj.get("neighbors", neighbors)), timeout_ms / 1e3,
            _check_recall(recall), tenant, False)


def _check_recall(recall: float | None) -> float | None:
    if recall is not None and not 0.0 < recall <= 1.0:
        raise ValueError(f"recall target must be in (0, 1], got {recall}")
    return recall


def slab_pool_prometheus_lines(engine_stats: dict) -> list[str]:
    """Prometheus lines for the tiered slab index (serve/slabpool.py),
    empty when the engine is fully resident. Shared by the single-host
    server's /metrics and the routed host's (serve/frontend.py), so the
    pool reads the same on every serving tier."""
    pool = engine_stats.get("slab_pool")
    if not pool:
        return []
    return [
        "# TYPE knn_slab_pool_resident gauge",
        f'knn_slab_pool_resident{{tier="device"}} '
        f'{pool["device_resident"]}',
        f'knn_slab_pool_resident{{tier="host"}} {pool["host_resident"]}',
        "# TYPE knn_slab_pool_device_bytes gauge",
        f'knn_slab_pool_device_bytes {pool["device_bytes_used"]}',
        "# TYPE knn_slab_pool_device_budget_bytes gauge",
        f'knn_slab_pool_device_budget_bytes '
        f'{pool["device_budget_bytes"]}',
        "# TYPE knn_slab_promotions_total counter",
        f'knn_slab_promotions_total {pool["promotions"]}',
        "# TYPE knn_slab_evictions_total counter",
        f'knn_slab_evictions_total {pool["evictions"]}',
        "# TYPE knn_stream_stalls_total counter",
        f'knn_stream_stalls_total {pool["stream_stalls"]}',
        "# TYPE knn_stream_stall_seconds_total counter",
        f'knn_stream_stall_seconds_total {pool["stream_stall_seconds"]}',
        "# TYPE knn_slab_pool_hits_total counter",
        f'knn_slab_pool_hits_total{{tier="device"}} {pool["device_hits"]}',
        f'knn_slab_pool_hits_total{{tier="host"}} {pool["host_hits"]}',
        "# TYPE knn_slab_pool_cold_reads_total counter",
        f'knn_slab_pool_cold_reads_total {pool["cold_reads"]}',
        "# TYPE knn_slab_prefetch_enqueued_total counter",
        f'knn_slab_prefetch_enqueued_total {pool["prefetch_enqueued"]}',
    ] + _streaming_prometheus_lines(engine_stats)


def _tenant_prometheus_lines(srv, engine_stats: dict) -> list[str]:
    """Per-tenant slab-pool occupancy/stall shares and admission-quota
    state for /metrics (``knn_*{tenant=...}``) — empty on single-index
    servers, so their text output is byte-identical to pre-tenancy."""
    if getattr(srv, "tenants", None) is None:
        return []
    lines = []
    pool_t = engine_stats.get("slab_pool", {}).get("tenants") or {}
    if pool_t:
        lines += ["# TYPE knn_slab_pool_tenant_resident gauge"]
        for t in sorted(pool_t):
            for tier, key in (("device", "device_resident"),
                              ("host", "host_resident")):
                lines += [f'knn_slab_pool_tenant_resident{{tenant="{t}",'
                          f'tier="{tier}"}} {pool_t[t].get(key, 0)}']
        for metric, key in (
                ("knn_slab_tenant_promotions_total", "promotions"),
                ("knn_slab_tenant_evictions_total", "evictions"),
                ("knn_slab_tenant_cold_reads_total", "cold_reads"),
                ("knn_stream_tenant_stalls_total", "stream_stalls"),
                ("knn_stream_tenant_stall_seconds_total",
                 "stream_stall_seconds")):
            lines += [f"# TYPE {metric} counter"] + [
                f'{metric}{{tenant="{t}"}} {pool_t[t].get(key, 0)}'
                for t in sorted(pool_t)]
    if srv.quotas is not None:
        qs = srv.quotas.stats()
        qt = qs["tenants"]
        if qt:
            for metric, key, kind in (
                    ("knn_tenant_quota_rows", "quota_rows", "gauge"),
                    ("knn_tenant_inflight_rows", "inflight_rows", "gauge"),
                    ("knn_tenant_quota_rejected_total", "rejected",
                     "counter")):
                lines += [f"# TYPE {metric} {kind}"] + [
                    f'{metric}{{tenant="{t}"}} {qt[t][key]}'
                    for t in sorted(qt)]
    return lines


def qcache_prometheus_lines(qcache) -> list[str]:
    """Prometheus lines for the certified query cache (serve/qcache.py):
    the four reuse counters (+ size/insert gauges), each with a
    ``{tenant=}`` twin per tenant on multi-tenant servers. Empty when the
    cache is off — cache-off servers' /metrics text is unchanged. Shared
    by the single-host server and the pod front end."""
    if qcache is None:
        return []
    qs = qcache.stats()
    tenants = qs["tenants"]
    lines = []
    for metric, key in (("knn_qcache_hits_total", "hits"),
                        ("knn_qcache_seeds_total", "seeds"),
                        ("knn_qcache_dedup_rows_total", "dedup_rows"),
                        ("knn_qcache_evictions_total", "evictions")):
        lines += [f"# TYPE {metric} counter", f"{metric} {qs[key]}"]
        lines += [f'{metric}{{tenant="{t}"}} {tenants[t][key]}'
                  for t in sorted(tenants)]
    for metric, key in (("knn_qcache_misses_total", "misses"),
                        ("knn_qcache_inserts_total", "inserts"),
                        ("knn_qcache_inflight_aborts_total",
                         "inflight_aborts")):
        lines += [f"# TYPE {metric} counter", f"{metric} {qs[key]}"]
    for metric, key in (("knn_qcache_size_rows", "size_rows"),
                        ("knn_qcache_capacity_rows", "capacity_rows"),
                        ("knn_qcache_inflight_rows", "inflight_rows")):
        lines += [f"# TYPE {metric} gauge", f"{metric} {qs[key]}"]
    return lines


def _streaming_prometheus_lines(engine_stats: dict) -> list[str]:
    streaming = engine_stats.get("streaming")
    if not streaming:
        return []
    return [
        # recall-SLO tier (serve/recall.py stream_skip_cold): cold-slab
        # promotions given up for recall instead of stalled on — the
        # "stalls into recall" trade as a number
        "# TYPE knn_stream_skipped_promotions_total counter",
        f"knn_stream_skipped_promotions_total "
        f"{streaming['skipped_promotions']}",
        # drift guard (PR 17): skip-cold plans refused because the pool
        # was already stalling above the admission limit
        "# TYPE knn_stream_skip_cold_refusals_total counter",
        f"knn_stream_skip_cold_refusals_total "
        f"{streaming.get('skip_cold_refusals', 0)}",
    ]


#: knn_recall_estimated histogram upper edges (plan-level calibrated
#: recall per approximate request); +Inf bucket rides implicitly
RECALL_HIST_EDGES = (0.5, 0.8, 0.9, 0.95, 0.99, 1.0)


def recall_response_fields(plan, recall):
    """The response surface of one request's recall resolution, shared by
    the single-host server and the pod front end: ``(json_fields,
    binary_headers)``. Exact requests (no target at all) get neither —
    the pre-tier wire stays byte-identical. A target served EXACTLY
    (recall=1.0, or a target no calibrated plan meets) is answered
    ``exact: true`` with a 1.0 estimate — serving exact always meets any
    target."""
    if plan is None:
        if recall is None:
            return {}, []
        return ({"exact": True, "recall_target": float(recall),
                 "recall_estimated": 1.0},
                [("X-Knn-Exact", "1"),
                 ("X-Knn-Recall-Target", f"{recall:g}"),
                 ("X-Knn-Recall-Estimated", "1")])
    return ({"exact": False, "recall_target": float(plan.recall_target),
             "recall_estimated": float(plan.recall_estimated),
             "recall_plan": plan.name},
            [("X-Knn-Exact", "0"),
             ("X-Knn-Recall-Target", f"{plan.recall_target:g}"),
             ("X-Knn-Recall-Estimated", f"{plan.recall_estimated:g}"),
             ("X-Knn-Recall-Plan", plan.name)])


class ServingMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        # increments come from every handler thread; readers (the /stats
        # and /metrics renderers) take dict(...) copies — a point-in-time
        # copy of int counters is the intended snapshot semantics
        self.counters: guarded_by("_lock") = {
            "knn_requests_total": 0, "knn_rows_total": 0,
            "knn_overload_total": 0, "knn_deadline_total": 0,
            "knn_badrequest_total": 0, "knn_error_total": 0}
        self.latency = LatencyHistogram()
        # recall-SLO tier accounting: requests per tier plus a fixed-edge
        # histogram of the approximate responses' calibrated
        # recall_estimated (plan-level — every row of an approx request
        # shares its plan's claim)
        self.recall_tiers: guarded_by("_lock") = {"exact": 0, "approx": 0}
        self.recall_hist: guarded_by("_lock") = (
            [0] * (len(RECALL_HIST_EDGES) + 1))
        self.recall_hist_sum: guarded_by("_lock") = 0.0
        # multi-index tenancy: the same counter families keyed per tenant
        # ({tenant: {name: count}}) plus a per-tenant latency histogram —
        # empty (and never rendered) on single-index servers
        self.tenant_counters: guarded_by("_lock") = {}
        self.tenant_latency: guarded_by("_lock") = {}

    def snapshot(self) -> dict:
        """Locked point-in-time copy — what cross-object readers use
        (the guarded_by proof is self-rooted; see docs/ANALYSIS.md)."""
        with self._lock:
            return dict(self.counters)

    def inc(self, name: str, by: int = 1, tenant: str | None = None):
        with self._lock:
            # setdefault-style: endpoint-specific counters (e.g. the routed
            # hosts' knn_routed_rows_total) appear on first increment
            self.counters[name] = self.counters.get(name, 0) + by
            if tenant is not None:
                tc = self.tenant_counters.setdefault(tenant, {})
                tc[name] = tc.get(name, 0) + by

    def record_latency(self, seconds: float, tenant: str | None = None):
        """Global request-latency observation, plus the tenant's own
        histogram when the request was tenant-scoped."""
        self.latency.record(seconds)
        if tenant is None:
            return
        with self._lock:
            hist = self.tenant_latency.get(tenant)
            if hist is None:
                hist = self.tenant_latency[tenant] = LatencyHistogram()
        hist.record(seconds)

    def tenant_snapshot(self) -> dict:
        """{tenant: {counter: value}} point-in-time copy."""
        with self._lock:
            return {t: dict(c) for t, c in self.tenant_counters.items()}

    def tenant_latency_report(self, tenant: str) -> dict | None:
        with self._lock:
            hist = self.tenant_latency.get(tenant)
        return None if hist is None else hist.report()

    def note_recall(self, plan) -> None:
        """Record one request's recall tier (``plan`` is None for exact,
        a serve/recall.py RecallPlan otherwise)."""
        with self._lock:
            if plan is None:
                self.recall_tiers["exact"] += 1
                return
            self.recall_tiers["approx"] += 1
            r = float(plan.recall_estimated)
            self.recall_hist_sum += r
            for i, edge in enumerate(RECALL_HIST_EDGES):
                if r <= edge:
                    self.recall_hist[i] += 1
                    break
            else:
                self.recall_hist[-1] += 1

    def recall_snapshot(self) -> dict:
        with self._lock:
            return {
                "tiers": dict(self.recall_tiers),
                "estimated_hist": {
                    "edges": list(RECALL_HIST_EDGES),
                    "counts": list(self.recall_hist),
                    "sum": round(self.recall_hist_sum, 6),
                    "count": self.recall_tiers["approx"]},
            }

    def recall_prometheus_lines(self) -> list[str]:
        snap = self.recall_snapshot()
        lines = ["# TYPE knn_recall_requests_total counter"] + [
            f'knn_recall_requests_total{{tier="{t}"}} {v}'
            for t, v in sorted(snap["tiers"].items())]
        h = snap["estimated_hist"]
        lines += ["# TYPE knn_recall_estimated histogram"]
        cum = 0
        for edge, c in zip(h["edges"], h["counts"]):
            cum += c
            lines += [f'knn_recall_estimated_bucket{{le="{edge}"}} {cum}']
        lines += [f'knn_recall_estimated_bucket{{le="+Inf"}} {h["count"]}',
                  f"knn_recall_estimated_sum {h['sum']}",
                  f"knn_recall_estimated_count {h['count']}"]
        return lines


class KnnServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, engine, *, max_delay_s=0.002,
                 max_queue_rows=4096, default_timeout_s=5.0, query_fn=None,
                 verbose=False, pipeline_depth=2, faults=None,
                 recall_policy=None, tenant_quota_rows=0,
                 qcache_rows=4096, qcache_seed_rows=512):
        self.engine = engine
        #: multi-index tenancy (serve/tenancy.py): a MultiTenantEngine
        #: exposes a TenantRegistry — its presence switches on the
        #: /v1/<tenant>/knn surface, per-tenant metrics, and quotas.
        #: Single-index engines leave all three None/off, keeping the
        #: wire byte-identical to pre-tenancy servers.
        self.tenants = getattr(engine, "tenants", None)
        self.quotas = None
        #: recall-SLO tier (serve/recall.py): maps a request's
        #: ``"recall": 0.95`` target to a calibrated cheaper plan. The
        #: built-in table serves by default, K-CONDITIONED on the
        #: engine's heap depth (deep k needs gentler knobs); operators
        #: swap in a harness-calibrated one via --recall-policy
        #: (cli/serve_main.py)
        self.recall_policy = (
            RecallPolicy.for_k(getattr(engine, "k", None))
            if recall_policy is None else recall_policy)
        #: deterministic fault injection (serve/faults.py; KNN_FAULTS env)
        #: — the single-host twin of the pod hosts' injector, so failure
        #: drills run against any serving tier
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self.admission = AdmissionController(
            max_queue_rows=max_queue_rows,
            default_timeout_s=default_timeout_s)
        if self.tenants is not None:
            # per-tenant row-budget slices of the same controller; 0 =
            # tenants unsliced (global cap only) until set_quota is called
            self.quotas = TenantQuotas(
                self.admission, default_quota_rows=tenant_quota_rows)
        self.graceful = (GracefulQueryFn(engine) if query_fn is None
                         else query_fn)
        #: certified query cache (serve/qcache.py): exact-hit reuse,
        #: in-flight dedup, triangle-inequality radius seeds.
        #: ``qcache_rows=0`` turns the whole layer off; a CUSTOM query_fn
        #: keeps the hit/dedup tiers but disables seeding (seed vectors
        #: are the only tier that changes the query_fn call signature)
        self.qcache = None
        if qcache_rows:
            self.qcache = QueryCache(
                capacity_rows=qcache_rows,
                seed_rows=(qcache_seed_rows if query_fn is None else 0),
                fingerprint=(f"{engine.engine_name}:n={engine.n_points}"
                             f":k={engine.k}:dim={engine.dim}"))
        # depth 2 by default: batch t+1's device traversal overlaps batch
        # t's host merge/demux (results identical to depth 1 — the pipeline
        # reorders nothing, it only overlaps). See docs/SERVING.md.
        self.batcher = DynamicBatcher(self.graceful,
                                      max_batch=engine.max_batch,
                                      max_delay_s=max_delay_s,
                                      timers=engine.timers,
                                      pipeline_depth=pipeline_depth,
                                      # stall-aware flush floor: slivers
                                      # below the narrowest shape bucket
                                      # keep coalescing while the pipe is
                                      # busy (serve/batcher.py)
                                      min_batch=engine.shape_buckets[0],
                                      qcache=self.qcache)
        self.admission.pipeline_rows_fn = self.batcher.inflight_rows
        if self.batcher.pipelined and hasattr(engine, "set_launch_workers"):
            # let the engine keep as many programs in flight as the
            # pipeline can hand it (its async-program-queue stand-in)
            engine.set_launch_workers(pipeline_depth)
        self.metrics = ServingMetrics()
        self.ready = False
        self.verbose = verbose
        self._loop_entered = False
        super().__init__(addr, _Handler)

    def serve_forever(self, poll_interval=0.5):
        self._loop_entered = True
        super().serve_forever(poll_interval)

    def close(self):
        self.batcher.shutdown()
        # BaseServer.shutdown() waits on an event only serve_forever() sets —
        # calling it when the loop was never entered (warmup failed, Ctrl-C
        # during compile) would hang forever instead of exiting
        if self._loop_entered:
            self.shutdown()
        self.server_close()


class JsonHttpHandler(BaseHTTPRequestHandler):
    """Shared handler plumbing (keep-alive, quiet logging, body helpers)
    for every serving endpoint — this server, the pod front end, and the
    per-host slice servers (serve/frontend.py)."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str, extra=()):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj, extra=()):
        self._send(code, json.dumps(obj).encode(), "application/json", extra)

    # chunked-response writer: ``_send`` always sets Content-Length, which
    # forces the whole body to be materialized up front — exactly the
    # transient-RAM doubling /slab_rows must avoid. These three stream an
    # HTTP/1.1 chunked body instead (http.client reassembles transparently
    # on the pull side).
    def _start_chunked(self, code: int, ctype: str, extra=()):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in extra:
            self.send_header(k, v)
        self.end_headers()

    def _write_chunk(self, data: bytes):
        if data:
            self.wfile.write(b"%x\r\n" % len(data))
            self.wfile.write(data)
            self.wfile.write(b"\r\n")

    def _end_chunked(self):
        self.wfile.write(b"0\r\n\r\n")

    def _apply_fault(self, path: str) -> bool:
        """Consult the server's FaultInjector (if any) for this request;
        True when an injected fault consumed it (serve/faults.py)."""
        inj = getattr(self.server, "faults", None)
        if inj is None or not inj.active():
            return False
        return apply_http_fault(
            self, inj.decide(path, getattr(self, "command", "") or ""))


class _Handler(JsonHttpHandler):

    # ------------------------------------------------------------------ GET
    def do_GET(self):
        srv: KnnServer = self.server
        path = urlparse(self.path).path
        if path == "/healthz":
            if srv.ready:
                self._send_json(200, {"status": "ok",
                                      "engine": srv.engine.engine_name})
            else:
                self._send_json(503, {"status": "warming"})
        elif path == "/stats":
            out = {
                "engine": srv.engine.stats(),
                "batcher": srv.batcher.stats(),
                "admission": srv.admission.stats(),
                "server": dict(srv.metrics.snapshot(),
                               request_latency=srv.metrics.latency.report()),
                "recall": dict(srv.metrics.recall_snapshot(),
                               policy=srv.recall_policy.stats()),
            }
            if srv.qcache is not None:
                out["qcache"] = srv.qcache.stats()
            if srv.tenants is not None:
                out["tenants"] = self._tenant_stats(srv)
            self._send_json(200, out)
        elif path == "/metrics":
            self._send(200, self._prometheus(srv).encode(),
                       "text/plain; version=0.0.4")
        elif (srv.tenants is not None and path.startswith("/v1/")
                and path.endswith("/stats")
                and len(path.split("/")) == 4):
            name = path.split("/")[2]
            if name not in srv.tenants:
                self._send_json(404, {"error": f"no such tenant {name!r}",
                                      "tenants": srv.tenants.names()})
                return
            self._send_json(200, dict(self._tenant_stats(srv)[name],
                                      tenant=name))
        else:
            self._send_json(404, {"error": f"no such path {path}"})

    @staticmethod
    def _tenant_stats(srv: KnnServer) -> dict:
        """The per-tenant /stats namespace: each tenant's server-side
        counters + latency, quota state, and engine view (index geometry
        plus its pool residency/stall share)."""
        counters = srv.metrics.tenant_snapshot()
        quota = srv.quotas.stats()["tenants"] if srv.quotas is not None else {}
        engine_tenants = srv.engine.stats().get("tenants", {})
        out = {}
        for name in srv.tenants.names():
            out[name] = {
                "server": dict(
                    counters.get(name, {}),
                    request_latency=srv.metrics.tenant_latency_report(name)),
                "quota": quota.get(name, {
                    "quota_rows": srv.quotas.quota(name)
                    if srv.quotas is not None else 0,
                    "inflight_rows": 0, "rejected": 0}),
                "engine": engine_tenants.get(name, {}),
            }
        return out

    @staticmethod
    def _prometheus(srv: KnnServer) -> str:
        e, b, a = srv.engine.stats(), srv.batcher.stats(), srv.admission.stats()
        lines = []
        # per-tenant twins of each counter family ride as {tenant=}
        # labels right under the unlabeled (aggregate) series; empty on
        # single-index servers, so their text output is unchanged
        tsnap = srv.metrics.tenant_snapshot()
        for name, val in srv.metrics.snapshot().items():
            lines += [f"# TYPE {name} counter", f"{name} {val}"]
            lines += [f'{name}{{tenant="{t}"}} {tsnap[t][name]}'
                      for t in sorted(tsnap) if name in tsnap[t]]
        # engine-side cumulative counters: bytes fetched across the host
        # link and result rows completed — the device-vs-host merge
        # placement shows up as fetch_bytes/result_rows shrinking ~R x
        # tile-skip accounting (tile-row units, serve/engine.py): executed
        # vs skipped is the radius prune's win as a number — the locality
        # bench's gate, and the dashboard signal that query traffic has
        # gone spatially incoherent (skipped falling toward zero)
        for name, val in (("knn_fetch_bytes_total", e["fetch_bytes"]),
                          ("knn_result_rows_total", e["result_rows"]),
                          ("knn_tiles_executed_total", e["tiles_executed"]),
                          ("knn_tiles_skipped_total", e["tiles_skipped"]),
                          # cumulative seconds the dispatch worker spent
                          # blocked on the pipeline-depth bound (a proper
                          # counter — the gauge twins below predate it and
                          # stay for dashboard compat)
                          ("knn_dispatch_stall_seconds_total",
                           b["dispatch_stall_seconds"]),
                          ("knn_dispatch_stalls_total",
                           b["dispatch_stalls"])):
            lines += [f"# TYPE {name} counter", f"{name} {val}"]
        # per-score-mode tile attribution: which scorer (MXU matmul-form
        # vs VPU elementwise) burned the executed tiles — the kernel-bench
        # speedup's dashboard counterpart
        lines += ["# TYPE knn_tiles_executed_by_mode_total counter"] + [
            f'knn_tiles_executed_by_mode_total{{mode="{m}"}} '
            f'{e[f"tiles_executed_{m}"]}' for m in ("mxu", "vpu")]
        lines += ["# TYPE knn_tiles_skipped_by_mode_total counter"] + [
            f'knn_tiles_skipped_by_mode_total{{mode="{m}"}} '
            f'{e[f"tiles_skipped_{m}"]}' for m in ("mxu", "vpu")]
        lines += ["# TYPE knn_merge_mode gauge",
                  f'knn_merge_mode{{mode="{e["merge"]}"}} 1']
        lines += ["# TYPE knn_score_dtype gauge",
                  f'knn_score_dtype{{dtype="{e["score_dtype"]}"}} 1']
        lines += ["# TYPE knn_query_buckets gauge"] + [
            f'knn_query_buckets{{qpad="{q}"}} {b}'
            for q, b in e["query_buckets"].items()]
        gauges = {
            "knn_ready": int(srv.ready),
            "knn_engine_degraded": int(e["degraded_reason"] is not None),
            "knn_compile_count": e["compile_count"],
            "knn_index_points": e["n_points"],
            "knn_num_shards": e["num_shards"],
            "knn_queue_rows": b["queue_rows"],
            "knn_inflight_rows": a["inflight_rows"],
            "knn_admission_rejected_total": a["rejected"],
            "knn_batches_total": b["batches"],
            "knn_batch_rows_served_total": b["rows_served"],
            # pipeline occupancy: configured depth, batches/rows currently
            # between dispatch and demux, and cumulative dispatch stalls
            # (dispatch worker blocked on the depth bound)
            "knn_pipeline_depth": b["pipeline_depth"],
            "knn_pipeline_inflight_batches": b["inflight_batches"],
            "knn_pipeline_inflight_rows": b["inflight_rows"],
            "knn_pipeline_dispatch_stalls_total": b["dispatch_stalls"],
            "knn_pipeline_dispatch_stall_seconds_total":
                b["dispatch_stall_seconds"],
        }
        for name, val in gauges.items():
            lines += [f"# TYPE {name} gauge", f"{name} {val}"]
        # certified query cache (serve/qcache.py): the three reuse tiers'
        # counters with {tenant=} twins on multi-tenant servers — absent
        # when the cache is off, so those servers' text is unchanged
        lines += qcache_prometheus_lines(srv.qcache)
        # tiered slab index (serve/slabpool.py): per-tier residency,
        # promotion/eviction totals, stream-stall accounting — absent for
        # fully-resident engines
        lines += slab_pool_prometheus_lines(e)
        # multi-index tenancy: per-tenant pool occupancy/stall shares and
        # admission-quota state — absent on single-index servers
        lines += _tenant_prometheus_lines(srv, e)
        # recall-SLO tier: exact/approx request split plus the calibrated
        # recall_estimated distribution of the approximate responses
        lines += srv.metrics.recall_prometheus_lines()
        lines += srv.metrics.latency.prometheus_lines(
            "knn_request_latency_seconds")
        for src, prom in (("engine_batch_seconds",
                           "knn_engine_batch_seconds"),
                          ("pipeline_stall_seconds",
                           "knn_pipeline_stall_seconds")):
            hist = srv.engine.timers.histograms.get(src)
            if hist is not None:
                lines += hist.prometheus_lines(prom)
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------------ POST
    def _parse_body(self):
        """-> (queries, want_neighbors, timeout_s, recall, tenant,
        binary)."""
        return parse_knn_body(self.path, self.headers, self.rfile,
                              dim=getattr(self.server.engine, "dim", 3))

    @staticmethod
    def _tenant_path(path: str) -> str | None:
        """The <tenant> of a ``/v1/<tenant>/knn`` POST path (None when
        the path is not tenant-scoped)."""
        parts = path.split("/")
        if (len(parts) == 4 and parts[0] == "" and parts[1] == "v1"
                and parts[2] and parts[3] == "knn"):
            return parts[2]
        return None

    def do_POST(self):
        srv: KnnServer = self.server
        path = urlparse(self.path).path
        path_tenant = self._tenant_path(path)
        if path != "/knn" and path_tenant is None:
            self._send_json(404, {"error": "POST /knn only"})
            return
        if path_tenant is not None and srv.tenants is None:
            self._send_json(404, {
                "error": f"no tenant namespaces on a single-index server "
                         f"(POST /knn); got {path}"})
            return
        if self._apply_fault("/knn"):
            return
        t0 = time.perf_counter()
        try:
            q, want_nbrs, timeout_s, recall, tenant, binary = (
                self._parse_body())
        except (ValueError, json.JSONDecodeError) as e:
            srv.metrics.inc("knn_requests_total")
            srv.metrics.inc("knn_badrequest_total")
            self._send_json(400, {"error": str(e)})
            return
        # tenant resolution: URL > JSON field / header > default. On a
        # single-index server the field is ignored entirely (the legacy
        # wire, byte for byte); on a multi-tenant server every request
        # lands on exactly one named tenant and strangers are a 404
        name = None
        if srv.tenants is not None:
            name = path_tenant or tenant or srv.engine.default_tenant
            if name not in srv.tenants:
                srv.metrics.inc("knn_requests_total")
                srv.metrics.inc("knn_unknown_tenant_total")
                self._send_json(404, {"error": f"no such tenant {name!r}",
                                      "tenants": srv.tenants.names()})
                return
        srv.metrics.inc("knn_requests_total", tenant=name)
        # recall-SLO resolution: a target of 1.0 (or one no calibrated plan
        # meets) falls through to plan=None — the exact path, untouched
        plan = (srv.recall_policy.plan_for(recall)
                if recall is not None else None)
        timeout_s = timeout_s or srv.admission.default_timeout_s
        n = len(q)
        if n > srv.engine.max_batch:
            srv.metrics.inc("knn_badrequest_total", tenant=name)
            self._send_json(413, {
                "error": f"batch of {n} exceeds max_batch "
                         f"{srv.engine.max_batch}; split the request"})
            return
        if n == 0:
            if binary:
                self._send(200, b"", "application/octet-stream")
            else:
                self._send_json(200, {"dists": []})
            return
        try:
            # multi-tenant admission reserves the tenant's quota slice
            # first, then the global row cap (serve/tenancy.py); both
            # reject with the same OverloadError -> 429 + Retry-After
            admitted = (srv.quotas.admitted_rows(name, n)
                        if srv.quotas is not None
                        else srv.admission.admitted_rows(n))
            with admitted:
                dists, nbrs = srv.batcher.submit(q, timeout_s=timeout_s,
                                                 plan=plan, tenant=name)
        except OverloadError as e:
            srv.metrics.inc("knn_overload_total", tenant=name)
            self._send_json(429, {"error": str(e)},
                            extra=[("Retry-After", f"{e.retry_after_s:g}")])
            return
        except DeadlineExceeded as e:
            srv.metrics.inc("knn_deadline_total", tenant=name)
            self._send_json(504, {"error": str(e)})
            return
        except UnservableShapeError as e:
            srv.metrics.inc("knn_badrequest_total", tenant=name)
            self._send_json(413, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - the service must not die
            srv.metrics.inc("knn_error_total", tenant=name)
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        srv.metrics.inc("knn_rows_total", n, tenant=name)
        srv.metrics.note_recall(plan)
        srv.metrics.record_latency(time.perf_counter() - t0, tenant=name)
        fields, hdrs = recall_response_fields(plan, recall)
        # multi-tenant responses echo the resolved tenant (JSON field /
        # binary header); single-index responses stay byte-identical
        if name is not None:
            fields = dict(fields, tenant=name)
            hdrs = list(hdrs) + [("X-Knn-Tenant", name)]
        if binary:
            self._send(200, np.asarray(dists, "<f4").tobytes(),
                       "application/octet-stream", extra=hdrs)
        else:
            out = {"dists": np.asarray(dists, np.float64).tolist()}
            if want_nbrs:
                out["neighbors"] = np.asarray(nbrs).tolist()
            out.update(fields)
            self._send_json(200, out)


def build_server(engine, host: str = "127.0.0.1", port: int = 8080,
                 **kwargs) -> KnnServer:
    """Construct (but do not start) a KnnServer; ``port=0`` picks a free
    port (``server.server_address[1]`` reports it — how the tests run)."""
    return KnnServer((host, port), engine, **kwargs)


def serve_forever(server: KnnServer, warmup: bool = True) -> None:
    """Warm every shape bucket, mark ready, and block serving requests."""
    eng = server.engine
    if warmup:
        info = eng.warmup()
        if "tenants" in info:
            # MultiTenantEngine.warmup: one shared compile pass covers
            # every tenant (the compile-count-flat contract)
            print(f"warmup compiles done: {info['compile_count']} "
                  f"compiles shared across {len(info['tenants'])} "
                  f"tenants")
        else:
            print(f"warmup compiles done: {info['per_bucket_s']} "
                  f"(seconds per bucket); query buckets "
                  f"{info['query_buckets']}; tiles executed/skipped "
                  f"{info['tiles_executed']}/{info['tiles_skipped']}")
    server.ready = True
    host, port = server.server_address[:2]
    print(f"serving kNN on http://{host}:{port} "
          f"(engine={eng.engine_name}, k={eng.k}, n={eng.n_points}, "
          f"dim={eng.dim}, score={eng.score_dtype}, "
          f"morton_sort={'on' if eng.sort_queries else 'off'})")
    server.serve_forever()
