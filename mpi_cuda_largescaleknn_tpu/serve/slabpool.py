"""Beyond-HBM tiered slab index: host-RAM/mmap slab pool + bounds-driven
prefetch streaming.

The resident engines cap index size at device memory — every slab must be
uploaded before the first query. But the reference's whole point is
datasets that EXCEED one accelerator's memory (PAPER.md §0: billions of
points; "queries never move — trees move"), and PANDA (arXiv:1607.08220)
shows extreme-scale kNN is won by keeping the working set MOVING through a
memory hierarchy rather than demanding full residency. This module tiers
the index in three levels:

- **device** — a working set of slab engines (one ``ResidentKnnEngine``
  per resident slab, ``emit='candidates'`` with global neighbor ids),
  bounded in BYTES by ``--device-slab-budget`` against each engine's
  reported ``device_bytes`` footprint, evicted LRU-with-pin;
- **host RAM** — a bounded pool of materialized numpy slab rows (the
  promotion source; on real hardware these would be pinned/page-locked
  buffers for DMA), LRU-capped at ``--host-pool-slabs``;
- **cold** — the source ``.float3``/``.npy`` file itself (``SlabSource``:
  the exact ``load_slab_rows`` split of serve/engine.py, mmap for
  ``.npy``), so a slab that fell out of both warm tiers is re-read with
  rows byte-identical to what a routed host / the slab handoff would
  materialize.

``StreamingKnnEngine`` is the engine-shaped facade the serving stack
drives (same ``dispatch``/``complete`` split, same /stats-feeding
``stats()`` surface): each batch is routed by a per-slab AABB bounds table
— the in-process twin of the PR-7 ``PodBoundsTable`` — to its
nearest-bounds slab plus every slab whose box contains it, the per-slab
candidate partials are folded with the canonical (dist2, id) merge
(serve/frontend.py ``fold_candidates`` — commutative, so slab completion
order can never change bits), and uncertified (query, slab) pairs
escalate in waves exactly like the routed pod
(``lb * (1 - routing_cert_slack) <= kth²`` keeps a slab in play) until
every skipped slab is CERTIFIED unable to contribute. Exactness is never
traded by DEFAULT: a needed slab that misses both warm tiers STALLS the
batch (counted in ``knn_stream_stall_seconds_total``), it is never
skipped or approximated — results are bit-identical to a fully-resident
engine at EVERY pool size (tests/test_slabpool.py's parity matrix over
budgets {1 slab, half, all}). A request that OPTS INTO the recall-SLO
tier (serve/recall.py, ``stream_skip_cold``) inverts exactly that one
trade: cold promotions whose bounds could still beat the kth distance
are skipped for recall instead of stalled on
(``stream_skipped_promotions``), and the slab warms asynchronously for
the next batch.

Overlap is what makes the tiers affordable (TPU-KNN, arXiv:2206.14286:
the scorer must never starve): ``dispatch`` PINS the batch's slab set
(pinned slabs cannot evict while their programs are in flight), a
dedicated promotion thread uploads prefetched slabs ASYNCHRONOUSLY, and
the PR-2 pipeline announces the NEXT admitted batch's routed slab set a
batch ahead (serve/batcher.py calls ``prefetch_hint`` with the queued
rows after each dispatch) — so promotions ride under the in-flight
batch's compute and a well-hinted stream stalls zero times
(``serve_smoke --streaming-bench`` gates a stall-fraction ceiling at
index size 4x the device budget, on top of bitwise probe parity).

AOT discipline across the churn: all slab engines are padded to ONE shape
class (``pad_shard_rows``) and share an ``ExecutableCache`` keyed by that
class, so an eviction/re-promotion cycle reuses the already-compiled
query programs — ``compile_count`` stays flat no matter how many times a
slab cycles through the pool.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector

_RECORD_BYTES = 12  # one float3 (io/reader.py)


class SlabSource:
    """The cold tier: materialize slab ``s`` of ``S`` on demand.

    Rows are byte-identical to ``serve/engine.py load_slab_rows`` (and
    therefore to a routed host's / the slab handoff's materialization):
    the reference's integer split ``[N*s/S, N*(s+1)/S)`` via
    ``read_file_portion`` for ``.float3``, an mmap slice for ``.npy``, a
    plain slice for an in-memory array (the routed streaming path hands
    its already-loaded host slab here), or — with ``url=`` — a row
    sub-range pulled over HTTP from a host serving the full index
    (``GET /slab_rows?wire=d16&begin=&end=``: the PR-17 delta codec, so
    cold-tier promotions move ~0.55x the f32 bytes and are
    fingerprint-verified lossless after decode; an old host falls back
    to the single-shot f32 body automatically). Reads are stateless and
    thread-compatible — the pool's locking lives above this."""

    def __init__(self, *, path: str | None = None, points=None,
                 url: str | None = None, num_slabs: int,
                 wire: str = "d16", timeout_s: float = 120.0,
                 throttle_bps: float | None = None):
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_bounds

        if sum(x is not None for x in (path, points, url)) != 1:
            raise ValueError("need exactly one of path=, points= or url=")
        if num_slabs < 1:
            raise ValueError(f"num_slabs must be >= 1, got {num_slabs}")
        self.path = path
        self.num_slabs = int(num_slabs)
        self._points = None
        self._mmap = None
        self._url = None
        if url is not None:
            import json as _json
            import urllib.request as _rq

            self._url = url.rstrip("/")
            self._wire = wire
            self._timeout_s = float(timeout_s)
            self._throttle_bps = throttle_bps
            with _rq.urlopen(self._url + "/stats",
                             timeout=self._timeout_s) as r:
                est = _json.loads(r.read()).get("engine") or {}
            self.n_total = int(est.get("n_points", -1))
            self.dim = int(est.get("dim", 0))
            off = int(est.get("row_offset", -1))
            if self.n_total < 0 or self.dim < 1 or off != 0:
                raise ValueError(
                    f"{url}: not a full-index source host (n_points="
                    f"{self.n_total} dim={self.dim} row_offset={off})")
        elif points is not None:
            self._points = np.asarray(points, np.float32)
            if self._points.ndim != 2 or self._points.shape[1] < 1:
                raise ValueError(f"points must be [N, D], got "
                                 f"{self._points.shape}")
            self.n_total = len(self._points)
            self.dim = int(self._points.shape[1])
        elif path.endswith(".npy"):
            self._mmap = np.load(path, mmap_mode="r")
            if self._mmap.ndim != 2 or self._mmap.shape[1] < 1:
                raise ValueError(f"{path}: expected an [N, D] array, got "
                                 f"shape {list(self._mmap.shape)}")
            self.n_total = len(self._mmap)
            self.dim = int(self._mmap.shape[1])
        else:
            self.n_total = os.path.getsize(path) // _RECORD_BYTES
            self.dim = 3
        #: slab s owns global rows [bounds[s][0], bounds[s][1]) — the
        #: reference's split, shared with every other slab consumer
        self.bounds = slab_bounds(self.n_total, self.num_slabs)

    def read(self, slab: int) -> np.ndarray:
        """Materialize slab ``slab``'s rows (f32[n, dim])."""
        b, e = self.bounds[slab]
        if self._url is not None:
            from mpi_cuda_largescaleknn_tpu.serve.replica import (
                pull_slab_rows,
            )

            rows, off = pull_slab_rows(
                self._url, timeout_s=self._timeout_s, wire=self._wire,
                begin=b, end=e, throttle_bps=self._throttle_bps)
            if off != b or len(rows) != e - b:
                raise ValueError(
                    f"{self._url}: slab {slab} range drifted: got "
                    f"[{off}, {off + len(rows)}) want [{b}, {e})")
            return rows
        if self._points is not None:
            return np.asarray(self._points[b:e], np.float32)
        if self._mmap is not None:
            # the mmap slice copies exactly the slab's pages into RAM —
            # the cold tier never loads the whole file
            return np.asarray(self._mmap[b:e], np.float32)
        from mpi_cuda_largescaleknn_tpu.io.reader import read_file_portion

        rows, begin, _n = read_file_portion(self.path, slab, self.num_slabs)
        assert begin == b, f"slab split drifted: {begin} != {b}"
        return rows

    def scan_aabbs(self, sink=None) -> list[dict]:
        """One bounding box + count per slab ({"lo", "hi", "count"},
        ``lo/hi = None`` for empty slabs — the router's unreachable
        sentinel). Streams one slab at a time, so the scan's resident
        footprint is one slab, not the index. ``sink(slab, rows)`` (if
        given) receives each slab's rows as they are scanned — the
        streaming engine seeds its pool's host tier with them, so the
        scan's I/O is not immediately repeated by the first promotions."""
        from mpi_cuda_largescaleknn_tpu.models.sharding import slab_aabbs

        out = []
        for s in range(self.num_slabs):
            rows = self.read(s)
            out.extend(slab_aabbs(rows, [(0, len(rows))]))
            if sink is not None:
                sink(s, rows)
        return out


class _DeviceSlab:
    """One device-resident slab: its engine, its byte footprint, and its
    LRU tick (a logical counter, not wall-clock — deterministic under the
    tests' injectable clock)."""

    __slots__ = ("engine", "bytes", "tick")

    def __init__(self, engine, nbytes: int, tick: int):
        self.engine = engine
        self.bytes = int(nbytes)
        self.tick = int(tick)


class SlabPool:
    """Tiered slab store: device engines over a host-RAM row pool over the
    cold source, with LRU-with-pin eviction and an async promotion thread.

    ``engine_factory(slab_id, rows, row_begin) -> engine`` builds the
    device tier's entries (the streaming engine supplies the real
    ``ResidentKnnEngine`` factory; unit tests inject fakes — no jax, no
    sleeps). The factory runs OUTSIDE the pool lock: builds take real time
    and must never block /stats scrapes or concurrent pins.

    Exactness contract: ``ensure`` blocks until the slab is resident — a
    miss is a counted STALL (``stream_stall_seconds``), never a skipped or
    approximated slab. Pinned slabs (``pin``/``unpin``: the dispatch path
    pins a batch's routed slab set for the life of its in-flight programs)
    are never evicted; if a single batch's pinned set exceeds the budget
    the pool overcommits transiently (counted) rather than deadlock —
    the budget is a steady-state bound, not a per-batch straitjacket.

    Multi-index tenancy (PR 18): pool keys are either plain slab ints
    (the single-index legacy form — one source, one factory) or
    ``(tenant, slab)`` tuples routed through a per-tenant registry
    (``register``). All tenants share ONE device byte budget and ONE
    host tier, so hot tenants naturally occupy the device tier while
    cold tenants fall back to host-RAM/mmap and ride the same promotion
    + cold-read path; per-tenant hit/stall/eviction accounting rides
    alongside the pool-wide counters. A pool never mixes both key kinds.
    """

    def __init__(self, source: SlabSource | None = None,
                 engine_factory=None, *,
                 device_budget_bytes: int = 0, host_pool_slabs: int = 0,
                 host_pool_bytes: int = 0,
                 faults: FaultInjector | None = None,
                 clock=time.perf_counter):
        self._clock = clock
        self._sleep = time.sleep  # injectable: fault tests never sleep
        self._faults = faults
        self._cv = threading.Condition()
        # --- every field below is shared between caller threads (pin/
        # ensure/stats) and the promotion thread; all access under _cv ---
        #: tenant -> (SlabSource, engine_factory). The legacy single-index
        #: form registers under tenant ``None`` and keys the pool by bare
        #: slab ints; multi-tenant callers register named tenants and key
        #: by (tenant, slab)
        self._routes: guarded_by("_cv") = {}
        if source is not None:
            self._routes[None] = (source, engine_factory)
        #: per-tenant accounting (tuple-keyed pools only): tenant ->
        #: counter dict, updated alongside the pool-wide totals
        self._tenants: guarded_by("_cv") = {}
        self._budget: guarded_by("_cv") = int(device_budget_bytes)
        self._host_cap: guarded_by("_cv") = int(host_pool_slabs)
        self._host_bytes_cap: guarded_by("_cv") = int(host_pool_bytes)
        self._host_bytes: guarded_by("_cv") = 0
        self._device: guarded_by("_cv") = {}
        self._device_bytes: guarded_by("_cv") = 0
        #: host-RAM row pool, insertion-ordered oldest-first (dicts keep
        #: insertion order; move-to-end on hit = LRU)
        self._host: guarded_by("_cv") = {}
        self._pins: guarded_by("_cv") = {}
        self._promoting: guarded_by("_cv") = set()
        self._queued: guarded_by("_cv") = set()
        self._tick: guarded_by("_cv") = 0
        self._closed: guarded_by("_cv") = False
        self.promotions: guarded_by("_cv") = 0
        self.promotion_errors: guarded_by("_cv") = 0
        self.last_error: guarded_by("_cv") = None
        self.evictions: guarded_by("_cv") = 0
        self.host_evictions: guarded_by("_cv") = 0
        self.device_hits: guarded_by("_cv") = 0
        self.host_hits: guarded_by("_cv") = 0
        self.cold_reads: guarded_by("_cv") = 0
        self.overcommits: guarded_by("_cv") = 0
        self.prefetch_enqueued: guarded_by("_cv") = 0
        self.prefetch_errors: guarded_by("_cv") = 0
        self.stream_stalls: guarded_by("_cv") = 0
        self.stream_stall_seconds: guarded_by("_cv") = 0.0
        self._pq: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._prefetch_loop,
                                        daemon=True, name="knn-slab-promote")
        self._thread.start()

    # ------------------------------------------------------- keys & routes

    @staticmethod
    def _as_key(s):
        """Normalize a caller's slab reference to a pool key: bare ints
        for the legacy single-index pool, (tenant, slab) tuples for a
        multi-tenant one."""
        return (s[0], int(s[1])) if isinstance(s, tuple) else int(s)

    def register(self, tenant, source: SlabSource, engine_factory) -> None:
        """Add (or replace) a tenant's cold source + engine factory.
        Registration happens at engine construction, before that
        tenant's keys circulate — routes are read-mostly after."""
        with self._cv:
            self._routes[tenant] = (source, engine_factory)

    def _route(self, key):  # lsk: holds[_cv]
        """(tenant, local slab, source, factory) for a pool key."""
        if isinstance(key, tuple):
            tenant, slab = key
        else:
            tenant, slab = None, int(key)
        src, fac = self._routes[tenant]
        return tenant, slab, src, fac

    def _tacct(self, key):  # lsk: holds[_cv]
        """The per-tenant counter dict for a tuple key (lazily created);
        None for legacy int keys — single-index pools pay nothing."""
        if not isinstance(key, tuple):
            return None
        acct = self._tenants.get(key[0])
        if acct is None:
            acct = self._tenants[key[0]] = {
                "promotions": 0, "evictions": 0, "device_hits": 0,
                "host_hits": 0, "cold_reads": 0, "prefetch_enqueued": 0,
                "stream_stalls": 0, "stream_stall_seconds": 0.0}
        return acct

    # ----------------------------------------------------------- accounting

    def _next_tick(self) -> int:  # lsk: holds[_cv]
        self._tick += 1
        return self._tick

    def _note_stall(self, seconds: float, key=None):  # lsk: holds[_cv]
        self.stream_stalls += 1
        self.stream_stall_seconds += max(0.0, float(seconds))
        acct = self._tacct(key)
        if acct is not None:
            acct["stream_stalls"] += 1
            acct["stream_stall_seconds"] += max(0.0, float(seconds))

    def stall_totals(self, tenant=None) -> tuple:
        """(stalls, cumulative stall seconds) — the drift guard's cheap
        sample, without building the full stats dict. ``tenant`` narrows
        to one tenant's share of a shared pool."""
        with self._cv:
            if tenant is None:
                return self.stream_stalls, self.stream_stall_seconds
            acct = self._tenants.get(tenant)
            if acct is None:
                return 0, 0.0
            return acct["stream_stalls"], acct["stream_stall_seconds"]

    def _host_put(self, key, rows) -> None:  # lsk: holds[_cv]
        """Insert/refresh a slab's rows in the host tier; trim LRU past
        the slab-count cap and/or the byte cap (``--host-pool-bytes`` —
        the byte form keeps mixed-size tenant slabs from blowing the
        tier; the newest insert always survives, like the device tier's
        overcommit). Device-resident slabs keep their own row reference
        (``engine.host_points``), so trimming here never loses data —
        worst case the cold tier resupplies."""
        old = self._host.pop(key, None)
        if old is not None:
            self._host_bytes -= int(getattr(old, "nbytes", 0))
        self._host[key] = rows
        self._host_bytes += int(getattr(rows, "nbytes", 0))
        while ((self._host_cap > 0 and len(self._host) > self._host_cap)
               or (self._host_bytes_cap > 0
                   and self._host_bytes > self._host_bytes_cap
                   and len(self._host) > 1)):
            victim = next(iter(self._host))
            self._host_bytes -= int(
                getattr(self._host[victim], "nbytes", 0))
            del self._host[victim]
            self.host_evictions += 1

    def _evict_to_fit(self, new_bytes: int) -> None:  # lsk: holds[_cv]
        """Evict LRU unpinned device slabs until ``new_bytes`` more fit
        the budget (0 = unbounded). Evicted engines demote their rows to
        the host tier (free re-warm). With nothing evictable the pool
        overcommits — a pinned set wider than the budget must complete,
        not deadlock."""
        if self._budget <= 0:
            return
        while self._device_bytes + new_bytes > self._budget and self._device:
            victims = [(ent.tick, s) for s, ent in self._device.items()
                       if self._pins.get(s, 0) == 0]
            if not victims:
                # counted per PROMOTION that lands over budget (insert
                # time only) — unpin/set-budget re-checks finding the
                # pool still over would overstate one wide batch as many
                if new_bytes > 0:
                    self.overcommits += 1
                return
            _tick, s = min(victims)
            ent = self._device.pop(s)
            self._device_bytes -= ent.bytes
            self.evictions += 1
            acct = self._tacct(s)
            if acct is not None:
                acct["evictions"] += 1
            rows = getattr(ent.engine, "host_points", None)
            if rows is not None:
                self._host_put(s, rows)

    # ------------------------------------------------------------- pin/ensure

    def pin(self, slabs) -> None:
        """Pin each slab against eviction (reference-counted). Pins apply
        whether or not the slab is resident yet — a pinned cold slab
        cannot be evicted between its promotion and its use."""
        with self._cv:
            for s in set(slabs):
                self._pins[s] = self._pins.get(s, 0) + 1

    def unpin(self, slabs) -> None:
        with self._cv:
            for s in set(slabs):
                c = self._pins.get(s, 0) - 1
                if c <= 0:
                    self._pins.pop(s, None)
                else:
                    self._pins[s] = c
            # a batch whose pinned set overcommitted the budget shrinks
            # back the moment its pins release — the budget is the
            # steady-state bound, enforced at every release point
            self._evict_to_fit(0)
            self._cv.notify_all()

    def ensure(self, slab: int, count_stall: bool = True):
        """Return the slab's resident engine, promoting it first if
        needed. A promotion the caller had to WAIT for (cold/host miss, or
        an in-flight promotion it parked behind) is a counted stall unless
        ``count_stall=False`` (warmup/prefetch — data motion the stream
        never waited on)."""
        slab = self._as_key(slab)
        t0 = None
        while True:
            with self._cv:
                ent = self._device.get(slab)
                if ent is not None:
                    ent.tick = self._next_tick()
                    if t0 is None:
                        self.device_hits += 1
                        acct = self._tacct(slab)
                        if acct is not None:
                            acct["device_hits"] += 1
                    elif count_stall:
                        self._note_stall(self._clock() - t0, slab)
                    return ent.engine
                if slab in self._promoting:
                    # another thread (usually the promotion worker) is
                    # already building it — park until it lands
                    if t0 is None:
                        t0 = self._clock()
                    self._cv.wait(0.05)
                    continue
                self._promoting.add(slab)
                if t0 is None:
                    t0 = self._clock()
            break
        try:
            eng = self._build(slab)
        except BaseException as e:
            with self._cv:
                self._promoting.discard(slab)
                self.promotion_errors += 1
                self.last_error = f"slab {slab}: {type(e).__name__}: {e}"
                self._cv.notify_all()
            raise
        with self._cv:
            self._evict_to_fit(eng.device_bytes)
            self._device[slab] = _DeviceSlab(eng, eng.device_bytes,
                                             self._next_tick())
            self._device_bytes += int(eng.device_bytes)
            self._promoting.discard(slab)
            self.promotions += 1
            acct = self._tacct(slab)
            if acct is not None:
                acct["promotions"] += 1
            if count_stall:
                self._note_stall(self._clock() - t0, slab)
            self._cv.notify_all()
        return eng

    def acquire(self, slabs) -> dict:
        """Ensure every slab of a routed set is resident; {key: engine}."""
        return {self._as_key(s): self.ensure(s) for s in slabs}

    def _build(self, key):
        """Materialize rows (host tier first, cold source on miss) and
        build the slab's engine. Runs with NO pool lock held (the brief
        route/host-tier lookups take the lock; the read + factory do
        not)."""
        with self._cv:
            _tenant, slab, src, fac = self._route(key)
            b, _e = src.bounds[slab]
            rows = self._host.get(key)
            if rows is not None:
                self._host.pop(key)
                self._host[key] = rows  # move-to-end = LRU refresh
                self.host_hits += 1
                acct = self._tacct(key)
                if acct is not None:
                    acct["host_hits"] += 1
        if rows is None:
            rows = src.read(slab)
            with self._cv:
                self.cold_reads += 1
                acct = self._tacct(key)
                if acct is not None:
                    acct["cold_reads"] += 1
                self._host_put(key, rows)
        self._maybe_fault(key)
        return fac(slab, rows, b)

    def _maybe_fault(self, key) -> None:
        """Deterministic promotion faults (serve/faults.py): ``latency``
        slows the upload (the slow-promotion stall drill), any other op
        fails it — both on the same seeded grammar the HTTP handlers
        use, keyed as ``PROMOTE /slab/<id>`` (int keys) or
        ``PROMOTE /slab/<tenant>/<id>`` (tenant keys)."""
        if self._faults is None or not self._faults.active():
            return
        path = (f"/slab/{key[0]}/{key[1]}" if isinstance(key, tuple)
                else f"/slab/{key}")
        spec = self._faults.decide(path, "PROMOTE")
        if spec is None:
            return
        if spec.op == "latency":
            self._sleep(spec.delay_s)
        else:
            raise RuntimeError(f"injected promotion fault: {spec.op}")

    # -------------------------------------------------------------- prefetch

    def prefetch(self, slabs) -> None:
        """Enqueue async promotions (dedup against resident / in-flight /
        already-queued). The promotion thread uploads them under the
        in-flight batch's compute; a prefetched slab later ``ensure``d is
        a device hit — zero stall."""
        todo = []
        with self._cv:
            if self._closed:
                return
            for s in slabs:
                s = self._as_key(s)
                ent = self._device.get(s)
                if ent is not None:
                    # a hint declares the WHOLE set hot: refresh resident
                    # members' LRU ticks so promoting the missing ones
                    # cannot evict a sibling of the same hinted set
                    ent.tick = self._next_tick()
                    continue
                if s in self._promoting or s in self._queued:
                    continue
                self._queued.add(s)
                todo.append(s)
                acct = self._tacct(s)
                if acct is not None:
                    acct["prefetch_enqueued"] += 1
            self.prefetch_enqueued += len(todo)
        for s in todo:
            self._pq.put(s)

    def _prefetch_loop(self) -> None:
        while True:
            s = self._pq.get()
            if s is None:
                return
            try:
                self.ensure(s, count_stall=False)
            except Exception:
                # ensure's failure path already recorded the cause in
                # promotion_errors/last_error; count the PREFETCH-path
                # share separately and survive — a dead promotion thread
                # would turn every later miss into a stall
                with self._cv:
                    self.prefetch_errors += 1
            finally:
                # dequeue only AFTER the promotion finished (or failed):
                # discarding before ensure() marks _promoting would open
                # a window where wait_idle sees both sets empty and
                # reports idle mid-build
                with self._cv:
                    self._queued.discard(s)
                    self._cv.notify_all()

    def seed_host(self, slab: int, rows) -> None:
        """Pre-populate the host tier (LRU-capped as usual) without
        touching the hit/miss counters — the startup AABB scan already
        read these rows, so the first promotions should not re-read the
        cold tier for them."""
        with self._cv:
            self._host_put(self._as_key(slab), rows)

    def warm_fill(self, slabs, est_bytes: int) -> list:
        """Promote slabs in order until the next would exceed the budget
        (``est_bytes`` = one slab's footprint; all pool slabs share a
        shape class, so one estimate covers them). Synchronous and
        stall-free by definition — this is warmup, the stream has not
        started."""
        done = []
        for s in slabs:
            s = self._as_key(s)
            with self._cv:
                if s in self._device:
                    continue
                if (self._budget > 0
                        and self._device_bytes + est_bytes > self._budget):
                    break
            self.ensure(s, count_stall=False)
            done.append(s)
        return done

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until no promotion is queued or in flight (tests + the
        prefetch-overlap bench use this to separate 'announced ahead'
        from 'stalled on')."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._queued or self._promoting:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.05))
            return True

    # ----------------------------------------------------------------- admin

    def set_device_budget(self, nbytes: int) -> None:
        """Retune the device budget at runtime; shrinking evicts LRU
        unpinned slabs immediately."""
        with self._cv:
            self._budget = int(nbytes)
            self._evict_to_fit(0)

    def resident_engines(self) -> list:
        with self._cv:
            return [ent.engine for ent in self._device.values()]

    def resident_items(self) -> list:
        """[(key, engine)] for every device-resident slab — per-tenant
        facades filter this to their own keys."""
        with self._cv:
            return [(k, ent.engine) for k, ent in self._device.items()]

    def resident_slabs(self) -> list:
        with self._cv:
            return sorted(self._device)

    def close(self) -> None:
        with self._cv:
            self._closed = True
        self._pq.put(None)
        self._thread.join(timeout=10)

    def stats(self) -> dict:
        with self._cv:
            out = {
                "num_slabs": sum(src.num_slabs
                                 for src, _fac in self._routes.values()),
                "device_resident": len(self._device),
                "host_resident": len(self._host),
                "device_bytes_used": self._device_bytes,
                "device_budget_bytes": self._budget,
                "host_pool_slabs": self._host_cap,
                "host_pool_bytes": self._host_bytes_cap,
                "host_bytes_used": self._host_bytes,
                "resident_slabs": sorted(self._device),
                "pinned_slabs": sorted(self._pins),
                "promotions": self.promotions,
                "promotion_errors": self.promotion_errors,
                "last_error": self.last_error,
                "evictions": self.evictions,
                "host_evictions": self.host_evictions,
                "device_hits": self.device_hits,
                "host_hits": self.host_hits,
                "cold_reads": self.cold_reads,
                "overcommits": self.overcommits,
                "prefetch_enqueued": self.prefetch_enqueued,
                "prefetch_errors": self.prefetch_errors,
                "stream_stalls": self.stream_stalls,
                "stream_stall_seconds": round(self.stream_stall_seconds, 6),
            }
            if self._tenants:
                per = {}
                for t, acct in self._tenants.items():
                    d = dict(acct)
                    d["stream_stall_seconds"] = round(
                        d["stream_stall_seconds"], 6)
                    d["device_resident"] = sum(
                        1 for k in self._device
                        if isinstance(k, tuple) and k[0] == t)
                    d["host_resident"] = sum(
                        1 for k in self._host
                        if isinstance(k, tuple) and k[0] == t)
                    d["pinned"] = sum(
                        1 for k in self._pins
                        if isinstance(k, tuple) and k[0] == t)
                    per[t] = d
                out["tenants"] = per
            return out


class _StreamHandle:
    """A dispatched-but-uncompleted streaming batch: the original queries
    (degradation replay + escalation sub-batches), the bounds table's
    lower bounds, the visited matrix, the per-slab in-flight sub-batches,
    the pinned slab set ``complete`` releases, and the recall plan
    (serve/recall.py, None = exact) the batch runs under."""

    __slots__ = ("queries", "n", "engine_name", "t0", "lb", "visited",
                 "subs", "pinned", "plan", "skip_cold", "seeds")

    def __init__(self, queries, n, engine_name, t0, plan=None, seeds=None):
        self.queries = queries
        self.n = n
        self.engine_name = engine_name
        self.t0 = t0
        self.lb = None
        self.visited = None
        self.subs = []
        self.pinned = set()
        self.plan = plan
        #: certified per-row init radii (serve/qcache.py; None = unseeded)
        #: — rides the handle so the fold and every escalation sub-batch
        #: start their heaps at the same certified bound
        self.seeds = seeds
        #: dispatch's ADMITTED skip-cold decision for this batch (the
        #: drift guard may refuse the plan's ask); the fold must follow
        #: the same decision or wave 1 and escalation would disagree
        self.skip_cold = False


class StreamingKnnEngine:
    """Engine facade over a ``SlabPool``: serve an index bigger than
    device memory, bit-identical to a fully-resident engine.

    Same ``dispatch``/``complete``/``query`` contract as
    ``ResidentKnnEngine`` (the batcher, admission wrapper, and HTTP
    server drive it unchanged); ``emit='candidates'`` additionally serves
    ``complete_candidates`` so a routed pod host can itself stream
    sub-slabs (serve_main ``--routing bounds --num-slabs``). Thread
    compatibility matches the resident engine's: the batcher's dispatch
    and completion workers may overlap one batch's escalation with the
    next batch's wave 1 — the pool lock and each slab engine's own lock
    serialize what must serialize.
    """

    def __init__(self, path: str | None = None, *, points=None,
                 num_slabs: int = 0, k: int, device_slab_budget: int = 0,
                 host_pool_slabs: int = 0, host_pool_bytes: int = 0,
                 prefetch_depth: int = 1,
                 mesh=None, engine: str = "auto", bucket_size: int = 0,
                 max_radius: float = math.inf, max_batch: int = 1024,
                 min_batch: int = 8, merge: str = "auto",
                 query_buckets: int = 0, score_dtype: str = "f32",
                 id_offset: int = 0, emit: str = "final",
                 faults: FaultInjector | None = None,
                 source_url: str | None = None,
                 source_wire: str = "d16",
                 source_throttle_bps: float | None = None,
                 skip_cold_stall_limit: float = 0.25,
                 source: SlabSource | None = None,
                 pool: SlabPool | None = None,
                 tenant: str | None = None,
                 shared_exec_cache=None, pad_shard_rows: int = 0,
                 timers: PhaseTimers | None = None,
                 clock=time.perf_counter):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import get_mesh
        from mpi_cuda_largescaleknn_tpu.parallel.ring import resolve_engine
        from mpi_cuda_largescaleknn_tpu.serve.engine import ExecutableCache
        from mpi_cuda_largescaleknn_tpu.serve.frontend import (
            routing_cert_slack,
        )

        if emit not in ("final", "candidates"):
            raise ValueError(f"emit must be 'final' or 'candidates', "
                             f"got {emit!r}")
        if pool is not None and tenant is None:
            raise ValueError("a shared pool= needs a tenant= namespace "
                             "for this engine's (tenant, slab) keys")
        if source is not None:
            self._source = source
        else:
            self._source = SlabSource(path=path, points=points,
                                      url=source_url, num_slabs=num_slabs,
                                      wire=source_wire,
                                      throttle_bps=source_throttle_bps)
        self.num_slabs = self._source.num_slabs
        self.n_points = self._source.n_total
        self.dim = self._source.dim
        if self.n_points < 1:
            raise ValueError("streaming engine needs a non-empty index")
        self.k = int(k)
        self.id_offset = int(id_offset)
        self.emit = emit
        self.max_radius = float(max_radius)
        self.prefetch_depth = int(prefetch_depth)
        self.device_slab_budget = int(device_slab_budget)
        self.host_pool_slabs = int(host_pool_slabs)
        self.host_pool_bytes = int(host_pool_bytes)
        self.tenant = tenant
        self._clock = clock
        #: never retains host rows itself (the pool's tiers do) — the
        #: /slab_rows pull path needs a single contiguous array, which a
        #: streaming host by definition does not keep
        self.host_points = None
        self.mesh = mesh if mesh is not None else get_mesh(None)
        #: shared accounting sink: every slab engine counts fetch/result/
        #: tile totals here, so eviction never zeroes the /stats surface.
        #: A multi-tenant facade passes ONE timers + executable cache to
        #: every tenant view, so compiled programs (and their counters)
        #: are shared across tenants — tenant count never becomes
        #: compile count
        self.timers = timers if timers is not None else PhaseTimers()
        self._exec_cache = (shared_exec_cache if shared_exec_cache
                            is not None else ExecutableCache())
        self.cert_slack = routing_cert_slack(self.dim)
        self._meta_lock = threading.Lock()
        self._engine_name: guarded_by("_meta_lock") = resolve_engine(engine)
        self._degraded_reason: guarded_by("_meta_lock") = None
        self._launch_workers: guarded_by("_meta_lock") = 1
        #: drift guard for the recall tier: recent (clock, cumulative
        #: stall seconds) samples; when the pool is ALREADY stalling
        #: above ``skip_cold_stall_limit`` (fraction of wall time spent
        #: stalled over the sampled window), a ``stream_skip_cold`` plan
        #: is REFUSED for the batch — under traffic drift the skip tier
        #: collapses recall AND still pays promotion churn (TUNING.md),
        #: so exact serving is strictly the better failure mode
        self.skip_cold_stall_limit = float(skip_cold_stall_limit)
        self._stall_ring: guarded_by("_meta_lock") = []
        self.skip_cold_refusals: guarded_by("_meta_lock") = 0
        #: one shape class for every slab engine: pad each engine's local
        #: shards to the LARGEST slab's per-shard row count, so the shared
        #: ExecutableCache hits across slabs and re-promotions
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS

        num_shards = self.mesh.shape[AXIS]
        max_slab = max(e - b for b, e in self._source.bounds)
        #: one shape class across the POOL: at least this engine's
        #: largest slab, or a caller-supplied class (the multi-tenant
        #: facade passes the max over every tenant so the shared cache
        #: hits across all of them)
        self._pad_shard = max(int(pad_shard_rows),
                              -(-max_slab // num_shards))
        self._engine_kw = dict(
            bucket_size=bucket_size, max_radius=max_radius,
            max_batch=max_batch, min_batch=min_batch, merge=merge,
            query_buckets=query_buckets, score_dtype=score_dtype)
        if pool is not None:
            self._pool = pool
            self._owns_pool = False
            self._pool.register(tenant, self._source, self._make_engine)
        else:
            # a standalone tenant-keyed engine registers its source under
            # the tenant namespace ONLY (every pool key is (tenant, slab));
            # the legacy None route exists just for bare-int keys
            self._pool = SlabPool(
                None if tenant is not None else self._source,
                self._make_engine,
                device_budget_bytes=device_slab_budget,
                host_pool_slabs=host_pool_slabs,
                host_pool_bytes=host_pool_bytes, faults=faults,
                clock=clock)
            self._owns_pool = True
            if tenant is not None:
                self._pool.register(tenant, self._source,
                                    self._make_engine)
        #: per-slab routing boxes (the in-process PodBoundsTable): f64
        #: lo/hi per non-empty slab, +inf lower bound for empty ones.
        #: The scan's rows seed the pool's host tier as they stream by —
        #: the first promotions then re-read RAM, not the cold source
        aabbs = self._source.scan_aabbs(
            sink=lambda s, rows: self._pool.seed_host(self._pkey(s), rows))
        self.slab_aabbs = aabbs
        self._nonempty = np.array([a["count"] > 0 for a in aabbs], bool)
        self._slab_lo = np.array([a["lo"] if a["lo"] is not None
                                  else [np.inf] * self.dim for a in aabbs],
                                 np.float64).reshape(-1, self.dim)
        self._slab_hi = np.array([a["hi"] if a["hi"] is not None
                                  else [-np.inf] * self.dim for a in aabbs],
                                 np.float64).reshape(-1, self.dim)
        # bootstrap: promote the first non-empty slab and adopt its
        # resolved config as the template every sibling shares (all slab
        # engines are built from the same knobs + shape class)
        first = int(np.argmax(self._nonempty))
        t = self._pool.ensure(self._pkey(first), count_stall=False)
        self._template_slab = first
        self.max_batch = t.max_batch
        self.shape_buckets = list(t.shape_buckets)
        self.query_buckets = dict(t.query_buckets)
        self.query_buckets_setting = t.query_buckets_setting
        self.merge_mode = t.merge_mode
        self.score_dtype = t.score_dtype
        self.score_mode = t.score_mode
        self.sort_queries = t.sort_queries
        self.bucket_size = t.bucket_size
        self.num_shards = t.num_shards
        self.slab_device_bytes = int(t.device_bytes)
        self.canonical_ties = t.canonical_ties
        #: pod-surface compatibility (a streaming engine is always one
        #: process; routed hosts wrap it with emit='candidates')
        self.process_index = 0
        self.process_count = 1

    # ---------------------------------------------------------- pool keying

    def _pkey(self, slab: int):
        """This engine's pool key for a local slab id: bare ints for an
        owned single-index pool, (tenant, slab) in a shared pool."""
        return (self.tenant, int(slab)) if self.tenant is not None \
            else int(slab)

    def _pkeys(self, slabs) -> list:
        return [self._pkey(s) for s in slabs]

    def _resident_local(self) -> set:
        """This engine's device-resident LOCAL slab ids (a shared pool
        holds other tenants' keys too — filter to ours)."""
        if self.tenant is None:
            return set(self._pool.resident_slabs())
        return {k[1] for k in self._pool.resident_slabs()
                if isinstance(k, tuple) and k[0] == self.tenant}

    def _my_engines(self) -> list:
        if self.tenant is None:
            return self._pool.resident_engines()
        return [e for k, e in self._pool.resident_items()
                if isinstance(k, tuple) and k[0] == self.tenant]

    # ------------------------------------------------------------ engine mgmt

    def _make_engine(self, slab: int, rows: np.ndarray, row_begin: int):
        """SlabPool engine factory: one canonical-tie candidates engine
        per slab, global ids via the slab's row origin, shared timers +
        AOT cache, common shape class."""
        from mpi_cuda_largescaleknn_tpu.serve.engine import ResidentKnnEngine

        with self._meta_lock:
            engine_name = self._engine_name
            workers = self._launch_workers
        eng = ResidentKnnEngine(
            rows, self.k, mesh=self.mesh, engine=engine_name,
            id_offset=self.id_offset + int(row_begin), emit="candidates",
            timers=self.timers, executable_cache=self._exec_cache,
            pad_shard_rows=self._pad_shard, **self._engine_kw)
        if workers > 1:
            eng.set_launch_workers(workers)
        return eng

    @property
    def slab_pool(self) -> SlabPool:
        return self._pool

    @property
    def engine_name(self) -> str:
        with self._meta_lock:
            return self._engine_name

    @property
    def degraded_reason(self) -> str | None:
        with self._meta_lock:
            return self._degraded_reason

    def can_degrade(self) -> bool:
        with self._meta_lock:
            return self._engine_name == "pallas_tiled"

    def degrade(self, reason: str) -> None:
        """Swap every resident slab engine (and all future promotions) to
        the XLA twin — the resident engine's degradation contract, pool
        wide. Identical results by the twin-engine contract."""
        with self._meta_lock:
            if self._engine_name != "pallas_tiled":
                raise RuntimeError(
                    f"engine '{self._engine_name}' has no fallback")
            self._engine_name = "tiled"
            self._degraded_reason = reason
        for eng in self._my_engines():
            if eng.can_degrade():
                eng.degrade(reason)

    def set_launch_workers(self, n: int) -> None:
        with self._meta_lock:
            self._launch_workers = max(1, int(n))
            n = self._launch_workers
        for eng in self._my_engines():
            eng.set_launch_workers(n)

    def warmup(self) -> dict:
        """Compile every shape bucket ONCE (into the shared cache — every
        slab engine reuses them), then fill the remaining device budget
        with slabs in row order. Returns the template's warmup dict plus
        the warm-fill summary."""
        t = self._pool.ensure(self._pkey(self._template_slab),
                              count_stall=False)
        info = t.warmup()
        filled = self._pool.warm_fill(
            self._pkeys(s for s in range(self.num_slabs)
                        if self._nonempty[s] and s != self._template_slab),
            self.slab_device_bytes)
        info["warm_slabs"] = sorted(
            [self._template_slab]
            + [k[1] if isinstance(k, tuple) else k for k in filled])
        return info

    # ----------------------------------------------------------------- routing

    def _lower_bounds(self, q: np.ndarray) -> np.ndarray:
        """f64[n, S] squared lower-bound distance per (query, slab); +inf
        for empty slabs — the PodBoundsTable decision, in-process."""
        from mpi_cuda_largescaleknn_tpu.utils.math import (
            aabb_lower_bound_dist2,
        )

        out = np.full((len(q), self.num_slabs), np.inf)
        ne = self._nonempty
        if ne.any() and len(q):
            out[:, ne] = aabb_lower_bound_dist2(
                q, self._slab_lo[ne], self._slab_hi[ne])
        return out

    def _wave1_want(self, q: np.ndarray):
        """The PR-7 wave-1 routing rule, shared by dispatch and the
        prefetcher so hints can never warm a different slab set than the
        dispatch will pin: each query wants its nearest-bounds slab PLUS
        every slab whose box contains it (a zero lower bound can never
        certify away). Returns (lb f64[n, S], want bool[n, S])."""
        lb = self._lower_bounds(q)
        first = np.argmin(lb, axis=1)
        reach = np.isfinite(lb[np.arange(len(q)), first])
        want = lb <= 0.0
        rows_r = np.nonzero(reach)[0]
        want[rows_r, first[rows_r]] = True
        return lb, want

    def prefetch_hint(self, queries) -> None:
        """Announce a FUTURE batch's rows: compute its wave-1 slab set and
        enqueue async promotions, so by the time that batch dispatches its
        slabs are warm (the batcher calls this with the queued rows right
        after dispatching the current batch — the PR-2 overlap applied to
        data motion)."""
        q = np.asarray(queries, np.float32).reshape(-1, self.dim)
        if len(q) == 0:
            return
        _lb, want = self._wave1_want(q)
        self.timers.count("prefetch_hints", 1)
        self._pool.prefetch(
            self._pkeys(np.nonzero(want.any(axis=0))[0].tolist()))

    # --------------------------------------------------------------- query API

    #: samples kept by the drift guard: enough history to smooth one
    #: noisy batch, short enough that recovery re-admits within ~a ring
    skip_cold_window = 64

    def _skip_cold_admit(self) -> bool:
        """Drift-aware admission for ``stream_skip_cold`` (TUNING.md's
        PR-16 caveat, closed): sample the pool's cumulative stall clock,
        and refuse the recall plan when the stall FRACTION over the
        sampled window is already above ``skip_cold_stall_limit`` — a
        pool that busy promoting is in traffic drift, where skipping
        collapses recall without saving the churn. Counted in
        ``skip_cold_refusals``; rides the injectable clock."""
        now = self._clock()
        _stalls, stall_s = self._pool.stall_totals(self.tenant)
        with self._meta_lock:
            ring = self._stall_ring
            ring.append((now, stall_s))
            if len(ring) > self.skip_cold_window:
                del ring[0]
            t0, s0 = ring[0]
            span = now - t0
            if len(ring) < 2 or span <= 0.0:
                return True  # no signal yet: admit
            if (stall_s - s0) / span > self.skip_cold_stall_limit:
                self.skip_cold_refusals += 1
                return False
            return True

    def dispatch(self, queries: np.ndarray, plan=None,
                 seed_radius=None) -> _StreamHandle:
        """Wave 1 of the streamed batch: route rows to their
        nearest-bounds slab plus every slab whose box contains them (the
        PR-7 rule — a zero lower bound can never certify away), PIN that
        slab set, promote any non-resident member (a stall, counted), and
        launch the per-slab sub-batches on the slab engines' async launch
        pools. Also enqueues prefetch for the next-nearest
        ``prefetch_depth`` slabs — the likely escalation targets — so an
        escalation wave finds them warm.

        ``plan`` (serve/recall.py RecallPlan, None = exact): the program
        knobs ride into each slab engine's plan-keyed executable, and
        ``stream_skip_cold`` defers every cold wave-1 slab except each
        query's NEAREST one (always ensured, even cold, so every row gets
        k real candidates) — deferred slabs warm asynchronously and are
        reconsidered against the folded kth distance in the escalation
        loop, where a still-cold one is SKIPPED for recall instead of
        stalled on (``stream_skipped_promotions``)."""
        queries = np.ascontiguousarray(
            np.asarray(queries, np.float32).reshape(-1, self.dim))
        n = len(queries)
        # certified radius seeds (serve/qcache.py): exact tier only — an
        # approximate plan's visit schedule (skip_cold) must not interact
        # with a tightened init radius, so seeds are dropped under a plan
        seeds = None
        if seed_radius is not None and plan is None:
            seeds = np.asarray(seed_radius, np.float32).reshape(-1)
            if len(seeds) != n:
                raise ValueError(
                    f"seed_radius has {len(seeds)} rows for {n} queries")
            if not np.any(np.isfinite(seeds)):
                seeds = None
        handle = _StreamHandle(queries, n, self.engine_name, self._clock(),
                               plan=plan, seeds=seeds)
        if n == 0:
            return handle
        lb, want = self._wave1_want(queries)
        visited = np.zeros((n, self.num_slabs), bool)
        handle.skip_cold = (plan is not None and plan.stream_skip_cold
                            and self._skip_cold_admit())
        if handle.skip_cold:
            resident = self._resident_local()
            first = np.argmin(lb, axis=1)
            must = set(int(s) for i, s in enumerate(first)
                       if np.isfinite(lb[i, s]))
            deferred = [s for s in np.nonzero(want.any(axis=0))[0].tolist()
                        if s not in resident and s not in must]
            if deferred:
                # serve this batch from what is warm; warm the rest UNDER
                # its compute for the escalation pass / future batches
                want[:, deferred] = False
                self._pool.prefetch(self._pkeys(deferred))
        wave = [(s, np.nonzero(want[:, s])[0])
                for s in range(self.num_slabs) if want[:, s].any()]
        sids = [s for s, _rows in wave]
        self._pool.pin(self._pkeys(sids))
        handle.pinned.update(sids)
        # hand the whole wave to the promotion thread first: a multi-slab
        # cold wave then builds one slab on this thread while the next
        # builds asynchronously, instead of strictly serial stalls
        self._pool.prefetch(self._pkeys(sids))
        try:
            for s, rows in wave:
                eng = self._pool.ensure(self._pkey(s))
                # seeded slab sub-batch: a slab-local init slot (seed², -1)
                # only ever displaces candidates with d2 ≥ seed², which sit
                # strictly beyond the certified global kth — the fold pushes
                # every filler slot out before certification closes
                if seeds is not None:
                    sub = eng.dispatch(queries[rows],
                                       seed_radius=seeds[rows])
                elif plan is None:
                    sub = eng.dispatch(queries[rows])
                else:
                    sub = eng.dispatch(queries[rows], plan=plan)
                handle.subs.append((s, rows, eng, sub))
                visited[rows, s] = True
        except BaseException:
            # a failed promotion/dispatch must not leak this batch's pins
            # — leaked pins would make the slabs permanently unevictable
            self._pool.unpin(self._pkeys(handle.pinned))
            handle.pinned = set()
            raise
        handle.lb, handle.visited = lb, visited
        if self.prefetch_depth > 0:
            # escalation insurance: the unvisited slabs nearest ANY row of
            # this batch are the ones its escalation waves would stall on
            rest = np.where(want.any(axis=0), np.inf, lb.min(axis=0))
            order = np.argsort(rest, kind="stable")
            depth = [int(s) for s in order[:self.prefetch_depth]
                     if np.isfinite(rest[s])]
            if depth:
                self._pool.prefetch(self._pkeys(depth))
        return handle

    def _complete_fold(self, handle: _StreamHandle):
        """Fold wave partials; escalate uncertified (query, slab) pairs
        until certification closes — the RoutedPodFanout loop, in-process
        and loss-free (every slab is always reachable: a miss stalls, it
        never drains). Returns the folded (d2[n, k], idx[n, k])."""
        from mpi_cuda_largescaleknn_tpu.serve.frontend import fold_candidates

        n, k = handle.n, self.k
        cur_d2 = np.full((n, k), np.inf, np.float32)
        cur_idx = np.full((n, k), -1, np.int32)
        seeds = handle.seeds
        if seeds is not None:
            # certified seeds bound the fold's running kth from wave 1 on:
            # r2 starts at seed² (> the true kth², strictly), so escalation
            # promotes strictly fewer slabs while every slab holding a true
            # top-k or boundary-tied candidate still satisfies
            # lb_safe <= true kth² <= r2 at every wave — identical answer
            cur_d2[:] = (seeds * seeds)[:, None]
        q, lb, visited = handle.queries, handle.lb, handle.visited
        plan = handle.plan
        # recall plan: (c) shave the escalation margin, (d) never stall
        # an escalation wave on a cold slab — skip it for recall instead
        slack = float(plan.route_slack) if plan is not None else 0.0
        skip_cold = handle.skip_cold
        lb_safe = lb * (1.0 - self.cert_slack)
        reachable = np.isfinite(lb_safe)
        subs = handle.subs
        try:
            wave = 1
            while True:
                for s, rows, eng, sub in subs:
                    d2p, idxp = eng.complete_candidates(sub)
                    fold_candidates(cur_d2, cur_idx, rows, d2p, idxp, k)
                r2 = cur_d2[:, k - 1].astype(np.float64)
                need = (~visited) & reachable & (
                    lb_safe <= r2[:, None] * (1.0 - slack))
                if not need.any():
                    break
                sids = [s for s in range(self.num_slabs) if need[:, s].any()]
                if skip_cold:
                    resident = self._resident_local()
                    cold = [s for s in sids if s not in resident]
                    if cold:
                        # the recall sacrifice (d) makes: these bounds
                        # COULD beat the kth distance, but the slab is not
                        # device-resident — give those pairs up, count the
                        # skipped promotions, and warm the slabs async so
                        # the NEXT batch finds them resident
                        self.timers.count("stream_skipped_promotions",
                                          len(cold))
                        for s in cold:
                            visited[need[:, s], s] = True
                        self._pool.prefetch(self._pkeys(cold))
                        sids = [s for s in sids if s in resident]
                        if not sids:
                            continue
                if wave == 1:
                    self.timers.count("stream_escalations",
                                      int(need.any(axis=1).sum()))
                self.timers.count("stream_escalation_waves", 1)
                wave += 1
                new = [s for s in sids if s not in handle.pinned]
                if new:
                    self._pool.pin(self._pkeys(new))
                    handle.pinned.update(new)
                    # overlap multi-slab waves
                    self._pool.prefetch(self._pkeys(new))
                subs = []
                for s in sids:
                    rows = np.nonzero(need[:, s])[0]
                    eng = self._pool.ensure(self._pkey(s))
                    if seeds is not None:
                        sub = eng.dispatch(q[rows], seed_radius=seeds[rows])
                    elif plan is None:
                        sub = eng.dispatch(q[rows])
                    else:
                        sub = eng.dispatch(q[rows], plan=plan)
                    subs.append((s, rows, eng, sub))
                    visited[rows, s] = True
        finally:
            self._pool.unpin(self._pkeys(handle.pinned))
            handle.pinned = set()
        self.timers.hist("stream_batch_seconds").record(
            self._clock() - handle.t0)
        self.timers.count("stream_batches", 1)
        return cur_d2, cur_idx

    def complete(self, handle: _StreamHandle):
        """(dists f32[n], idx i32[n, k]) — the public engine contract,
        bit-identical to a fully-resident engine of the same knobs (the
        canonical fold over canonical-tie slab partials; the routed-pod
        parity argument with slabs instead of hosts)."""
        if handle.n == 0:
            return (np.zeros(0, np.float32),
                    np.zeros((0, self.k), np.int32))
        if self.emit == "candidates":
            raise RuntimeError(
                "emit='candidates' streaming engines return full candidate"
                " rows — use complete_candidates (the routed host's fold)")
        d2, idx = self._complete_fold(handle)
        return np.sqrt(d2[:, self.k - 1]), idx

    def complete_candidates(self, handle: _StreamHandle):
        """Routed-host streaming ``complete``: the folded full candidate
        rows (dist2[n, k], idx[n, k]) over this engine's slabs — what
        POST /route_knn serves when a routed host streams sub-slabs."""
        if handle.n == 0:
            return (np.full((0, self.k), np.inf, np.float32),
                    np.full((0, self.k), -1, np.int32))
        if self.emit != "candidates":
            raise RuntimeError(
                "engine was built with emit='final' — construct with "
                "emit='candidates' for the routed candidate-row contract")
        return self._complete_fold(handle)

    def query(self, queries: np.ndarray, plan=None, seed_radius=None):
        return self.complete(self.dispatch(queries, plan=plan,
                                           seed_radius=seed_radius))

    def refetch_exact(self, queries):
        """Survivor re-fetch hook (PR-17 quantized wire): exact f32
        candidate rows, byte-equal to any earlier batch containing these
        rows — the streaming fold is bit-deterministic per query row
        (commutative fold + certification closure), so re-asking costs a
        promotion at worst, never bits."""
        return self.complete_candidates(self.dispatch(queries))

    def close(self) -> None:
        if self._owns_pool:
            self._pool.close()

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict:
        pool = self._pool.stats()
        cache = self._exec_cache.stats()
        if self.tenant is None:
            my_resident = pool["device_resident"]
        else:
            mine = pool.get("tenants", {}).get(self.tenant, {})
            my_resident = int(mine.get("device_resident", 0))
        with self._meta_lock:
            engine_name = self._engine_name
            degraded_reason = self._degraded_reason
            skip_cold_refusals = self.skip_cold_refusals
        return {
            "engine": engine_name,
            "merge": self.merge_mode,
            "score_dtype": self.score_dtype,
            "score_mode": self.score_mode,
            "dim": self.dim,
            "degraded_reason": degraded_reason,
            "n_points": self.n_points,
            "k": self.k,
            "num_shards": self.num_shards,
            "multihost": False,
            "process_index": self.process_index,
            "process_count": self.process_count,
            "my_positions": list(range(self.num_shards)),
            "row_offset": self.id_offset,
            "emit": self.emit,
            "canonical_ties": self.canonical_ties,
            "max_radius": (None if math.isinf(self.max_radius)
                           else self.max_radius),
            # the routing surface a pod front end folds over: one box per
            # SLAB (the streaming engine's own routing granularity)
            "shard_bounds": self.slab_aabbs,
            "device_bytes": self.slab_device_bytes * my_resident,
            "max_batch": self.max_batch,
            "bucket_size": self.bucket_size,
            "shape_buckets": list(self.shape_buckets),
            # AOT discipline pool-wide: the shared cache's compile count is
            # the recompile-freedom number (flat across slab churn)
            "compiled_shapes": cache["shapes"],
            "compile_count": cache["compiles"],
            "executable_cache": cache,
            "query_buckets": {str(qv): b for qv, b in
                              sorted(self.query_buckets.items())},
            "sort_queries": self.sort_queries,
            "tiles_executed": self.timers.counter("tiles_executed"),
            "tiles_skipped": self.timers.counter("tiles_skipped"),
            "tiles_executed_mxu": self.timers.counter("tiles_executed_mxu"),
            "tiles_skipped_mxu": self.timers.counter("tiles_skipped_mxu"),
            "tiles_executed_vpu": self.timers.counter("tiles_executed_vpu"),
            "tiles_skipped_vpu": self.timers.counter("tiles_skipped_vpu"),
            "fetch_bytes": self.timers.counter("fetch_bytes"),
            "result_rows": self.timers.counter("result_rows"),
            # the tiered-index surface: per-tier residency, budget, hit/
            # miss counters, promotion/eviction totals, stall accounting
            "slab_pool": dict(
                pool,
                slab_device_bytes=self.slab_device_bytes,
                prefetch_depth=self.prefetch_depth,
                prefetch_hints=self.timers.counter("prefetch_hints"),
                **({} if self.tenant is None
                   else {"tenant": self.tenant})),
            "streaming": {
                "num_slabs": self.num_slabs,
                "batches": self.timers.counter("stream_batches"),
                "escalations": self.timers.counter("stream_escalations"),
                "escalation_waves":
                    self.timers.counter("stream_escalation_waves"),
                # recall-SLO tier (stream_skip_cold): cold-slab promotions
                # skipped for recall instead of stalled on
                "skipped_promotions":
                    self.timers.counter("stream_skipped_promotions"),
                # drift guard (PR 17): skip-cold plans refused because
                # the pool's stall fraction was already above the limit
                "skip_cold_refusals": skip_cold_refusals,
                "skip_cold_stall_limit": self.skip_cold_stall_limit,
            },
            "timers": self.timers.report(),
        }
