"""Certified query cache: exact-hit reuse, in-flight dedup, radius seeds.

Real user traffic is massively repetitive, and a served kNN answer over an
immutable index is a *certificate*: the same query bytes must produce the
same answer bytes, and a near-duplicate query's answer is bounded by the
triangle inequality. This module turns those two facts into three reuse
tiers, all exactness-preserving, threaded through the batcher
(serve/batcher.py ``submit``):

1. **Exact-hit LRU** — keyed by (tenant, index generation, plan token,
   query row bytes). A repeat query is served verbatim from the cached
   row: byte-identical response, zero device work. Sound because the
   index is immutable per generation (``invalidate()`` bumps the
   generation and drops everything when an index ever swaps).
2. **In-flight dedup** — the first submitter of a row becomes its OWNER
   (the row runs on the device once); identical rows arriving before the
   owner publishes JOIN the in-flight entry and receive the same bytes.
   A thundering herd of one query costs one row of compute. If the owner
   fails, joiners are told (``error``) and retry as their own owners —
   a failure never strands a waiter.
3. **Triangle-inequality radius seeding** — for a query q near a cached
   q0 whose kth distance d_k(q0) is known, every true neighbor of q lies
   within r = d_k(q0) + ||q - q0||, so the engine may START its heap at
   r instead of ``max_radius`` and prune tiles sooner
   (``ResidentKnnEngine.dispatch(seed_radius=...)``). The answer is
   provably unchanged — IF the seed never understates the bound.

Seed soundness (the part the tests pin bit-for-bit): the heap adopts
candidates by strict-< against the init slots, and under the canonical
(dist2, id) tie order an init slot ``(seed**2, -1)`` WINS ties against
real candidates. So the f32 seed must satisfy ``f32(seed)**2`` strictly
greater than every true-top-k candidate's device-computed f32 dist2 —
a plain ``nextafter`` in the radius domain is NOT enough (``a**2`` and
``nextafter(a)**2`` can round to the same f32). ``seed_for`` therefore
computes the bound in f64, applies a dimension-scaled multiplicative
slack covering the f32 distance kernel's rounding (mirroring the routed
certification slack), casts to f32, rounds up one more ulp, and floors
the result so ``seed**2`` cannot underflow to 0.0 (which would exclude
distance-0 candidates). Extra slack only admits more candidates — always
safe; only an understated bound could change answers.

Seeds are only drawn from FULL exact rows (all k ids real, finite kth
distance): fullness guarantees at least k true candidates strictly
inside the seed, so every init slot is displaced and the seeded result
is bitwise identical to the unseeded one — including under a finite
engine ``max_radius`` (a clamped seed degenerates to the unseeded init).
Approximate-plan requests are never seeded (their visit schedules
interact with the init radius) and never feed the seed pool; they still
get tiers 1 and 2 under their plan's ``batch_key()`` token.

Shared state discipline: batcher submitter threads and handler threads
race on every structure here, so the LRU, the in-flight registry, the
seed pools and all counters live under one leaf lock (lskcheck's
guarded_by pass proves it; the lock is never held across device work or
another lock).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

#: smallest admissible seed radius: its f32 square (~1e-36) is still a
#: normal-ish positive float, so distance-0 candidates (d2 == 0.0) stay
#: strictly inside the seed and are admitted by the strict-< heap
_SEED_FLOOR = np.float32(1e-18)


class _InFlightRow:
    """One row currently on the device on behalf of its first submitter.

    Joiners park on ``event``; the owner fills ``result`` (the row's
    answer tuple) or ``error`` before setting it. Immutable-after-set, so
    readers need no lock once the event fires."""

    __slots__ = ("event", "result", "error", "joiners")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.joiners = 0


class SeedPool:
    """Ring of recent (query row, certified kth distance) pairs for ONE
    index (one tenant). Fixed capacity, overwrite-oldest; the vectorized
    nearest-source lookup runs on snapshot copies outside the cache lock.
    Only ever fed full exact rows, so every stored dk is a true kth
    distance certificate."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._q: guarded_by("_lock") = None  # f32[capacity, dim], lazy
        self._dk: guarded_by("_lock") = None  # f32[capacity]
        self._count: guarded_by("_lock") = 0
        self._pos: guarded_by("_lock") = 0

    def add(self, qrow: np.ndarray, dk: float) -> None:
        with self._lock:
            if self._q is None:
                self._q = np.empty((self.capacity, len(qrow)), np.float32)
                self._dk = np.empty(self.capacity, np.float32)
            if self._q.shape[1] != len(qrow):
                return  # dim mismatch: never seed across index shapes
            self._q[self._pos] = qrow
            self._dk[self._pos] = np.float32(dk)
            self._pos = (self._pos + 1) % self.capacity
            self._count = min(self._count + 1, self.capacity)

    def snapshot(self):
        """(q f32[m, dim], dk f32[m]) copies — or None when empty."""
        with self._lock:
            if self._count == 0:
                return None
            m = self._count
            return self._q[:m].copy(), self._dk[:m].copy()


def certified_seeds(qrows: np.ndarray, src_q: np.ndarray,
                    src_dk: np.ndarray) -> np.ndarray:
    """Per-row certified init radii for ``qrows`` from cached sources.

    For each query row the bound is ``min_j (dk[j] + ||q - q_j||)``,
    computed in f64, inflated by a dim-scaled slack covering the engine's
    f32 distance rounding, cast to f32 and rounded UP one ulp, floored at
    ``_SEED_FLOOR`` — so the f32 seed's square strictly exceeds every
    true-top-k candidate's device-computed dist2 (the strict-< parity
    requirement in the module docstring). Pure function of its inputs;
    the caller decides which rows actually use their seed."""
    q64 = qrows.astype(np.float64)
    s64 = src_q.astype(np.float64)
    # [n, m] exact-in-f64 pairwise distances (f32 inputs are exact f64)
    d = np.sqrt(((q64[:, None, :] - s64[None, :, :]) ** 2).sum(axis=2))
    bound = np.min(src_dk.astype(np.float64)[None, :] + d, axis=1)
    dim = qrows.shape[1]
    slack = max(16.0 * (dim + 2) * 2.0 ** -24, 1e-5)
    seed = np.nextafter((bound * (1.0 + slack)).astype(np.float32),
                        np.float32(np.inf))
    return np.maximum(seed, _SEED_FLOOR)


class QueryCache:
    """The three-tier reuse layer the batcher threads every request
    through. One instance per server; multi-tenant servers share it (the
    tenant name is part of every key and each tenant has its own seed
    pool — results and seeds NEVER cross indexes).

    ``capacity_rows`` bounds the exact-hit LRU in rows; ``seed_rows``
    bounds each tenant's seed ring. ``fingerprint`` is the serving
    index's identity string (informational — the generation counter is
    what actually fences reuse across index swaps via ``invalidate``).
    """

    def __init__(self, *, capacity_rows: int = 4096, seed_rows: int = 512,
                 fingerprint: str = ""):
        if capacity_rows < 1:
            raise ValueError("capacity_rows must be >= 1")
        self.capacity_rows = int(capacity_rows)
        self.seed_rows = int(seed_rows)
        self.fingerprint = str(fingerprint)
        self._lock = threading.Lock()
        #: key -> row result tuple (arity-generic: (dist, ids[, exact]))
        self._lru: guarded_by("_lock") = OrderedDict()
        #: key -> _InFlightRow owned by some submitter
        self._inflight: guarded_by("_lock") = {}
        #: tenant -> SeedPool (SeedPool has its own leaf lock)
        self._seed_pools: guarded_by("_lock") = {}
        #: index generation: part of every key; invalidate() bumps it
        self._gen: guarded_by("_lock") = 0
        self.hits: guarded_by("_lock") = 0
        self.misses: guarded_by("_lock") = 0
        self.seeds: guarded_by("_lock") = 0
        self.dedup_rows: guarded_by("_lock") = 0
        self.evictions: guarded_by("_lock") = 0
        self.inserts: guarded_by("_lock") = 0
        self.inflight_aborts: guarded_by("_lock") = 0
        #: per-tenant counter twins for the four /metrics series
        self._tenant_counts: guarded_by("_lock") = {}

    # ---------------------------------------------------------------- keys

    def _tcounts(self, tenant):  # lsk: holds[_lock]
        c = self._tenant_counts.get(tenant)
        if c is None:
            c = {"hits": 0, "seeds": 0, "dedup_rows": 0, "evictions": 0}
            self._tenant_counts[tenant] = c
        return c

    def invalidate(self) -> None:
        """Fence a new index generation: drop every cached row and seed.
        In-flight entries keyed under the old generation still complete
        for their joiners; their publication lands in dead keys."""
        with self._lock:
            self._gen += 1
            self._lru.clear()
            self._seed_pools = {}

    # --------------------------------------------------------------- begin

    def begin(self, queries: np.ndarray, plan_token, tenant):
        """Classify every row of a request under one lock acquisition.

        Returns a per-row action list: ``("hit", row_tuple)`` — serve the
        cached bytes; ``("local", j)`` — duplicate of row j of THIS
        request, copy its answer; ``("join", entry)`` — duplicate of a
        row another request has in flight, wait on the entry;
        ``("own", key)`` — this request computes the row and MUST later
        ``publish`` or ``abort`` the key."""
        actions = []
        seen = {}
        with self._lock:
            gen = self._gen
            tc = self._tcounts(tenant)
            for i in range(len(queries)):
                key = (tenant, gen, plan_token, queries[i].tobytes())
                j = seen.get(key)
                if j is not None:
                    self.dedup_rows += 1
                    tc["dedup_rows"] += 1
                    actions.append(("local", j))
                    continue
                seen[key] = i
                row = self._lru.get(key)
                if row is not None:
                    self._lru.move_to_end(key)
                    self.hits += 1
                    tc["hits"] += 1
                    actions.append(("hit", row))
                    continue
                entry = self._inflight.get(key)
                if entry is not None:
                    entry.joiners += 1
                    self.dedup_rows += 1
                    tc["dedup_rows"] += 1
                    actions.append(("join", entry))
                    continue
                self.misses += 1
                self._inflight[key] = _InFlightRow()
                actions.append(("own", key))
        return actions

    # --------------------------------------------------------------- seeds

    def seed_for(self, qrows: np.ndarray, tenant) -> np.ndarray | None:
        """Certified per-row init radii for an EXACT-tier sub-batch, or
        None when the tenant's seed pool is empty. Rows with no useful
        bound come back +inf (the engine treats them as unseeded)."""
        if len(qrows) == 0:
            return None
        with self._lock:
            pool = self._seed_pools.get(tenant)
        snap = pool.snapshot() if pool is not None else None
        if snap is None:
            return None
        seeds = certified_seeds(qrows, *snap)
        finite = int(np.sum(np.isfinite(seeds)))
        if finite == 0:
            return None
        with self._lock:
            self.seeds += finite
            self._tcounts(tenant)["seeds"] += finite
        return seeds

    # ------------------------------------------------------------- publish

    def publish(self, keys: list, outs: tuple, queries: np.ndarray,
                plan_token, tenant) -> None:
        """Deliver a completed sub-batch: wake joiners, insert rows into
        the LRU, and feed full exact rows to the tenant's seed pool.

        ``keys`` are the ``("own", key)`` keys in sub-batch row order;
        ``outs`` is the engine result tuple — ``(dists, ids)`` or
        ``(dists, ids, exact)`` (routed degraded serving). A row with
        ``exact == False`` wakes its joiners (they asked for THESE bytes)
        but is never inserted: a degraded partial answer must not outlive
        the outage that produced it."""
        rows = []
        for j, key in enumerate(keys):
            # copy per-cell: an LRU row must not pin the batch arrays
            rows.append((key, tuple(np.copy(a[j]) for a in outs)))
        exact_plan = plan_token is None
        with self._lock:
            tc = self._tcounts(tenant)
            pool = None
            if exact_plan:
                pool = self._seed_pools.get(tenant)
                if pool is None and self.seed_rows > 0:
                    pool = SeedPool(self.seed_rows)
                    self._seed_pools[tenant] = pool
            for j, (key, row) in enumerate(rows):
                entry = self._inflight.pop(key, None)
                if entry is not None:
                    entry.result = row
                    entry.event.set()
                if len(row) > 2 and not bool(row[2]):
                    continue
                self._lru[key] = row
                self._lru.move_to_end(key)
                self.inserts += 1
                while len(self._lru) > self.capacity_rows:
                    self._lru.popitem(last=False)
                    self.evictions += 1
                    tc["evictions"] += 1
                if (pool is not None and np.isfinite(row[0])
                        and np.all(np.asarray(row[1]) >= 0)):
                    pool.add(queries[j], float(row[0]))

    def abort(self, keys: list, error: Exception | None = None) -> None:
        """Release owned keys after a failed sub-batch: joiners wake with
        the error and retry as their own owners (serve/batcher.py)."""
        err = error if error is not None else RuntimeError(
            "in-flight owner failed")
        with self._lock:
            for key in keys:
                entry = self._inflight.pop(key, None)
                if entry is not None:
                    self.inflight_aborts += 1
                    entry.error = err
                    entry.event.set()

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity_rows": self.capacity_rows,
                "seed_rows": self.seed_rows,
                "fingerprint": self.fingerprint,
                "generation": self._gen,
                "size_rows": len(self._lru),
                "inflight_rows": len(self._inflight),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (round(self.hits / (self.hits + self.misses), 4)
                             if (self.hits + self.misses) else None),
                "seeds": self.seeds,
                "dedup_rows": self.dedup_rows,
                "evictions": self.evictions,
                "inserts": self.inserts,
                "inflight_aborts": self.inflight_aborts,
                "tenants": {t: dict(c)
                            for t, c in self._tenant_counts.items()
                            if t is not None},
            }
