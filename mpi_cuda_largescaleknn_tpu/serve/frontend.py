"""Pod-mesh serving: per-host slice servers + the fan-out front end.

One more level of the replicate-traverse-merge shape (ROADMAP "multi-host
serving"): H serving processes — one per pod host, joined into a single
global device mesh by ``jax.distributed`` exactly like the batch CLIs
(cli/multihost.py) — each run ONE ``ResidentKnnEngine`` over that global
mesh with ``merge="device"``. The engine's AOT query program is unchanged
from single-host serving: the PR-4 Morton admission + multi-bucket
traversal rides inside it, and the PR-3 reduction
(``parallel/ring.py device_merge_final`` / ops/candidates.py
``tree_merge_candidates``) now simply runs on the GLOBAL pod-mesh axis, so
the pod-final [Q, k] answer materializes sharded 1/R per device with NO
host-side cross-host gather at all (PANDA's lesson: fold the reduction into
the communication schedule, never gather partials to one node; EQuARX's:
small-payload cross-device reductions belong inside the XLA program). Each
host fetches only its addressable row slices (``engine.complete_slices``),
so the POD's total fetched result bytes equal ONE final answer — a
host-count factor below every-host-fetches-everything, on top of PR 3's
R x within a host.

Because the engine program is a collective, every host must dispatch
IDENTICAL batches in the SAME order. That is the front end's contract:

- ``PodFanout`` replicates each admitted batch (same bytes) to every
  host's ``POST /shard_knn?seq=N``; the per-host ``HostSliceServer``
  dispatches strictly in ``seq`` order (a condition variable reorders
  late-arriving sockets), so the pod never interleaves.
- The fan-out exposes the engine's ``dispatch``/``complete`` split, so the
  front end's ``DynamicBatcher`` pipelines pod batches exactly like the
  single-host server pipelines device batches (``pipeline_depth``).
- ``FrontendServer`` speaks the same public contract as the single-host
  server — POST /knn (JSON or binary), /healthz, /stats, /metrics — plus
  per-host health and straggler accounting (per-batch spread between the
  first and last host slice to land).

Failure semantics (docs/SERVING.md "Failure handling & degraded mode"):
each host is supervised through a ``healthy -> suspect -> drained ->
rejoining`` lifecycle (serve/health.py) fed by both dispatch outcomes and
the background ``HealthMonitor``'s /healthz probes. Routed mode: dispatch
retries transient per-host failures (connect errors, timeouts, 5xx) with
capped-exponential deterministic backoff; a host that keeps failing is
DRAINED and the fan-out routes around it. The ``on_host_loss`` policy then
decides what happens to the queries whose certified routing set touches
the drained slab: ``fail`` answers them 503 + Retry-After (unaffected
queries keep serving bit-identical), ``degrade`` serves the fold of the
surviving hosts' partials — well-defined because the candidate fold is
commutative — explicitly flagged ``exact: false``. A drained host rejoins
only after the monitor revalidates its config/bounds fingerprint against
the pod table captured at startup. Replicate mode (``--routing off``) is
still one SPMD machine — a lost host slice is not degradable — but gets
drain-then-fail semantics: the pod is marked broken, requests answer 503
(not an opaque 500), and when every host probes healthy again with a
matching fingerprint and a consistent ``next_seq`` the monitor resets the
sequence stream (the clean restart path). All of it is exercised
deterministically via serve/faults.py injectors (tests/test_failover.py,
``serve_smoke --chaos-bench``).

Shard-local routing (``--routing bounds``): the replicate-everything
fan-out above makes adding hosts add WORK, not capacity — every host
traverses every batch. The routed mode is the paper's bounds-driven
demand-matching variant (PAPER.md §0: trees only travel to ranks whose
bounds can still improve a query; PANDA's distributed bounds pruning,
PAPERS.md) applied at pod scale: hosts run as INDEPENDENT engines (no
global mesh, no collectives, no seq ordering), each owning one row-slab of
the index with GLOBAL neighbor ids (``id_offset``), and serving full
candidate rows from ``POST /route_knn`` (``engine.emit='candidates'``).
The front end assembles a ``PodBoundsTable`` from every host's per-shard
AABBs at startup and, per batch: (wave 1) sends each query only to its
nearest-bounds host; then folds the returned partials with the canonical
(dist2, id) merge — commutative, so wave arrival order can never change
bits — and (escalation waves) re-dispatches exactly the (query, host)
pairs whose box lower bound can still beat that query's current k-th
distance, until every skipped host is CERTIFIED unable to contribute
(``lb * (1 - slack) > kth_dist2``; the slack covers the engines' f32
rounding so certification can never skip a true neighbor, ties included).
Clustered traffic certifies most queries after one host — pod throughput
then scales with hosts instead of trailing one host
(``serve_smoke.py --routing-bench``); results stay bit-identical to the
replicate-everything pod because slab sharding keeps ids ascending by
host, making the pod's shard-major tie discipline THE canonical order.

Replication (docs/SERVING.md "Replication & slab handoff"): routed hosts
claiming the same row range are REPLICAS of one slab — byte-
interchangeable by the replica fingerprint gate — and every routing
decision above is per SLAB, with one healthy member picked per sub-batch
by deterministic health-weighted spreading (serve/replica.py
``ReplicaSet``). A single drained host is then simply routed around at
full exactness; ``exact: false`` fires only when ALL replicas of an
improving slab are down. The monitor's ``ReplicaManager`` closes the
loop with slab HANDOFF: a warm ``--standby`` host adopts an under-
replicated slab (``POST /adopt_slab`` — re-materialized from the source
file or pulled from a surviving replica) and is bound into the replica
set only after its fingerprint proves config+bounds+AOT parity against
the pod table.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.request
import zlib
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from http.server import ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.obs.timers import LatencyHistogram, PhaseTimers
from mpi_cuda_largescaleknn_tpu.serve.admission import (
    AdmissionController,
    DeadlineExceeded,
    OverloadError,
)
from mpi_cuda_largescaleknn_tpu.serve.batcher import DynamicBatcher
from mpi_cuda_largescaleknn_tpu.serve.faults import FaultInjector
from mpi_cuda_largescaleknn_tpu.serve.health import (
    Backoff,
    HealthMonitor,
    HostHealth,
    host_fingerprint,
)
from mpi_cuda_largescaleknn_tpu.serve.qcache import QueryCache
from mpi_cuda_largescaleknn_tpu.serve.recall import RecallPolicy
from mpi_cuda_largescaleknn_tpu.serve.server import (
    JsonHttpHandler,
    ServingMetrics,
    parse_knn_body,
    qcache_prometheus_lines,
    recall_response_fields,
    slab_pool_prometheus_lines,
)
from mpi_cuda_largescaleknn_tpu.serve.wire import (
    WireError,
    WireNegotiator,
    WireStats,
    decode_candidates_q16,
    encode_candidates_q16,
    encode_slab_chunk,
    frame_chunk,
    wire_caps,
)
from mpi_cuda_largescaleknn_tpu.utils.math import aabb_lower_bound_dist2

# -------------------------------------------------------------- host side


class HostSliceServer(ThreadingHTTPServer):
    """Per-host serving process: one engine slice of the pod.

    Serves the front end only (no public /knn). Two modes:

    - ``routing="off"`` (pod mode): ``POST /shard_knn?seq=N`` with a raw
      little-endian f32 body dispatches the batch on the GLOBAL mesh — in
      strict ``seq`` order, because the underlying program is a collective
      every host must enter identically — and answers with this host's row
      slices of the pod-final result.
    - ``routing="bounds"`` (routed mode): the engine is an INDEPENDENT
      slab server (no global mesh, ``emit='candidates'``);
      ``POST /route_knn`` dispatches any sub-batch in arrival order (no
      collectives, so no seq discipline) and answers with the full
      candidate rows (d2[m,k] + ids[m,k]) the front end folds across
      hosts.

    /healthz, /stats and /metrics mirror the single-host server's
    observability surface either way (plus the per-shard AABB table and
    routed-row counters in routed mode).
    """

    daemon_threads = True
    #: how long a handler thread waits for ITS turn in the seq order
    #: before giving up (a lost lower seq means the pod is wedged anyway);
    #: class attribute = the default for the constructor knob below
    seq_timeout_s = 120.0

    def __init__(self, addr, engine, *, routing: str = "off",
                 seq_timeout_s: float | None = None,
                 faults: FaultInjector | None = None,
                 standby_config: dict | None = None,
                 wire: str = "auto",
                 verbose: bool = False):
        if routing not in ("off", "bounds"):
            raise ValueError(f"routing must be 'off' or 'bounds', "
                             f"got {routing!r}")
        if wire not in ("auto", "f32"):
            raise ValueError(f"host wire mode must be 'auto' or 'f32', "
                             f"got {wire!r}")
        #: "f32" = advertise and serve only the uncompressed codecs (the
        #: old-binary emulation / codec kill switch; serve_main --wire)
        self.wire_mode = wire
        if seq_timeout_s is not None:
            if seq_timeout_s <= 0:
                raise ValueError(f"seq_timeout_s must be > 0, "
                                 f"got {seq_timeout_s}")
            self.seq_timeout_s = float(seq_timeout_s)
        #: deterministic fault injection (serve/faults.py): programmatic,
        #: or KNN_FAULTS at start, or POST /faults at runtime
        self.faults = faults if faults is not None else FaultInjector.from_env()
        #: warm-standby mode (slab handoff, serve/replica.py): the server
        #: starts with NO engine and materializes one on POST /adopt_slab
        #: from the engine-construction knobs recorded here (path, k,
        #: shards, bucket geometry — serve_main --standby fills it)
        self.standby_config = dict(standby_config) if standby_config else None
        if self.standby_config is not None:
            if routing != "bounds":
                raise ValueError("standby hosts serve the routed tier — "
                                 "launch with --routing bounds")
            if engine is not None:
                raise ValueError("a standby starts empty; its engine is "
                                 "materialized by POST /adopt_slab")
        elif routing == "bounds":
            if getattr(engine, "emit", "final") != "candidates":
                raise ValueError(
                    "routed host serving needs an engine built with "
                    "emit='candidates' — the front end's partial merge "
                    "folds full candidate rows, not kth distances")
            if getattr(engine, "process_count", 1) > 1:
                raise ValueError(
                    "routed hosts are independent processes — do not join "
                    "a global mesh (launch without --coordinator)")
            # the front end pipelines depth-2 sub-batches per host
            engine.set_launch_workers(2)
        self.engine = engine
        self.routing = routing
        self.ready = False
        self.verbose = verbose
        self._loop_entered = False
        self.metrics = ServingMetrics()
        #: per-(path, codec) wire byte accounting (serve/wire.py) behind
        #: /stats wire_traffic and the /metrics knn_wire_* families
        self.wire_stats = WireStats()
        self._seq_cond = threading.Condition()
        self.next_seq: guarded_by("_seq_cond") = 0
        self._adopt_lock = threading.Lock()
        # adoption lifecycle, written by the adopt handler + its
        # background thread and read by /healthz scrapes (the replica
        # manager polls it) — all access under _adopt_lock
        self.adopt_state: guarded_by("_adopt_lock") = (
            "standby" if self.standby_config is not None else None)
        self.adopt_error: guarded_by("_adopt_lock") = None
        self.adopt_slab: guarded_by("_adopt_lock") = None
        self.adopt_seconds: guarded_by("_adopt_lock") = None
        super().__init__(addr, _HostHandler)

    def serve_forever(self, poll_interval=0.5):
        self._loop_entered = True
        super().serve_forever(poll_interval)

    def close(self):
        if self._loop_entered:
            self.shutdown()
        self.server_close()

    def run_in_order(self, seq: int, queries: np.ndarray):
        """Dispatch ``queries`` as pod batch ``seq`` and fetch this host's
        slices. Dispatch is serialized in ascending ``seq`` (the pod-wide
        program order); completes overlap freely — that is the engine's
        dispatch/complete pipelining, per host."""
        with self._seq_cond:
            deadline = time.monotonic() + self.seq_timeout_s
            while seq != self.next_seq:
                if seq < self.next_seq:
                    raise ValueError(f"seq {seq} already dispatched "
                                     f"(next is {self.next_seq})")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"seq {seq} waited {self.seq_timeout_s:.0f}s for "
                        f"seq {self.next_seq} to arrive — pod stream broken")
                self._seq_cond.wait(remaining)
            try:
                handle = self.engine.dispatch(queries)
            finally:
                # advance even on a dispatch error: the same deterministic
                # failure raises on EVERY host (same bytes, same config),
                # so the pod stays aligned at seq+1
                self.next_seq += 1
                self._seq_cond.notify_all()
        return self.engine.complete_slices(handle)

    def next_seq_snapshot(self) -> int:
        """Locked read of the stream position for handler threads —
        ``next_seq`` is guarded_by ``_seq_cond`` and the monitor's
        replicate-mode seq-consensus reset reads it via /stats, so a
        torn/stale read could spuriously defer a pod reset."""
        with self._seq_cond:
            return self.next_seq

    def run_routed(self, queries: np.ndarray):
        """Routed mode: dispatch a sub-batch in arrival order (the engine's
        own lock + FIFO launch pool serialize device entry; nothing is
        collective, so concurrent handler threads are fine) and return the
        full candidate rows ``(d2[m, k], idx[m, k])``."""
        handle = self.engine.dispatch(queries)
        return self.engine.complete_candidates(handle)

    # ------------------------------------------------------------- handoff

    def adopt_snapshot(self) -> dict:
        """Locked view of the adoption lifecycle (None state = not a
        standby) — what /healthz reports and the replica manager polls."""
        with self._adopt_lock:
            return {"state": self.adopt_state, "slab": self.adopt_slab,
                    "error": self.adopt_error,
                    "seconds": self.adopt_seconds}

    def start_adoption(self, req: dict, host_id: int,
                       num_hosts: int) -> bool:
        """Begin adopting slab ``host_id`` of ``num_hosts`` on a
        background thread (engine builds take seconds — the HTTP handler
        answers 202 immediately and the manager polls /healthz). False
        when an adoption is already running or done (409 upstream);
        ``failed`` may retry."""
        with self._adopt_lock:
            if self.adopt_state not in ("standby", "failed"):
                return False
            self.adopt_state = "adopting"
            self.adopt_slab = int(host_id)
            self.adopt_error = None
        threading.Thread(target=self._run_adoption,
                         args=(dict(req), int(host_id), int(num_hosts)),
                         daemon=True, name="knn-adopt").start()
        return True

    def _run_adoption(self, req: dict, host_id: int, num_hosts: int):
        """Materialize + warm the adopted slab, then flip ready. The
        engine is assigned BEFORE ``ready`` so a handler that sees
        ready=True always sees the engine; any failure parks the server
        back in ``failed`` with the reason on /healthz (the manager's
        fingerprint gate then never sees a half-built host)."""
        from mpi_cuda_largescaleknn_tpu.serve.engine import (
            materialize_slab_engine,
        )
        from mpi_cuda_largescaleknn_tpu.serve.replica import pull_slab_rows

        t0 = time.perf_counter()
        try:
            cfg = dict(self.standby_config)
            points = id_offset = None
            if req.get("source_url"):
                points, id_offset = pull_slab_rows(req["source_url"])
            eng, id_offset, _n_total = materialize_slab_engine(
                cfg.get("path"), host_id, num_hosts,
                k=cfg["k"], shards=cfg.get("shards"),
                engine=cfg.get("engine", "auto"),
                merge=cfg.get("merge", "auto"),
                bucket_size=cfg.get("bucket_size", 0),
                max_radius=cfg.get("max_radius", float("inf")),
                max_batch=cfg.get("max_batch", 1024),
                min_batch=cfg.get("min_batch", 8),
                query_buckets=cfg.get("query_buckets", 0),
                score_dtype=cfg.get("score_dtype", "f32"),
                points=points, id_offset=id_offset, warmup=True)
            # the adopt request carries the pod table's slab identity:
            # a file/num_hosts mismatch must fail HERE, loudly, not leak
            # wrong rows to the (fingerprint-gated) bind downstream
            want_off = req.get("row_offset")
            if want_off is not None and int(want_off) != id_offset:
                raise ValueError(
                    f"adopted slab starts at row {id_offset}, the pod "
                    f"table expects {want_off} — input file or num_hosts "
                    "disagrees with the pod's split")
            want_n = req.get("n_points")
            if want_n is not None and int(want_n) != eng.n_points:
                raise ValueError(
                    f"adopted slab holds {eng.n_points} rows, the pod "
                    f"table expects {want_n}")
            eng.set_launch_workers(2)
            self.engine = eng
            self.ready = True
            with self._adopt_lock:
                self.adopt_state = "adopted"
                self.adopt_seconds = round(time.perf_counter() - t0, 3)
        except Exception as e:  # noqa: BLE001 - surfaced on /healthz
            with self._adopt_lock:
                self.adopt_state = "failed"
                self.adopt_error = f"{type(e).__name__}: {e}"


class _HostHandler(JsonHttpHandler):
    def do_GET(self):
        srv: HostSliceServer = self.server
        path = urlparse(self.path).path
        if path == "/faults":
            # the fault admin surface is always exempt from injection
            self._send_json(200, {"specs": srv.faults.config()})
            return
        if self._apply_fault(path):
            return
        if srv.engine is None:
            # warm standby (slab handoff): no slab adopted yet — /healthz
            # reports the adoption lifecycle so the replica manager can
            # poll it; everything else answers 503 until adoption lands
            snap = srv.adopt_snapshot()
            status = {"standby": "standby", "adopting": "adopting",
                      "failed": "adopt-failed"}.get(snap["state"],
                                                    "standby")
            if path == "/healthz":
                body = {"status": status, "role": "standby",
                        "routing": srv.routing, "adopt": snap}
                if snap["error"]:
                    body["adopt_error"] = snap["error"]
                self._send_json(503, body)
            elif path == "/stats":
                self._send_json(200, {"routing": srv.routing,
                                      "standby": True, "adopt": snap,
                                      "wire": wire_caps(srv.wire_mode),
                                      "server": srv.metrics.snapshot()})
            elif path == "/metrics":
                self._send(200, "# TYPE knn_ready gauge\nknn_ready 0\n"
                           .encode(), "text/plain; version=0.0.4")
            else:
                self._send_json(503, {"error": "standby host: no slab "
                                               "adopted yet"},
                                extra=[("Retry-After", "1")])
            return
        if path == "/healthz":
            body = {"status": "ok" if srv.ready else "warming",
                    "role": ("host-routed" if srv.routing == "bounds"
                             else "host-slice"),
                    "routing": srv.routing,
                    "process_index": srv.engine.process_index,
                    "next_seq": srv.next_seq_snapshot()}
            adopt = srv.adopt_snapshot()
            if adopt["state"] is not None:
                body["adopt"] = adopt
            self._send_json(200 if srv.ready else 503, body)
        elif path == "/stats":
            # wire caps live at the ROOT (not in the engine block), so
            # advertising a new codec can never shift the replica
            # fingerprint and wedge mixed old/new pod handoffs
            self._send_json(200, {"engine": srv.engine.stats(),
                                  "routing": srv.routing,
                                  "next_seq": srv.next_seq_snapshot(),
                                  "wire": wire_caps(srv.wire_mode),
                                  "wire_traffic": srv.wire_stats.snapshot(),
                                  "server": srv.metrics.snapshot()})
        elif path == "/slab_rows":
            # slab handoff's pull path: a standby adopting this host's
            # slab fetches the host-side rows instead of re-reading the
            # source file (serve/replica.py pull_slab_rows)
            pts = getattr(srv.engine, "host_points", None)
            if pts is None:
                self._send_json(404, {
                    "error": "no host-side slab rows on this server "
                             "(routed slab hosts only)"})
                return
            qs = parse_qs(urlparse(self.path).query)
            if "wire" in qs:
                self._send_slab_stream(srv, pts, qs)
                return
            # legacy puller (no ?wire=): the pre-codec single-shot body.
            # zero-copy: the slab is 1/H of the index and the pull lands
            # exactly while this host absorbs the dead replica's load —
            # a .tobytes() here would transiently double the slab's RAM
            body = memoryview(np.ascontiguousarray(pts, "<f4")).cast("B")
            srv.wire_stats.add("slab_rows", "f32", len(body), len(pts))
            self._send(200, body, "application/octet-stream",
                       extra=[("X-Knn-Rows", str(len(pts))),
                              ("X-Knn-Dim", str(srv.engine.dim)),
                              ("X-Knn-Row-Offset",
                               str(srv.engine.id_offset))])
        elif path == "/metrics":
            e = srv.engine.stats()
            lines = []
            for name, val in (
                    ("knn_fetch_bytes_total", e["fetch_bytes"]),
                    ("knn_result_rows_total", e["result_rows"]),
                    ("knn_tiles_executed_total", e["tiles_executed"]),
                    ("knn_tiles_skipped_total", e["tiles_skipped"])):
                lines += [f"# TYPE {name} counter", f"{name} {val}"]
            # server-side request counters (incl. the routed-row counter
            # knn_routed_rows_total in routed mode)
            for name, val in sorted(srv.metrics.snapshot().items()):
                lines += [f"# TYPE {name} counter", f"{name} {val}"]
            for name, val in (("knn_ready", int(srv.ready)),
                              ("knn_compile_count", e["compile_count"]),
                              ("knn_num_shards", e["num_shards"]),
                              ("knn_host_process_index", e["process_index"]),
                              ("knn_host_next_seq", srv.next_seq_snapshot()),
                              ("knn_host_row_offset", e["row_offset"]),
                              ("knn_host_routed",
                               int(srv.routing == "bounds"))):
                lines += [f"# TYPE {name} gauge", f"{name} {val}"]
            # a routed host may itself STREAM sub-slabs of its row range
            # (serve_main --routing bounds --num-slabs): surface its
            # tiered-pool counters with the single-host server's renderer
            lines += slab_pool_prometheus_lines(e)
            lines += srv.wire_stats.prometheus_lines()
            self._send(200, ("\n".join(lines) + "\n").encode(),
                       "text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no such path {path}"})

    #: rows per /slab_rows stream chunk: ~768 KiB of f32 at dim=3 — big
    #: enough to amortize framing, small enough that the transient copy
    #: is a rounding error next to the slab itself
    slab_chunk_rows = 65536

    def _send_slab_stream(self, srv, pts, qs):
        """New-style ``/slab_rows?wire=d16|f32``: chunk-streamed with the
        serve/wire.py app framing. Each chunk is encoded (d16 delta codec
        or raw f32) and written immediately — the peak transient is one
        chunk, never a second copy of the slab. The fingerprint header is
        the crc32 of the RAW f32 bytes; the puller verifies it after
        decode, so a torn or corrupt transfer can never materialize."""
        codec = ("d16" if qs.get("wire", ["f32"])[0] == "d16"
                 and self.server.wire_mode != "f32" else "f32")
        pts = np.ascontiguousarray(pts, "<f4")
        try:
            begin = int(qs.get("begin", ["0"])[0])
            end = int(qs.get("end", [str(len(pts))])[0])
            if not (0 <= begin <= end <= len(pts)):
                raise ValueError(f"row range [{begin}, {end}) outside "
                                 f"[0, {len(pts)})")
        except ValueError as e:
            self._send_json(400, {"error": f"bad slab range: {e}"})
            return
        sel = pts[begin:end]
        crc = zlib.crc32(memoryview(sel).cast("B"))
        self._start_chunked(
            200, "application/octet-stream",
            extra=[("X-Knn-Rows", str(len(sel))),
                   ("X-Knn-Dim", str(srv.engine.dim)),
                   ("X-Knn-Row-Offset", str(srv.engine.id_offset + begin)),
                   ("X-Knn-Wire", codec),
                   ("X-Knn-Fingerprint", f"{crc:08x}")])
        sent = 0
        step = self.slab_chunk_rows
        try:
            for i in range(0, len(sel), step):
                sub = sel[i:i + step]
                if codec == "d16":
                    payload = encode_slab_chunk(sub)
                else:
                    payload = b"\x00" + sub.tobytes()
                self._write_chunk(frame_chunk(len(sub), payload))
                sent += 8 + len(payload)
            self._end_chunked()
        except (BrokenPipeError, ConnectionResetError):
            # puller went away mid-stream (its torn-transfer detection
            # handles the partial body); nothing for us to salvage
            self.close_connection = True
        srv.wire_stats.add("slab_rows", codec, sent, len(sel))

    def do_POST(self):
        srv: HostSliceServer = self.server
        parsed = urlparse(self.path)
        if parsed.path == "/faults":
            # runtime fault-spec replacement (chaos bench / tests): body is
            # {"spec": "<grammar>"}; empty spec clears. Exempt from
            # injection, so a "dead" host can still be revived
            try:
                length = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(length).decode() or "{}")
                srv.faults.set_specs(obj.get("spec", ""))
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400, {"error": str(e)})
                return
            self._send_json(200, {"specs": srv.faults.config()})
            return
        if self._apply_fault(parsed.path):
            return
        if parsed.path == "/adopt_slab":
            # slab handoff (serve/replica.py): direct a warm standby to
            # materialize + warm one slab. 202 = adoption started; the
            # caller polls /healthz and fingerprint-gates before binding
            if srv.standby_config is None:
                self._send_json(409, {
                    "error": "not a standby host — adopt_slab only "
                             "applies to --standby processes"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(length).decode() or "{}")
                host_id = int(obj["host_id"])
                num_hosts = int(obj.get(
                    "num_hosts", srv.standby_config.get("num_hosts", 1)))
                if not (0 <= host_id < num_hosts):
                    raise ValueError(f"host_id {host_id} outside "
                                     f"[0, {num_hosts})")
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._send_json(400, {"error": f"bad adopt request: {e}"})
                return
            if not srv.start_adoption(obj, host_id, num_hosts):
                self._send_json(409, {
                    "error": "adoption already in progress or done",
                    "adopt": srv.adopt_snapshot()})
                return
            self._send_json(202, {"status": "adopting",
                                  "host_id": host_id,
                                  "num_hosts": num_hosts})
            return
        if srv.engine is None:
            self._send_json(503, {"error": "standby host: no slab "
                                           "adopted yet"},
                            extra=[("Retry-After", "1")])
            return
        want = "/route_knn" if srv.routing == "bounds" else "/shard_knn"
        if parsed.path != want:
            self._send_json(404, {
                "error": f"this host serves POST {want} only "
                         f"(routing={srv.routing})"})
            return
        srv.metrics.inc("knn_requests_total")
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            dim = getattr(srv.engine, "dim", 3)
            if len(raw) % (4 * dim):
                raise ValueError(f"need an n*{4 * dim}-byte f32 body")
            if srv.routing == "off":
                seq = int(parse_qs(parsed.query).get("seq", ["-1"])[0])
                if seq < 0:
                    raise ValueError("need ?seq=N (the pod program order)")
            q = np.frombuffer(raw, "<f4").reshape(-1, dim)
        except ValueError as e:
            srv.metrics.inc("knn_badrequest_total")
            self._send_json(400, {"error": str(e)})
            return
        try:
            if srv.routing == "bounds":
                wire_req = parse_qs(parsed.query).get("wire", ["f32"])[0]
                if wire_req == "x32":
                    # survivor re-fetch: the engine hook re-derives the
                    # exact rows (batch-composition independent, so they
                    # are byte-equal to the quantized wave's)
                    d2, idx = srv.engine.refetch_exact(q)
                else:
                    d2, idx = srv.run_routed(q)
            else:
                rows, dists, nbrs = srv.run_in_order(seq, q)
        except TimeoutError as e:
            # seq-order wait expired: the pod stream is stalled, not this
            # request's fault — 503 + Retry-After, so a well-behaved
            # client backs off instead of treating it as a server bug
            srv.metrics.inc("knn_seq_timeout_total")
            self._send_json(503, {"error": f"TimeoutError: {e}"},
                            extra=[("Retry-After", "1")])
            return
        except Exception as e:  # noqa: BLE001 - the front end retries/fails
            srv.metrics.inc("knn_error_total")
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if srv.routing == "bounds":
            srv.metrics.inc("knn_rows_total", len(q))
            srv.metrics.inc("knn_routed_rows_total", len(q))
            # negotiated wire codec (serve/wire.py): ?wire=q16 compresses
            # the candidate rows (upper-bound decode, exact re-merge on
            # the frontend); ?wire=x32 is the survivor re-fetch variant —
            # exact d2 only, ids implied by the engine's determinism. An
            # old frontend sends no ?wire= and gets the f32 body with no
            # X-Knn-Wire header, byte-identical to the pre-codec binary.
            codec, extra = "f32", []
            d2 = np.ascontiguousarray(d2, "<f4")
            idx = np.ascontiguousarray(idx, "<i4")
            if srv.wire_mode == "f32":
                # f32-only host (old-binary emulation): any ?wire= ask
                # degrades to the uncompressed body with no X-Knn-Wire
                # header — the frontend's negotiated fallback, never an
                # error (an x32 refetch still gets exact d2 this way)
                body = d2.tobytes() + idx.tobytes()
            elif wire_req == "x32":
                codec, body = "x32", d2.tobytes()
            elif wire_req == "q16":
                body = encode_candidates_q16(d2, idx)
                if body is not None:
                    codec = "q16"
                else:
                    body = d2.tobytes() + idx.tobytes()
            else:
                body = d2.tobytes() + idx.tobytes()
            if codec != "f32":
                extra = [("X-Knn-Wire", codec)]
            srv.wire_stats.add("candidates", codec, len(body), len(q))
            self._send(200, body, "application/octet-stream",
                       extra=[("X-Knn-Rows", str(len(q))),
                              ("X-Knn-K", str(srv.engine.k))] + extra)
            return
        srv.metrics.inc("knn_rows_total", len(rows))
        body = (np.ascontiguousarray(rows, "<i4").tobytes()
                + np.ascontiguousarray(dists, "<f4").tobytes()
                + np.ascontiguousarray(nbrs, "<i4").tobytes())
        self._send(200, body, "application/octet-stream",
                   extra=[("X-Knn-Rows", str(len(rows))),
                          ("X-Knn-K", str(srv.engine.k))])


# ---------------------------------------------------------- front-end side


class PodBrokenError(RuntimeError):
    """A host failed mid-stream: the pod's collective program order is
    unrecoverable without restarting the host processes together (the
    monitor's pod-reset path clears it once they do)."""


class HostCallError(RuntimeError):
    """One HTTP call to one host failed. ``transient`` distinguishes
    retry-worthy failures (connect errors, timeouts, 5xx, torn payloads)
    from config errors (4xx) that retrying can never fix."""

    def __init__(self, msg: str, transient: bool = True):
        super().__init__(msg)
        self.transient = transient


class _HostEndpoint:
    """Front-end bookkeeping for one host: address pieces + accounting +
    the supervised health lifecycle (serve/health.py)."""

    def __init__(self, url: str, health_config: dict | None = None):
        self.url = url
        p = urlparse(url if "//" in url else "//" + url)
        self.host = p.hostname or "127.0.0.1"
        self.port = p.port or 80
        self.prefix = p.path.rstrip("/")
        self.latency = LatencyHistogram()
        self.ok = 0
        self.errors = 0
        self.last_error: str | None = None
        self.health = HostHealth(**(health_config or {}))
        self.retries = 0
        self.probe_errors = 0
        self.scrape_errors = 0


class PodFanout:
    """Replicate each batch to every host; assemble the per-host slices.

    The ``dispatch``/``complete`` split mirrors the engine's, so the
    front end's ``DynamicBatcher`` pipelines pod batches: ``dispatch``
    assigns the next pod-wide sequence number and posts the batch to all
    hosts concurrently (returning a handle of in-flight HTTP futures);
    ``complete`` joins them, scatters each host's ``(rows, dists, nbrs)``
    slices into the full batch, and records straggler spread (last host
    minus first host wall-clock per batch). Row coverage is asserted —
    a missing row means the pod's mesh ownership disagrees with the
    front end's host list, never something to paper over.
    """

    def __init__(self, host_urls: list[str], *, k: int, max_batch: int,
                 timeout_s: float = 120.0, timers: PhaseTimers | None = None,
                 dim: int = 3, retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 request_timeout_s: float | None = None,
                 health_config: dict | None = None):
        if not host_urls:
            raise ValueError("need at least one host URL")
        #: retained so runtime-bound endpoints (slab handoff's
        #: bind_replica) get the same health lifecycle knobs
        self._health_cfg = health_config
        self.endpoints = [_HostEndpoint(u, health_config)
                          for u in host_urls]
        self.k = int(k)
        self.dim = int(dim)
        self.max_batch = int(max_batch)
        self.timeout_s = float(timeout_s)
        #: per-TRY budget for routed posts (None = the pod-wide timeout_s):
        #: one slow host burns at most this much of the batch's wall-clock
        #: per attempt instead of the whole fan-out timeout
        self.request_timeout_s = (float(request_timeout_s)
                                  if request_timeout_s else None)
        #: bounded retries on TRANSIENT per-host failures (routed mode; the
        #: replicate stream is a collective and cannot re-send a seq)
        self.retries = int(retries)
        self.retry_backoff = Backoff(base_s=retry_backoff_s, cap_s=2.0,
                                     jitter=0.1, seed=0)
        self._sleep = time.sleep  # injectable: retry tests never sleep
        self.timers = timers if timers is not None else PhaseTimers()
        self._lock = threading.Lock()
        # stream state + accounting shared between the batcher's dispatch/
        # completion workers, handler threads (/stats), and the health
        # monitor's reset path — all access under _lock (lskcheck-proven)
        self.broken: guarded_by("_lock") = None
        self._seq: guarded_by("_lock") = 0
        self.batches: guarded_by("_lock") = 0
        self.straggler_seconds: guarded_by("_lock") = 0.0
        self._tls = threading.local()
        # enough workers for `depth` batches x H hosts in flight
        self._pool = ThreadPoolExecutor(
            max_workers=4 * len(self.endpoints),
            thread_name_prefix="knn-fanout")

    # ------------------------------------------------------------- transport

    def _conn(self, ep: _HostEndpoint) -> http.client.HTTPConnection:
        conns = getattr(self._tls, "conns", None)
        if conns is None:
            conns = self._tls.conns = {}
        c = conns.get(ep.url)
        if c is None:
            c = http.client.HTTPConnection(
                ep.host, ep.port,
                timeout=self.request_timeout_s or self.timeout_s)
            conns[ep.url] = c
        return c

    def _drop_conn(self, ep: _HostEndpoint):
        c = getattr(self._tls, "conns", {}).pop(ep.url, None)
        if c is not None:
            try:
                c.close()
            # lsk: allow[except-swallow] teardown of an already-failed
            except Exception:  # noqa: BLE001 - connection: nothing to record
                pass

    def _post_shard(self, ep: _HostEndpoint, seq: int, body: bytes):
        """POST one batch to one host; parse its slice triple. Returns
        (rows, dists, nbrs, seconds)."""
        t0 = time.perf_counter()
        try:
            conn = self._conn(ep)
            conn.request("POST", f"{ep.prefix}/shard_knn?seq={seq}",
                         body=body,
                         headers={"Content-Type":
                                  "application/octet-stream"})
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise PodBrokenError(
                    f"host {ep.url} answered {resp.status} for seq {seq}: "
                    f"{payload[:300].decode(errors='replace')}")
            m = int(resp.getheader("X-Knn-Rows", "-1"))
            kk = int(resp.getheader("X-Knn-K", str(self.k)))
            if m < 0 or kk != self.k or len(payload) != 4 * m * (2 + kk):
                raise PodBrokenError(
                    f"host {ep.url} slice malformed: rows={m} k={kk} "
                    f"bytes={len(payload)}")
            rows = np.frombuffer(payload, "<i4", count=m)
            dists = np.frombuffer(payload, "<f4", count=m, offset=4 * m)
            nbrs = np.frombuffer(payload, "<i4", count=m * kk,
                                 offset=8 * m).reshape(m, kk)
        except PodBrokenError:
            self._drop_conn(ep)
            raise
        except Exception as e:
            self._drop_conn(ep)
            raise PodBrokenError(
                f"host {ep.url} unreachable for seq {seq}: "
                f"{type(e).__name__}: {e}") from e
        return rows, dists, nbrs, time.perf_counter() - t0

    # ---------------------------------------------------------- query_fn API

    def dispatch(self, queries: np.ndarray):
        """Fan one admitted batch out to every host (non-blocking)."""
        q = np.ascontiguousarray(np.asarray(queries, np.float32)
                                 .reshape(-1, self.dim))
        with self._lock:
            # broken-check and seq-assignment are ONE atomic step: a
            # reset_stream racing between them could otherwise hand this
            # batch a stale stream position
            if self.broken:
                raise PodBrokenError(self.broken)
            seq = self._seq
            self._seq += 1
        body = q.astype("<f4").tobytes()
        futs = [self._pool.submit(self._post_shard, ep, seq, body)
                for ep in self.endpoints]
        return {"seq": seq, "n": len(q), "futs": futs,
                "t0": time.perf_counter()}

    def complete(self, handle):
        """Join every host's slice and assemble the full batch."""
        n = handle["n"]
        out_d = np.full(n, np.nan, np.float32)
        out_n = np.full((n, self.k), -1, np.int32)
        filled = np.zeros(n, bool)
        dts = []
        err: PodBrokenError | None = None
        for ep, fut in zip(self.endpoints, handle["futs"]):
            try:
                rows, dists, nbrs, dt = fut.result()
            except PodBrokenError as e:
                with self._lock:
                    ep.errors += 1
                    ep.last_error = str(e)
                # drain-then-fail: the health state records WHICH host took
                # the pod down, and the monitor's pod-reset path undrains
                # it once the whole pod restarts consistently
                ep.health.force_drain(str(e))
                err = err or e
                continue
            with self._lock:
                ep.ok += 1
                ep.latency.record(dt)
            ep.health.note_success()
            dts.append(dt)
            out_d[rows] = dists
            out_n[rows] = nbrs
            filled[rows] = True
        if err is not None:
            # one SPMD machine: a lost host slice is not degradable
            with self._lock:
                self.broken = self.broken or str(err)
            raise err
        if not filled.all():
            missing = int((~filled).sum())
            raise PodBrokenError(
                f"assembled batch seq {handle['seq']} is missing {missing} "
                f"of {n} rows — host list does not cover the pod mesh")
        with self._lock:
            self.batches += 1
            if len(dts) > 1:
                spread = max(dts) - min(dts)
                self.straggler_seconds += spread
                self.timers.hist("fanout_straggler_seconds").record(spread)
        self.timers.hist("fanout_batch_seconds").record(
            time.perf_counter() - handle["t0"])
        return out_d, out_n

    def __call__(self, queries):
        return self.complete(self.dispatch(queries))

    # ------------------------------------------------------------------ admin

    def probe_health(self, timeout_s: float = 2.0) -> dict:
        """GET every host's /healthz; {url: {"ok": bool, ...}}. Failures
        are no longer swallowed silently: each lands in the endpoint's
        ``last_error`` + ``probe_errors`` counter, so the health monitor
        and a /stats reader see the same truth."""
        out = {}
        for ep in self.endpoints:
            try:
                with urllib.request.urlopen(ep.url.rstrip("/") + "/healthz",
                                            timeout=timeout_s) as r:
                    out[ep.url] = {"ok": r.status == 200,
                                   **json.loads(r.read().decode())}
            except Exception as e:  # noqa: BLE001 - down IS the answer
                msg = f"healthz probe failed: {type(e).__name__}: {e}"
                with self._lock:
                    ep.probe_errors += 1
                    ep.last_error = msg
                out[ep.url] = {"ok": False, "error": msg}
        return out

    def scrape_host_stats(self, timeout_s: float = 5.0) -> dict:
        out = {}
        for ep in self.endpoints:
            try:
                with urllib.request.urlopen(ep.url.rstrip("/") + "/stats",
                                            timeout=timeout_s) as r:
                    out[ep.url] = json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 - surfaced per host
                msg = f"stats scrape failed: {type(e).__name__}: {e}"
                with self._lock:
                    ep.scrape_errors += 1
                    ep.last_error = msg
                out[ep.url] = {"error": msg}
        return out

    def broken_reason(self) -> str | None:
        """Locked read of the broken marker — the accessor cross-object
        readers (handlers, the health monitor) use; the guarded_by
        convention's self-rooted proof does not reach them, so they must
        not touch ``.broken`` directly (docs/ANALYSIS.md)."""
        with self._lock:
            return self.broken

    def reset_stream(self, next_seq: int) -> None:
        """Clean-restart path (replicate mode): clear the broken marker and
        re-align the front end's sequence counter with the (restarted)
        hosts' consistent ``next_seq`` — only the health monitor calls
        this, after validating every host's fingerprint."""
        with self._lock:
            self.broken = None
            self._seq = int(next_seq)

    def drained_mask(self) -> np.ndarray:
        """bool[H]: which endpoints are currently drained/rejoining."""
        return np.array([ep.health.is_drained() for ep in self.endpoints],
                        bool)

    def health_snapshot(self) -> dict:
        return {ep.url: dict(ep.health.snapshot(), retries=ep.retries,
                             probe_errors=ep.probe_errors,
                             scrape_errors=ep.scrape_errors)
                for ep in self.endpoints}

    def close(self) -> None:
        """Stop the fan-out pool. Worker threads exit and their cached
        per-host connections are closed with them (each thread's dict is
        only reachable from its own ``threading.local`` slot)."""
        self._pool.shutdown(wait=False)

    def stats(self) -> dict:
        health = self.health_snapshot()
        with self._lock:
            return {
                "hosts": [ep.url for ep in self.endpoints],
                "batches": self.batches,
                "next_seq": self._seq,
                "broken": self.broken,
                "straggler_seconds_total": round(self.straggler_seconds, 6),
                "per_host": {
                    ep.url: {"ok": ep.ok, "errors": ep.errors,
                             "retries": ep.retries,
                             "probe_errors": ep.probe_errors,
                             "scrape_errors": ep.scrape_errors,
                             "state": health[ep.url]["state"],
                             "last_error": ep.last_error,
                             "latency": ep.latency.report()}
                    for ep in self.endpoints},
                "health": health,
            }


def routing_cert_slack(dim: int) -> float:
    """Relative certification slack: a host is only CERTIFIED skippable
    when ``lb * (1 - slack) > kth_dist2``. The box bound is computed in
    f64, but the engines score pairs in f32 with relative error bounded by
    ~(D+2) * 2^-24 (one rounding per multiply/add of the D-term sum), so a
    point exactly ON a box face could score BELOW the exact bound. The
    slack must therefore GROW with the dimension — a constant that covers
    D=3 silently under-covers D=256 — so it is 16 x the error-model bound
    with a 1e-5 floor: negligible pruning loss at any D, and the
    non-strict ``<=`` comparison keeps every exact-tie host, which is what
    preserves tie-id bitwise parity with replicate-everything."""
    return max(1e-5, 16.0 * (dim + 2) * 2.0 ** -24)


class PodBoundsTable:
    """The routing decision table: every host's per-shard AABBs + counts.

    Assembled once at front-end startup from the hosts' /stats
    (``pod_config_from_hosts``). ``lower_bounds(q)`` returns, per (query,
    host), the squared distance below which NO point of that host can lie
    — the min over the host's per-shard box bounds (tighter than one
    whole-slab box). Empty shards carry the ``lo/hi = None`` sentinel and
    contribute nothing; a host with ONLY empty shards is unreachable
    (bound +inf) and is never routed to nor escalated to.
    """

    def __init__(self, hosts: list[dict], dim: int):
        self.dim = int(dim)
        self.num_hosts = len(hosts)
        self.host_points = [int(h["n_points"]) for h in hosts]
        los, his, owner = [], [], []
        for hid, h in enumerate(hosts):
            for sb in h["shards"]:
                if sb.get("count", 0) > 0:
                    if sb.get("lo") is None or sb.get("hi") is None:
                        raise ValueError(
                            f"host {hid} shard bounds malformed: "
                            f"count {sb['count']} but no lo/hi box")
                    los.append(sb["lo"])
                    his.append(sb["hi"])
                    owner.append(hid)
        self._lo = np.asarray(los, np.float64).reshape(-1, self.dim)
        self._hi = np.asarray(his, np.float64).reshape(-1, self.dim)
        self._owner = np.asarray(owner, np.int64)

    def lower_bounds(self, queries: np.ndarray) -> np.ndarray:
        """f64[n, H] squared lower-bound distance per (query, host);
        +inf for hosts with no points."""
        q = np.asarray(queries, np.float64).reshape(-1, self.dim)
        out = np.full((len(q), self.num_hosts), np.inf)
        if len(self._lo) == 0 or len(q) == 0:
            return out
        lb = aabb_lower_bound_dist2(q, self._lo, self._hi)
        for h in range(self.num_hosts):
            sel = self._owner == h
            if sel.any():
                out[:, h] = lb[:, sel].min(axis=1)
        return out


class RoutedPodFanout(PodFanout):
    """Bounds-routed fan-out: each query visits only the hosts whose shard
    boxes can still improve it, instead of the whole pod.

    ``dispatch`` (wave 1) computes the bounds table's lower bounds and
    posts each query to its single nearest-bounds host (ties -> lowest
    host index). ``complete`` joins the wave, folds the returned candidate
    rows with the canonical (dist2, id) merge — commutative, so the fold
    cannot depend on arrival order — then repeats: any (query, host) pair
    with ``lb * (1 - slack) <= kth_dist2`` and not yet visited is
    re-dispatched in an escalation wave, until every skipped host is
    certified unable to contribute (monotone radius ⇒ the loop terminates;
    in practice one escalation wave at most). Queries with fewer than k
    candidates keep an infinite radius, so they escalate to every
    reachable host — exactness is never traded for routing.

    Results are bit-identical to the replicate-everything pod (ties
    included) when the hosts' engines run the canonical tie order — the
    default; ``pod_config_from_hosts`` warns otherwise.
    """

    def __init__(self, host_urls: list[str], *, k: int, max_batch: int,
                 bounds: PodBoundsTable, timeout_s: float = 120.0,
                 timers: PhaseTimers | None = None, dim: int = 3,
                 retries: int = 2, retry_backoff_s: float = 0.05,
                 request_timeout_s: float | None = None,
                 health_config: dict | None = None,
                 replica_groups: list[dict] | None = None,
                 spread_seed: int = 0, wire: str = "auto",
                 wire_host_caps: dict | None = None):
        from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaSet

        super().__init__(host_urls, k=k, max_batch=max_batch,
                         timeout_s=timeout_s, timers=timers, dim=dim,
                         retries=retries, retry_backoff_s=retry_backoff_s,
                         request_timeout_s=request_timeout_s,
                         health_config=health_config)
        #: per-host negotiated wire codec (serve/wire.py): ``wire`` is the
        #: frontend knob (auto|f32|q16); caps come from each host's /stats
        #: root as scraped at startup (pod_config_from_hosts) and on
        #: adoption (ReplicaManager) — a host with no caps negotiates f32,
        #: so mixed old/new pods interop without config
        self.negotiator = WireNegotiator(
            "f32" if wire == "f32" else ("q16" if wire == "q16" else "auto"))
        for url, caps in (wire_host_caps or {}).items():
            self.negotiator.set_caps(url, caps)
        self.wire_stats = WireStats()
        #: slab -> replica-endpoint-group table (serve/replica.py): every
        #: routing decision is per SLAB; a healthy member is picked per
        #: sub-batch. None = the trivial R=1 set (one slab per endpoint),
        #: which reproduces the pre-replica behavior exactly.
        self.replicas = ReplicaSet(self.endpoints, replica_groups,
                                   seed=spread_seed)
        if bounds.num_hosts != self.replicas.num_slabs:
            raise ValueError(f"bounds table covers {bounds.num_hosts} "
                             f"slabs, replica set has "
                             f"{self.replicas.num_slabs}")
        self.bounds = bounds
        self.routing_mode = "bounds"
        self.cert_slack = routing_cert_slack(self.dim)
        # routing accounting (under the inherited fan-out _lock)
        self.escalations: guarded_by("_lock") = 0
        self.escalation_waves: guarded_by("_lock") = 0
        self.degraded_rows: guarded_by("_lock") = 0
        self.host_loss_events: guarded_by("_lock") = 0
        self.hosts_per_query: guarded_by("_lock") = Counter()
        # quantized-exchange resolution accounting: how often the exact
        # re-merge was served verbatim (provably unchanged by re-fetch),
        # re-fetched, or degraded because every re-fetch replica failed
        self.wire_verbatim_rows: guarded_by("_lock") = 0
        self.wire_refetch_rows: guarded_by("_lock") = 0
        self.wire_refetch_posts: guarded_by("_lock") = 0
        self.wire_refetch_failed_rows: guarded_by("_lock") = 0
        for ep in self.endpoints:
            ep.routed_rows = 0

    def bind_replica(self, slab: int, url: str) -> _HostEndpoint:
        """Runtime re-bind of a slab's endpoint set: add a NEW endpoint
        (a handoff-validated adopted standby) as a replica of ``slab``.
        Only the replica manager calls this, AFTER the fingerprint gate —
        an unproven slab must never enter the routing tables. The
        endpoint list only ever grows (append is atomic under the GIL;
        dispatch threads iterate by index)."""
        ep = _HostEndpoint(url, self._health_cfg)
        ep.routed_rows = 0
        self.endpoints.append(ep)
        self.replicas.rebind(slab, len(self.endpoints) - 1)
        return ep

    # ------------------------------------------------------------- transport

    def _route_once(self, ep: _HostEndpoint, body: bytes, m: int,
                    codec: str = "f32"):
        """ONE POST attempt to one routed host; parse its candidate rows.
        Returns ``(d2, d2_lo, idx, seconds, codec)`` where ``codec`` is
        what the RESPONSE actually carried (the X-Knn-Wire header — a host
        that ignores or declines ``?wire=q16`` answers plain f32, so a
        mismatch is a clean fallback, never a decode error). For f32 the
        bounds coincide (``d2_lo is d2``); for q16 they bracket the true
        distance with the anchor (kth) slot exact; for x32 (the survivor
        re-fetch variant) ``idx`` is None — ids are implied by the
        engine's determinism. Raises ``HostCallError`` classified
        transient (5xx, timeouts, connect errors, torn payloads — worth a
        retry) or not (4xx config)."""
        k = self.k
        t0 = time.perf_counter()
        qs = f"?wire={codec}" if codec != "f32" else ""
        try:
            conn = self._conn(ep)
            conn.request("POST", f"{ep.prefix}/route_knn{qs}", body=body,
                         headers={"Content-Type":
                                  "application/octet-stream"})
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise HostCallError(
                    f"host {ep.url} answered {resp.status}: "
                    f"{payload[:300].decode(errors='replace')}",
                    transient=resp.status >= 500)
            got = int(resp.getheader("X-Knn-Rows", "-1"))
            kk = int(resp.getheader("X-Knn-K", str(k)))
            wire_got = resp.getheader("X-Knn-Wire") or "f32"
            if got != m or kk != k:
                raise HostCallError(
                    f"host {ep.url} partial malformed: rows={got} (want "
                    f"{m}) k={kk}")
            if wire_got == "q16":
                try:
                    d2, d2_lo, idx = decode_candidates_q16(payload, m, k)
                except WireError as e:
                    raise HostCallError(
                        f"host {ep.url} q16 partial undecodable: {e}") \
                        from e
            elif wire_got == "x32":
                if len(payload) != 4 * m * k:
                    raise HostCallError(
                        f"host {ep.url} x32 partial malformed: "
                        f"bytes={len(payload)}")
                d2 = np.frombuffer(payload, "<f4",
                                   count=m * k).reshape(m, k)
                d2_lo, idx = d2, None
            else:
                if len(payload) != 8 * m * k:
                    raise HostCallError(
                        f"host {ep.url} partial malformed: rows={got} "
                        f"(want {m}) k={kk} bytes={len(payload)}")
                d2 = np.frombuffer(payload, "<f4",
                                   count=m * k).reshape(m, k)
                idx = np.frombuffer(payload, "<i4", count=m * k,
                                    offset=4 * m * k).reshape(m, k)
                d2_lo = d2
        except HostCallError:
            self._drop_conn(ep)
            raise
        except Exception as e:
            self._drop_conn(ep)
            raise HostCallError(
                f"host {ep.url} unreachable: "
                f"{type(e).__name__}: {e}") from e
        self.wire_stats.add("candidates", wire_got, len(payload), m)
        return d2, d2_lo, idx, time.perf_counter() - t0, wire_got

    def _post_route(self, ep: _HostEndpoint, body: bytes, m: int,
                    codec: str = "f32"):
        """`_route_once` with bounded retries + deterministic backoff on
        TRANSIENT failures (the /route_knn contract is idempotent — a
        routed sub-batch is a pure read, so re-sending it is always safe,
        unlike the replicate stream's seq-consuming /shard_knn)."""
        attempt = 0
        while True:
            try:
                return self._route_once(ep, body, m, codec)
            except HostCallError as e:
                if not e.transient or attempt >= self.retries:
                    raise
                attempt += 1
                with self._lock:
                    ep.retries += 1
                self._sleep(self.retry_backoff.delay(attempt, key=ep.url))

    def _submit_wave(self, q: np.ndarray, rows_by_slab,
                     batch_failures: dict | None = None) -> list:
        """Post per-slab sub-batches concurrently, each to one healthy
        replica chosen by the spread policy (``ReplicaSet.pick`` — batch
        failures first, so a replica that just failed this batch is
        routed around immediately); returns ``[(slab, ep_index, rows,
        future)]`` for the sub-batches actually submitted. A slab whose
        every member is drained or over its per-batch budget submits
        nothing — the caller leaves those rows unvisited and the
        on-host-loss policy resolves them."""
        futs = []
        for s, rows in rows_by_slab:
            if len(rows) == 0:
                continue
            ep_i = self.replicas.pick(s, penalties=batch_failures,
                                      budget=self.retries)
            if ep_i is None:
                continue
            body = np.ascontiguousarray(q[rows], "<f4").tobytes()
            codec = self.negotiator.codec_for(self.endpoints[ep_i].url)
            futs.append((s, ep_i, rows,
                         self._pool.submit(self._post_route,
                                           self.endpoints[ep_i], body,
                                           len(rows), codec)))
        return futs

    # ---------------------------------------------------------- query_fn API

    def dispatch(self, queries: np.ndarray, plan=None, seed_radius=None):
        """Wave 1: each query to its nearest-bounds AVAILABLE slab (one
        picked replica of it), PLUS every available slab whose boxes
        contain it (non-blocking). A zero lower bound can never be
        certified away (0 <= kth_dist2 always), so an inside-the-box slab
        would be escalated to unconditionally — visiting it in wave 1
        spends the same rows one round trip earlier, which is most of the
        boundary traffic's latency. A slab is unavailable only when EVERY
        replica is drained — a single drained host is simply routed
        around; whether the answers a fully-down slab would have touched
        are 503d or served degraded is ``complete``'s caller's policy.

        ``plan`` (serve/recall.py RecallPlan, None = exact) is FRONTEND
        side only here: the /route_knn wire is unchanged (hosts always
        serve their exact slab partials) and the plan's ``route_slack``
        shaves ``complete``'s escalation margin — fewer boundary waves,
        bounded recall cost.

        ``seed_radius`` (serve/qcache.py certified radius seeds, exact
        tier only — dropped under a plan) is frontend-side too: the
        /route_knn wire is unchanged (hosts serve their full exact slab
        partials), but ``complete`` starts its escalation radius at the
        certified seed instead of +inf, so certification closes with
        fewer escalation waves. The seed sits strictly above the true
        kth distance, so every slab holding a true top-k or
        boundary-tied candidate is still visited — identical answer."""
        q = np.ascontiguousarray(np.asarray(queries, np.float32)
                                 .reshape(-1, self.dim))
        n = len(q)
        seeds = None
        if seed_radius is not None and plan is None:
            seeds = np.asarray(seed_radius, np.float32).reshape(-1)
            if len(seeds) != n:
                raise ValueError(
                    f"seed_radius has {len(seeds)} rows for {n} queries")
            if not np.any(np.isfinite(seeds)):
                seeds = None
        num_slabs = self.replicas.num_slabs
        lb = self.bounds.lower_bounds(q)
        visited = np.zeros((n, num_slabs), bool)
        futs = []
        if n:
            avail = self.replicas.slab_live_mask()
            lb_route = np.where(avail[None, :], lb, np.inf)
            first = np.argmin(lb_route, axis=1)
            reachable = np.isfinite(lb_route[np.arange(n), first])
            want = (lb <= 0.0) & avail[None, :]
            want[np.nonzero(reachable)[0], first[reachable]] = True
            waves = [(s, np.nonzero(want[:, s])[0])
                     for s in range(num_slabs)]
            futs = self._submit_wave(q, waves)
            # only rows actually submitted count as visited: a slab whose
            # last replica drained between the mask and the pick stays
            # unvisited and resolves per policy
            for s, _ep_i, rows, _f in futs:
                visited[rows, s] = True
        return {"q": q, "n": n, "lb": lb, "visited": visited,
                "futs": futs, "t0": time.perf_counter(), "plan": plan,
                "seeds": seeds}

    #: the front end resolves recall plans only against fan-outs that
    #: accept them; the replicate pod (base class) stays plan-blind and
    #: serves every target exactly
    supports_recall = True

    def __call__(self, queries, plan=None, seed_radius=None):
        return self.complete(self.dispatch(queries, plan=plan,
                                           seed_radius=seed_radius))

    def complete(self, handle):
        """Fold wave partials; escalate uncertified (query, slab) pairs.

        Returns ``(dists, idx, exact)``. A replica that fails all its
        retries feeds the health state machine (eventually draining it)
        and its sub-batch is put back on the uncertified list: the next
        wave's pick prefers a DIFFERENT live replica of the same slab (a
        single host loss costs one extra round trip, never exactness),
        falling back to wave-level retry of the same host only when it is
        the slab's sole member. After certification converges, any
        (query, all-replicas-down slab) pair whose bound could still
        improve the query marks that query ``exact=False`` — the fold of
        the surviving slabs' partials is still well-defined
        (commutative), just possibly missing that slab's candidates.
        Queries whose certified routing set never touched a fully-down
        slab stay bit-identical to a healthy pod."""
        n, k = handle["n"], self.k
        cur_d2 = np.full((n, k), np.inf, np.float32)
        cur_idx = np.full((n, k), -1, np.int32)
        seeds = handle.get("seeds")
        if seeds is not None:
            # certified seeds (serve/qcache.py) bound the escalation
            # radius from wave 1: r2 starts at seed² (> true kth²,
            # strictly), escalation visits strictly fewer slabs, and the
            # filler (seed², -1) slots are pushed out before the fold
            # closes — the final rows are bit-identical to unseeded
            cur_d2[:] = (seeds * seeds)[:, None]
        if n == 0:
            return (np.zeros(0, np.float32), cur_idx,
                    np.zeros(0, bool))
        q, visited = handle["q"], handle["visited"]
        num_slabs = self.replicas.num_slabs
        # recall plan (knob c): escalate only when a bound beats the kth
        # distance by the plan's slack margin — fewer boundary waves at a
        # bounded recall cost; 0.0 (exact) keeps certification exact
        plan = handle.get("plan")
        slack = float(plan.route_slack) if plan is not None else 0.0
        # the dim-scaled slack makes the certification conservative
        # against the engines' f32 rounding (routing_cert_slack)
        lb_safe = handle["lb"] * (1.0 - self.cert_slack)
        reachable = np.isfinite(lb_safe)
        futs = handle["futs"]
        dts = []
        wave = 1
        # per-BATCH failure budget per ENDPOINT: wave-level retries are
        # capped independently of the global drain threshold, so a host
        # that keeps answering /healthz (resetting its failure streak via
        # the monitor) while failing /route_knn can never loop this batch
        # forever — once over budget it is unusable for THIS batch; a
        # slab with no usable member resolves per the on-host-loss policy
        batch_failures: dict[int, int] = {}
        # every successful sub-batch is retained: quantized (q16) partials
        # fold as UPPER bounds — sound for the escalation radius — and the
        # retained rows + lower bounds drive the exact re-merge afterwards
        contribs: list[tuple] = []
        while True:
            for s, ep_i, rows, fut in futs:
                ep = self.endpoints[ep_i]
                try:
                    d2, d2_lo, idx, dt, codec = fut.result()
                except HostCallError as e:
                    with self._lock:
                        ep.errors += 1
                        ep.last_error = str(e)
                    ep.health.note_failure(str(e))
                    batch_failures[ep_i] = batch_failures.get(ep_i, 0) + 1
                    # un-visit the lost sub-batch: the certification loop
                    # re-dispatches it to another replica (or retries the
                    # sole member while it stays usable); once the whole
                    # slab is out, these pairs surface as uncertified ->
                    # degraded/failed per policy
                    visited[rows, s] = False
                    continue
                with self._lock:
                    ep.ok += 1
                    ep.latency.record(dt)
                    ep.routed_rows += len(rows)
                ep.health.note_success()
                dts.append(dt)
                fold_candidates(cur_d2, cur_idx, rows, d2, idx, k)
                contribs.append((s, ep_i, rows, d2, d2_lo, idx, codec))
            # quantized partials fold upper bounds, so this radius is >=
            # the exact fold's on the same visited set: escalation can
            # only widen — certification never skips a host a
            # full-precision fold would have visited
            r2 = cur_d2[:, k - 1].astype(np.float64)
            need = (~visited) & reachable & (
                lb_safe <= r2[:, None] * (1.0 - slack))
            avail = self.replicas.slab_live_mask(
                penalties=batch_failures, budget=self.retries)
            dispatchable = need & avail[None, :]
            if not dispatchable.any():
                break
            with self._lock:
                if wave == 1:
                    self.escalations += int(
                        dispatchable.any(axis=1).sum())
                self.escalation_waves += 1
            wave += 1
            waves = [(s, np.nonzero(dispatchable[:, s])[0])
                     for s in range(num_slabs)]
            futs = self._submit_wave(q, waves, batch_failures)
            if not futs:
                # no sub-batch could be submitted (every needed slab lost
                # its last usable replica between mask and pick): no
                # progress is possible — resolve the remainder per policy
                break
            for s, _ep_i, rows, _f in futs:
                visited[rows, s] = True
        # exact re-merge: with any quantized contribution in play, the
        # conservative fold's bits are NOT the served answer — resolve
        # each query to the f32-identical row (verbatim when provable,
        # x32 re-fetch + one-shot exact fold otherwise). A pure-f32 batch
        # skips this entirely: the fold above IS the pre-codec path.
        if any(c[6] == "q16" for c in contribs):
            r2_f32, cur_idx, refetch_failed = self._resolve_quantized(
                q, n, contribs, cur_d2, cur_idx)
            r2 = r2_f32.astype(np.float64)
        else:
            refetch_failed = None
            r2_f32 = cur_d2[:, k - 1]
        # certification closed over the AVAILABLE slabs; whatever remains
        # uncertified points at fully-down slabs — those queries are
        # inexact (judged under the plan's slack: the approximate tier
        # flags its rows inexact at the response layer regardless). The
        # exact radius is <= the conservative loop radius, so this final
        # check can only shrink the uncertified set — exact flags match
        # an f32-negotiated pod's bit for bit.
        uncertified = (~visited) & reachable & (
            lb_safe <= r2[:, None] * (1.0 - slack))
        exact = ~uncertified.any(axis=1)
        if refetch_failed is not None:
            # every replica of a quantized contributor refused the exact
            # re-fetch: those rows serve the conservative fold, flagged
            # inexact — the same honesty contract as a lost slab
            exact &= ~refetch_failed
        with self._lock:
            self.batches += 1
            if not exact.all():
                self.degraded_rows += int((~exact).sum())
                self.host_loss_events += 1
            self.hosts_per_query.update(
                visited.sum(axis=1).astype(int).tolist())
            if len(dts) > 1:
                spread = max(dts) - min(dts)
                self.straggler_seconds += spread
                self.timers.hist("fanout_straggler_seconds").record(spread)
        self.timers.hist("fanout_batch_seconds").record(
            time.perf_counter() - handle["t0"])
        return np.sqrt(r2_f32), cur_idx, exact

    # -------------------------------------------------- exact re-merge (q16)

    def _resolve_quantized(self, q, n, contribs, cur_d2, cur_idx):
        """Resolve the batch to the f32-identical served rows after a
        conservative (upper-bound) fold. Per query:

        - ONE contribution: its transmitted row verbatim — the ids ride
          the wire exactly and the kth slot (anchor / pad) is bit-exact,
          so the served pair needs no re-fetch.
        - several: serve the smallest-kth contribution verbatim when
          every OTHER contribution's smallest lower bound strictly
          exceeds that kth (``lo <= true d2``, so none of their
          candidates can enter the merged top-k — ties included, the
          inequality is strict); otherwise re-fetch exact distances for
          the quantized contributions (``?wire=x32`` — ids are implied by
          the engines' determinism) and run ONE exact fold over all of
          the query's rows, which equals the incremental f32 fold bit
          for bit (the merge is a total order over unique (d2, id)).

        Returns ``(kth_d2 f32[n], idx i32[n, k], refetch_failed bool[n])``
        — failed rows keep the conservative fold and are flagged by the
        caller."""
        k = self.k
        by_q: list[list] = [[] for _ in range(n)]
        for ci, c in enumerate(contribs):
            for j, qi in enumerate(c[2].tolist()):
                by_q[qi].append((ci, j))
        out_d2 = cur_d2[:, k - 1].copy()
        out_idx = cur_idx.copy()
        verbatim_rows = 0
        merge_q: list[int] = []
        refetch: dict[int, list[int]] = {}
        for qi in range(n):
            cl = by_q[qi]
            if not cl:
                continue  # unvisited everywhere — the host-loss path
            if len(cl) > 1:
                kths = [float(contribs[ci][3][j, k - 1]) for ci, j in cl]
                b = int(np.argmin(kths))
                if not all(float(contribs[ci][4][j, 0]) > kths[b]
                           for t, (ci, j) in enumerate(cl) if t != b):
                    merge_q.append(qi)
                    for ci, j in cl:
                        if contribs[ci][6] == "q16":
                            refetch.setdefault(ci, []).append(j)
                    continue
            else:
                b = 0
            ci, j = cl[b]
            out_d2[qi] = contribs[ci][3][j, k - 1]
            out_idx[qi] = contribs[ci][5][j]
            verbatim_rows += 1
        failed = np.zeros(n, bool)
        failed_ci: set[int] = set()
        if merge_q:
            jobs = {}
            for ci, js in refetch.items():
                s, ep_i, rows = contribs[ci][0], contribs[ci][1], \
                    contribs[ci][2]
                sub = np.asarray(js, np.int64)
                jobs[ci] = (sub, self._pool.submit(
                    self._refetch_exact, s, ep_i, q, rows[sub]))
            for ci, (sub, fut) in jobs.items():
                d2x = fut.result()
                if d2x is None:
                    failed_ci.add(ci)
                else:
                    # overwrite the decoded upper bounds with exact f32
                    # (q16 decode owns its arrays — always writeable)
                    contribs[ci][3][sub] = d2x
            init_d2 = np.full(k, np.inf, np.float32)
            init_idx = np.full(k, -1, np.int32)
            for qi in merge_q:
                cl = by_q[qi]
                if any(ci in failed_ci for ci, _j in cl):
                    failed[qi] = True  # conservative row already out_*
                    continue
                cat_d2 = np.concatenate(
                    [init_d2] + [contribs[ci][3][j] for ci, j in cl])
                cat_idx = np.concatenate(
                    [init_idx] + [contribs[ci][5][j] for ci, j in cl])
                order = np.lexsort((cat_idx, cat_d2))[:k]
                out_d2[qi] = cat_d2[order[k - 1]]
                out_idx[qi] = cat_idx[order]
        with self._lock:
            self.wire_verbatim_rows += verbatim_rows
            self.wire_refetch_rows += sum(
                len(sub) for sub, _f in jobs.values()) if merge_q else 0
            self.wire_refetch_posts += len(refetch) if merge_q else 0
            self.wire_refetch_failed_rows += int(failed.sum())
        return out_d2, out_idx, failed

    def _refetch_exact(self, s, ep_i, q, rows):
        """Exact-distance re-fetch for the fold survivors of one
        quantized sub-batch: ``?wire=x32`` re-poses the same query rows
        (a pure idempotent read) to the SAME endpoint; the response is
        d2 only — ids are implied because the engine is deterministic
        and batch-composition independent (the property every escalation
        wave already relies on). When that replica fails its retries,
        any other usable replica of the slab answers instead (members
        are byte-interchangeable by the fingerprint gate; an f32-only
        member simply answers full f32, which carries exact d2 too).
        Returns f32[len(rows), k] or None when the whole slab is out."""
        body = np.ascontiguousarray(q[rows], "<f4").tobytes()
        tried: dict[int, int] = {}
        while True:
            ep = self.endpoints[ep_i]
            try:
                d2, _lo, _idx, _dt, _codec = self._post_route(
                    ep, body, len(rows), "x32")
                return d2
            except HostCallError as e:
                with self._lock:
                    ep.errors += 1
                    ep.last_error = str(e)
                ep.health.note_failure(str(e))
                tried[ep_i] = self.retries + 1  # over budget: exclude
                nxt = self.replicas.pick(s, penalties=tried,
                                         budget=self.retries)
                if nxt is None:
                    return None
                ep_i = nxt

    # ------------------------------------------------------------------ admin

    def stats(self) -> dict:
        s = super().stats()
        replicas = self.replicas.stats()
        with self._lock:
            total_q = sum(self.hosts_per_query.values())
            total_h = sum(c * v for c, v in self.hosts_per_query.items())
            s["routing"] = {
                "mode": "bounds",
                "escalations": self.escalations,
                "escalation_waves": self.escalation_waves,
                "degraded_rows": self.degraded_rows,
                "host_loss_events": self.host_loss_events,
                "routed_rows": {ep.url: ep.routed_rows
                                for ep in self.endpoints},
                "hosts_per_query": {str(c): int(v) for c, v in
                                    sorted(self.hosts_per_query.items())},
                "hosts_per_query_mean": round(total_h / total_q, 4)
                if total_q else None,
                # replication surface: per-slab member/live table + the
                # spread counters (how picks distributed across replicas)
                "replicas": replicas,
            }
            s["wire"] = {
                **self.negotiator.snapshot(),
                "traffic": self.wire_stats.snapshot(),
                "verbatim_rows": self.wire_verbatim_rows,
                "refetch_rows": self.wire_refetch_rows,
                "refetch_posts": self.wire_refetch_posts,
                "refetch_failed_rows": self.wire_refetch_failed_rows,
            }
        return s


def fold_candidates(cur_d2, cur_idx, rows, d2, idx, k):
    """Fold one host's candidate rows into the running per-query top-k
    under the canonical (dist2, id) total order — ops/candidates.py
    ``merge_candidates(canonical=True)`` in numpy. Commutative and
    associative (ids are unique), so wave/host arrival order can never
    change the folded bits; init slots (idx -1) still win their ties at
    the radius cutoff, preserving the engines' strict-< adoption. Shared
    by the routed pod fan-out above and the tiered slab index's
    in-process fold (serve/slabpool.py) — one fold, one tie discipline."""
    cat_d2 = np.concatenate([cur_d2[rows], np.asarray(d2, np.float32)],
                            axis=1)
    cat_idx = np.concatenate([cur_idx[rows], np.asarray(idx, np.int32)],
                             axis=1)
    order = np.lexsort((cat_idx, cat_d2), axis=1)[:, :k]
    cur_d2[rows] = np.take_along_axis(cat_d2, order, axis=1)
    cur_idx[rows] = np.take_along_axis(cat_idx, order, axis=1)


#: pre-slabpool private name, kept for external callers/tests
_fold_candidates = fold_candidates


class FrontendServer(ThreadingHTTPServer):
    """Public pod front end: the single-host server's exact HTTP contract
    (POST /knn JSON + binary, /healthz, /stats, /metrics) backed by a
    ``PodFanout`` instead of a local engine, with the same admission
    backpressure and the same pipelined ``DynamicBatcher``."""

    daemon_threads = True

    def __init__(self, addr, fanout: PodFanout, *, max_delay_s=0.002,
                 max_queue_rows=4096, default_timeout_s=5.0,
                 pipeline_depth=2, min_batch=8, on_host_loss="fail",
                 verbose=False, recall_policy=None,
                 qcache_rows=4096, qcache_seed_rows=512):
        if on_host_loss not in ("fail", "degrade"):
            raise ValueError(f"on_host_loss must be 'fail' or 'degrade', "
                             f"got {on_host_loss!r}")
        self.fanout = fanout
        #: recall-SLO tier (serve/recall.py). Plans only engage on a
        #: routed fan-out (``supports_recall``); a replicate pod serves
        #: every target exactly — exact always meets any target. The
        #: built-in default table is k-conditioned on the pod's k.
        self.recall_policy = (
            RecallPolicy.for_k(getattr(fanout, "k", None))
            if recall_policy is None else recall_policy)
        #: what happens to queries whose certified routing set touches a
        #: drained slab: "fail" 503s them (exactness preserved), "degrade"
        #: serves the surviving hosts' fold flagged ``exact: false``
        self.on_host_loss = on_host_loss
        #: background drain/rejoin supervisor (serve/health.py); attached
        #: by build_frontend, stopped by close()
        self.monitor: HealthMonitor | None = None
        self.admission = AdmissionController(
            max_queue_rows=max_queue_rows,
            default_timeout_s=default_timeout_s)
        #: certified query cache (serve/qcache.py): exact hits and dedup
        #: on any pod; radius seeds only on a routed fan-out (a replicate
        #: pod folds every host anyway — a tightened radius saves nothing
        #: on its wire, so seeding stays off there)
        self.qcache = None
        if qcache_rows:
            seeding = bool(getattr(fanout, "supports_recall", False))
            self.qcache = QueryCache(
                capacity_rows=qcache_rows,
                seed_rows=(qcache_seed_rows if seeding else 0),
                fingerprint=f"pod:{type(fanout).__name__}"
                            f":hosts={len(fanout.endpoints)}:k={fanout.k}")
        self.batcher = DynamicBatcher(fanout, max_batch=fanout.max_batch,
                                      max_delay_s=max_delay_s,
                                      timers=fanout.timers,
                                      pipeline_depth=pipeline_depth,
                                      min_batch=min_batch,
                                      qcache=self.qcache)
        self.admission.pipeline_rows_fn = self.batcher.inflight_rows
        self.metrics = ServingMetrics()
        # pre-seed the failure-path counters so dashboards see zeros, not
        # missing series, before the first incident
        for name in ("knn_degraded_responses_total", "knn_unavailable_total"):
            self.metrics.inc(name, 0)
        self.ready = False
        self.verbose = verbose
        self._loop_entered = False
        super().__init__(addr, _FrontendHandler)

    def serve_forever(self, poll_interval=0.5):
        self._loop_entered = True
        super().serve_forever(poll_interval)

    def close(self):
        if self.monitor is not None:
            self.monitor.stop()
        self.batcher.shutdown()
        self.fanout.close()
        if self._loop_entered:
            self.shutdown()
        self.server_close()


class _FrontendHandler(JsonHttpHandler):
    # the POST /knn flow below deliberately mirrors server.py _Handler's
    # (same status mapping, same binary/JSON responses) — the two ARE the
    # same public contract; change them together
    def do_GET(self):
        srv: FrontendServer = self.server
        path = urlparse(self.path).path
        if path == "/healthz":
            # with a running monitor, answer from its supervised state (no
            # inline probe storm per scrape); otherwise probe live
            if srv.monitor is not None and srv.monitor.running:
                # suspect still counts as up: it is serving every request
                # (one blip of fail_threshold); only drained/rejoining
                # hosts are genuinely out of rotation
                hosts = {url: {"ok": h["state"] in ("healthy", "suspect"),
                               **h}
                         for url, h in srv.fanout.health_snapshot().items()}
            else:
                hosts = srv.fanout.probe_health()
            n_ok = sum(1 for h in hosts.values() if h.get("ok"))
            routed = getattr(srv.fanout, "routing_mode", "off") == "bounds"
            broken = srv.fanout.broken_reason()
            if broken or n_ok == 0 or not srv.ready:
                status, code = ("broken" if broken else "degraded"), 503
            elif n_ok == len(hosts):
                status, code = "ok", 200
            elif routed:
                # partial capacity: a routed pod keeps serving around the
                # drained slab (degraded or selectively 503d per policy)
                status, code = "degraded", 200
            else:
                status, code = "degraded", 503
            self._send_json(code, {
                "status": status,
                "role": "pod-frontend",
                "broken": broken,
                "on_host_loss": srv.on_host_loss,
                "hosts": hosts})
        elif path == "/stats":
            fan_stats = srv.fanout.stats()
            self._send_json(200, {
                "fanout": fan_stats,
                "pod": {
                    "on_host_loss": srv.on_host_loss,
                    "broken": fan_stats["broken"],
                    # same snapshot the fanout block embeds — taken once,
                    # so the two read paths can never diverge
                    "health": fan_stats["health"],
                    "monitor": (srv.monitor.stats()
                                if srv.monitor is not None else None),
                },
                "batcher": srv.batcher.stats(),
                "admission": srv.admission.stats(),
                "server": dict(srv.metrics.snapshot(),
                               request_latency=srv.metrics.latency.report()),
                "recall": dict(srv.metrics.recall_snapshot(),
                               policy=srv.recall_policy.stats()),
                "hosts": srv.fanout.scrape_host_stats(),
                **({"qcache": srv.qcache.stats()}
                   if srv.qcache is not None else {}),
            })
        elif path == "/metrics":
            self._send(200, self._prometheus(srv).encode(),
                       "text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no such path {path}"})

    @staticmethod
    def _prometheus(srv: FrontendServer) -> str:
        f, b, a = (srv.fanout.stats(), srv.batcher.stats(),
                   srv.admission.stats())
        lines = []
        for name, val in srv.metrics.snapshot().items():
            lines += [f"# TYPE {name} counter", f"{name} {val}"]
        for name, val in (
                ("knn_fanout_batches_total", f["batches"]),
                ("knn_fanout_straggler_seconds_total",
                 f["straggler_seconds_total"]),
                ("knn_dispatch_stall_seconds_total",
                 b["dispatch_stall_seconds"]),
                ("knn_dispatch_stalls_total", b["dispatch_stalls"])):
            lines += [f"# TYPE {name} counter", f"{name} {val}"]
        gauges = {
            "knn_ready": int(srv.ready),
            "knn_pod_broken": int(f["broken"] is not None),
            "knn_pod_hosts": len(f["hosts"]),
            "knn_queue_rows": b["queue_rows"],
            "knn_inflight_rows": a["inflight_rows"],
            "knn_admission_rejected_total": a["rejected"],
            "knn_batches_total": b["batches"],
            "knn_pipeline_depth": b["pipeline_depth"],
            "knn_pipeline_inflight_batches": b["inflight_batches"],
        }
        for name, val in gauges.items():
            lines += [f"# TYPE {name} gauge", f"{name} {val}"]
        # certified query cache (serve/qcache.py), absent when off
        lines += qcache_prometheus_lines(srv.qcache)
        # per-host health + latency percentiles (straggler hunting): one
        # gauge line per host, labelled by endpoint
        lines += ["# TYPE knn_host_up gauge", "# TYPE knn_host_p99_seconds "
                  "gauge", "# TYPE knn_host_errors_total gauge"]
        for url, h in f["per_host"].items():
            up = int(h["errors"] == 0 or h["ok"] > 0)
            p99 = h["latency"].get("p99")
            lines += [f'knn_host_up{{host="{url}"}} {up}',
                      f'knn_host_errors_total{{host="{url}"}} {h["errors"]}']
            if p99 is not None:
                lines += [f'knn_host_p99_seconds{{host="{url}"}} {p99}']
        # supervised lifecycle surface: state enum (0 healthy / 1 suspect /
        # 2 drained / 3 rejoining), dispatch retries, cumulative drained
        # seconds — the drain/rejoin story as numbers
        lines += ["# TYPE knn_host_state gauge"] + [
            f'knn_host_state{{host="{url}"}} {h["state_code"]}'
            for url, h in f["health"].items()]
        lines += ["# TYPE knn_dispatch_retries_total counter"] + [
            f'knn_dispatch_retries_total{{host="{url}"}} {h["retries"]}'
            for url, h in f["health"].items()]
        lines += ["# TYPE knn_host_drained_seconds_total counter"] + [
            f'knn_host_drained_seconds_total{{host="{url}"}} '
            f'{h["drained_seconds_total"]}'
            for url, h in f["health"].items()]
        lines += ["# TYPE knn_host_probe_errors_total counter"] + [
            f'knn_host_probe_errors_total{{host="{url}"}} '
            f'{h["probe_errors"]}'
            for url, h in f["health"].items()]
        # shard-local routing observability: escalation + per-host routed
        # rows + the hosts-visited-per-query histogram (the routing win as
        # a number: mean ~1 = clustered traffic certifying after one host,
        # mean ~H = incoherent traffic degenerating to replicate-everything)
        routing = f.get("routing")
        if routing:
            lines += ["# TYPE knn_routing_escalations_total counter",
                      f"knn_routing_escalations_total "
                      f"{routing['escalations']}",
                      "# TYPE knn_routing_escalation_waves_total counter",
                      f"knn_routing_escalation_waves_total "
                      f"{routing['escalation_waves']}",
                      "# TYPE knn_degraded_rows_total counter",
                      f"knn_degraded_rows_total "
                      f"{routing['degraded_rows']}"]
            lines += ["# TYPE knn_routed_rows_total counter"] + [
                f'knn_routed_rows_total{{host="{u}"}} {v}'
                for u, v in routing["routed_rows"].items()]
            hpq = {int(c): v for c, v in routing["hosts_per_query"].items()}
            total = sum(hpq.values())
            hsum = sum(c * v for c, v in hpq.items())
            lines += ["# TYPE knn_hosts_per_query histogram"]
            cum = 0
            for c in sorted(hpq):
                cum += hpq[c]
                lines += [f'knn_hosts_per_query_bucket{{le="{c}"}} {cum}']
            lines += [f'knn_hosts_per_query_bucket{{le="+Inf"}} {total}',
                      f"knn_hosts_per_query_sum {hsum}",
                      f"knn_hosts_per_query_count {total}"]
            # replication surface: live replicas per slab (0 = the only
            # state that can cost exactness), pick-spread per host, and
            # the handoff counters from the monitor's replica manager
            replicas = routing.get("replicas")
            if replicas:
                lines += ["# TYPE knn_replica_live gauge"] + [
                    f'knn_replica_live{{slab="{p["slab"]}"}} {p["live"]}'
                    for p in replicas["per_slab"]]
                lines += ["# TYPE knn_replica_spread gauge"] + [
                    f'knn_replica_spread{{host="{u}"}} {c}'
                    for u, c in sorted(replicas["spread"].items())]
                lines += ["# TYPE knn_replica_rebinds_total counter",
                          f"knn_replica_rebinds_total "
                          f"{replicas['rebinds']}"]
            mon = srv.monitor
            handoff = (mon.stats().get("handoff")
                       if mon is not None else None)
            if handoff:
                lines += [
                    "# TYPE knn_handoffs_total counter",
                    f"knn_handoffs_total {handoff['handoffs']}",
                    "# TYPE knn_handoff_rejections_total counter",
                    f"knn_handoff_rejections_total "
                    f"{handoff['handoff_rejections']}",
                    "# TYPE knn_handoff_failures_total counter",
                    f"knn_handoff_failures_total "
                    f"{handoff['handoff_failures']}",
                    "# TYPE knn_handoff_seconds_total counter",
                    f"knn_handoff_seconds_total "
                    f"{handoff['handoff_seconds_total']}"]
        # quantized wire exchange: bytes/rows per (path, codec) — the
        # same families the hosts export, so a scrape sees both ends
        # (routed fan-out only; the replicate pod ships no partials)
        wire_stats = getattr(srv.fanout, "wire_stats", None)
        if wire_stats is not None:
            lines += wire_stats.prometheus_lines()
        # recall-SLO tier: exact/approx split + recall_estimated histogram
        lines += srv.metrics.recall_prometheus_lines()
        lines += srv.metrics.latency.prometheus_lines(
            "knn_request_latency_seconds")
        for src, prom in (("fanout_batch_seconds", "knn_fanout_batch_seconds"),
                          ("fanout_straggler_seconds",
                           "knn_fanout_straggler_seconds"),
                          ("pipeline_stall_seconds",
                           "knn_pipeline_stall_seconds")):
            hist = srv.fanout.timers.histograms.get(src)
            if hist is not None:
                lines += hist.prometheus_lines(prom)
        return "\n".join(lines) + "\n"

    def do_POST(self):
        srv: FrontendServer = self.server
        if urlparse(self.path).path != "/knn":
            self._send_json(404, {"error": "POST /knn only"})
            return
        srv.metrics.inc("knn_requests_total")
        t0 = time.perf_counter()
        try:
            # the pod front end serves one index — the parsed tenant (a
            # serve/tenancy.py concern) is ignored, like the single-index
            # server does
            q, want_nbrs, timeout_s, recall, _tenant, binary = (
                parse_knn_body(self.path, self.headers, self.rfile,
                               dim=getattr(srv.fanout, "dim", 3)))
        except (ValueError, json.JSONDecodeError) as e:
            srv.metrics.inc("knn_badrequest_total")
            self._send_json(400, {"error": str(e)})
            return
        # plans only engage on a routed fan-out; a replicate pod is
        # plan-blind and serves the target exactly (plan stays None)
        plan = (srv.recall_policy.plan_for(recall)
                if recall is not None
                and getattr(srv.fanout, "supports_recall", False) else None)
        timeout_s = timeout_s or srv.admission.default_timeout_s
        n = len(q)
        if n > srv.fanout.max_batch:
            srv.metrics.inc("knn_badrequest_total")
            self._send_json(413, {
                "error": f"batch of {n} exceeds max_batch "
                         f"{srv.fanout.max_batch}; split the request"})
            return
        if n == 0:
            if binary:
                self._send(200, b"", "application/octet-stream")
            else:
                self._send_json(200, {"dists": []})
            return
        try:
            with srv.admission.admitted_rows(n):
                res = srv.batcher.submit(q, timeout_s=timeout_s, plan=plan)
        except OverloadError as e:
            srv.metrics.inc("knn_overload_total")
            self._send_json(429, {"error": str(e)},
                            extra=[("Retry-After", f"{e.retry_after_s:g}")])
            return
        except DeadlineExceeded as e:
            srv.metrics.inc("knn_deadline_total")
            self._send_json(504, {"error": str(e)})
            return
        except PodBrokenError as e:
            # drain-then-fail: the pod stream is down until the hosts
            # restart together (the monitor's reset path) — an operational
            # state, not a server bug, so 503 + Retry-After, never 500
            srv.metrics.inc("knn_unavailable_total")
            self._send_json(503, {"error": str(e)},
                            extra=[("Retry-After", "1")])
            return
        except Exception as e:  # noqa: BLE001 - the service must not die
            srv.metrics.inc("knn_error_total")
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        # routed fan-outs return (dists, nbrs, exact); the replicate pod's
        # stream is all-or-nothing, so its results are always exact
        dists, nbrs = res[0], res[1]
        exact = res[2] if len(res) > 2 else None
        all_exact = bool(exact.all()) if exact is not None else True
        if not all_exact and srv.on_host_loss == "fail":
            # only the queries whose certified routing set touches the
            # drained slab are refused; a request is the granularity the
            # client can retry, so any inexact row 503s the request
            srv.metrics.inc("knn_unavailable_total")
            self._send_json(503, {
                "error": f"{int((~exact).sum())} of {n} queries touch a "
                         "drained host slab (on-host-loss=fail); retry "
                         "after the host rejoins",
                "exact": False},
                extra=[("Retry-After", "1")])
            return
        if not all_exact:
            srv.metrics.inc("knn_degraded_responses_total")
        srv.metrics.inc("knn_rows_total", n)
        srv.metrics.note_recall(plan)
        srv.metrics.latency.record(time.perf_counter() - t0)
        fields, rhdrs = recall_response_fields(plan, recall)
        if plan is None and not all_exact:
            # a target served on the exact plan but degraded by host loss
            # must not claim exactness — the degradation surface below
            # (exact/exact_per_query, X-Knn-Exact) is the truthful answer
            fields, rhdrs = {}, []
        if binary:
            self._send(200, np.asarray(dists, "<f4").tobytes(),
                       "application/octet-stream",
                       extra=(rhdrs if rhdrs else
                              [] if exact is None else
                              [("X-Knn-Exact", "1" if all_exact else "0")]))
        else:
            out = {"dists": np.asarray(dists, np.float64).tolist()}
            if want_nbrs:
                out["neighbors"] = np.asarray(nbrs).tolist()
            if exact is not None:
                out["exact"] = all_exact
                if not all_exact:
                    out["exact_per_query"] = [bool(x) for x in exact]
            out.update(fields)
            self._send_json(200, out)


# ------------------------------------------------------------------ startup


def wait_hosts_ready(host_urls: list[str], timeout_s: float = 600.0,
                     poll_s: float = 1.0) -> None:
    """Block until every host's /healthz answers 200 (engines warmed).
    A probe failure here is the EXPECTED state (still warming / not bound
    yet), but it is recorded, not swallowed: the last error per host is
    what the timeout message reports, so "not ready" is actionable."""
    deadline = time.monotonic() + timeout_s
    pending = list(host_urls)
    last_err = "no probe answered"
    while pending:
        url = pending[0]
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                        timeout=5.0) as r:
                if r.status == 200:
                    pending.pop(0)
                    continue
                last_err = f"healthz answered {r.status}"
        except Exception as e:  # noqa: BLE001 - warming IS an answer here
            last_err = f"{type(e).__name__}: {e}"
        if time.monotonic() > deadline:
            raise TimeoutError(f"host {url} not ready after "
                               f"{timeout_s:.0f}s (last error: {last_err})")
        time.sleep(poll_s)


def pod_config_from_hosts(host_urls: list[str],
                          routing: str = "auto") -> dict:
    """Scrape every host's /stats, detect the serving mode, and validate
    the pod is coherent.

    ``routing="auto"`` adopts whatever mode the hosts were launched in
    (they must all agree); "off"/"bounds" additionally assert it. Pod mode
    (off) validates: same k / max_batch / shape buckets / merge=device,
    process_count matching the host list, mesh positions covering the
    whole axis. Routed mode (bounds) validates: every host independent
    (process_count 1) with emit='candidates', same k / dim / score config /
    radius cap, and the hosts' row slabs tiling [0, N) with no gap or
    overlap — a hole would silently drop real neighbors. Returns the
    front-end construction config (routed configs carry ``host_urls``
    re-ordered ascending by row offset plus the bounds-table inputs)."""
    if routing not in ("auto", "off", "bounds"):
        raise ValueError(f"routing must be auto|off|bounds, got {routing!r}")
    raw = []
    for url in host_urls:
        with urllib.request.urlopen(url.rstrip("/") + "/stats",
                                    timeout=10.0) as r:
            raw.append(json.loads(r.read().decode()))
    modes = {s.get("routing", "off") for s in raw}
    if len(modes) != 1:
        raise ValueError(f"hosts disagree on serving mode: {sorted(modes)} "
                         "— launch every host with the same --routing")
    mode = modes.pop()
    if routing != "auto" and routing != mode:
        raise ValueError(f"front end asked for routing='{routing}' but the "
                         f"hosts serve routing='{mode}'")
    stats = [s["engine"] for s in raw]
    # per-host config/bounds fingerprints, captured at validation time: the
    # health monitor's rejoin gate compares a RETURNING host against these
    # (serve/health.py host_fingerprint) before undraining it
    fingerprints = {url: host_fingerprint(e, mode)
                    for url, e in zip(host_urls, stats)}
    if mode == "bounds":
        ref = stats[0]
        for url, e in zip(host_urls, stats):
            # routed hosts answer independently, so only the result
            # CONTRACT must agree — k, dim, radius semantics, score dtype
            # (distances must be the same f32 values on every host) — plus
            # the candidate-emission wire format
            for key in ("k", "dim", "max_radius", "score_dtype",
                        "max_batch"):
                if e.get(key) != ref.get(key):
                    raise ValueError(
                        f"routed pod mismatch: host {url} has "
                        f"{key}={e.get(key)!r}, host {host_urls[0]} has "
                        f"{ref.get(key)!r}")
            if e.get("emit") != "candidates":
                raise ValueError(f"host {url} serves emit={e.get('emit')!r};"
                                 " routed hosts must emit candidates")
            if e.get("process_count", 1) > 1:
                raise ValueError(f"host {url} joined a global mesh "
                                 "(process_count > 1) — routed hosts are "
                                 "independent processes")
        if not all(e.get("canonical_ties", False) for e in stats):
            print("warning: a routed host serves without canonical "
                  "(dist2, id) ties — distances stay exact, but "
                  "equal-distance neighbor-id choices may differ from the "
                  "replicate-everything pod")
        # replica grouping (serve/replica.py): hosts claiming the same row
        # range are replicas of one slab — replica-for-replica fingerprint
        # equality and slab tiling over the GROUPS are validated there
        from mpi_cuda_largescaleknn_tpu.serve.replica import (
            group_routed_hosts,
        )

        grouped = group_routed_hosts(host_urls, stats, fingerprints)
        # wire caps come from the /stats ROOT (an old binary has none →
        # f32), keyed by url so the negotiator can resolve per endpoint
        caps = {url: s.get("wire") for url, s in zip(host_urls, raw)}
        return {"routing": "bounds",
                "host_urls": grouped["host_urls"],
                "wire_host_caps": caps,
                "fingerprints": fingerprints,
                "replica_groups": grouped["slabs"],
                "slab_fingerprints": grouped["slab_fingerprints"],
                "k": ref["k"], "dim": ref.get("dim", 3),
                "max_batch": min(e["max_batch"] for e in stats),
                # routed sub-batches start the moment a host is idle (no
                # pod-wide program to queue behind), so the batcher's
                # stall-aware flush floor drops to 1 row
                "min_batch": 1,
                "n_points": grouped["n_points"],
                "bounds_hosts": grouped["bounds_hosts"]}
    ref = stats[0]
    covered: set[int] = set()
    for url, e in zip(host_urls, stats):
        # every key that feeds the AOT program's identity must agree, or
        # the hosts would enter the pod-wide collective with different
        # programs/operands (engine+buckets change the traversal;
        # query_buckets/sort_queries change the staged batch bytes and
        # the Morton permutation each host computes locally)
        for key in ("k", "max_batch", "num_shards", "shape_buckets",
                    "merge", "n_points", "engine", "bucket_size",
                    "query_buckets", "sort_queries", "score_dtype", "dim"):
            if e.get(key) != ref.get(key):
                raise ValueError(
                    f"pod mismatch: host {url} has {key}={e.get(key)!r}, "
                    f"host {host_urls[0]} has {ref.get(key)!r}")
        if e.get("merge") != "device":
            raise ValueError(f"host {url} serves merge={e.get('merge')!r}; "
                             "the pod front end needs merge='device'")
        if e.get("process_count") != len(host_urls):
            raise ValueError(
                f"host {url} reports process_count={e.get('process_count')} "
                f"but the front end was given {len(host_urls)} hosts")
        covered.update(e.get("my_positions", []))
    if covered != set(range(ref["num_shards"])):
        raise ValueError(
            f"host list covers mesh positions {sorted(covered)} of "
            f"{ref['num_shards']} — slices would be missing rows")
    return {"routing": "off",
            "host_urls": list(host_urls),
            "fingerprints": fingerprints,
            "k": ref["k"], "max_batch": ref["max_batch"],
            "min_batch": ref["shape_buckets"][0],
            "num_shards": ref["num_shards"], "n_points": ref["n_points"],
            "dim": ref.get("dim", 3)}


def build_frontend(host_urls: list[str], *, host: str = "127.0.0.1",
                   port: int = 8080, max_delay_s: float = 0.002,
                   pipeline_depth: int = 2, max_queue_rows: int = 4096,
                   default_timeout_s: float = 5.0, timeout_s: float = 120.0,
                   routing: str = "auto", on_host_loss: str = "fail",
                   retries: int = 2, retry_backoff_s: float = 0.05,
                   request_timeout_s: float | None = None,
                   probe_interval_s: float = 5.0, fail_threshold: int = 3,
                   health_config: dict | None = None,
                   start_monitor: bool = True,
                   standbys: list[str] | None = None,
                   handoff_floor: int = 1, wire: str = "auto",
                   qcache_rows: int = 4096, qcache_seed_rows: int = 512,
                   verbose: bool = False) -> FrontendServer:
    """Validate the pod and construct (but do not start) a FrontendServer;
    ``port=0`` picks a free port (``server.server_address[1]``).
    ``routing`` selects the fan-out: "off" = replicate-everything pod,
    "bounds" = shard-local routing, "auto" = whatever the hosts serve.
    ``on_host_loss`` picks the drained-slab policy (fail = 503 affected
    queries, degrade = serve them flagged ``exact: false``); the health
    monitor starts supervising immediately unless ``start_monitor=False``
    (tests drive ``server.monitor.check_once()`` by hand instead).
    Routed pods: hosts claiming the same row range are REPLICAS of one
    slab (exactness degrades only when all of a slab's replicas are
    down); ``standbys`` lists warm ``--standby`` hosts the monitor's
    replica manager directs to adopt a slab whose live-replica count
    falls below ``handoff_floor`` (docs/SERVING.md "Replication & slab
    handoff"). ``wire`` picks the candidate-exchange codec policy
    (routed pods): "auto" negotiates the compressed q16 exchange with
    every capable host (exact f32 re-merge keeps served bits identical),
    "f32" forces the uncompressed wire everywhere, "q16" is auto said
    explicitly (a host without the cap still falls back to f32 — never
    an error). See docs/SERVING.md "Wire formats & negotiation"."""
    from mpi_cuda_largescaleknn_tpu.serve.replica import ReplicaManager

    cfg = pod_config_from_hosts(host_urls, routing=routing)
    hc = dict(fail_threshold=fail_threshold,
              probe_interval_s=probe_interval_s)
    hc.update(health_config or {})
    if cfg["routing"] == "bounds":
        table = PodBoundsTable(cfg["bounds_hosts"], cfg["dim"])
        fanout: PodFanout = RoutedPodFanout(
            cfg["host_urls"], k=cfg["k"], max_batch=cfg["max_batch"],
            bounds=table, timeout_s=timeout_s, dim=cfg["dim"],
            retries=retries, retry_backoff_s=retry_backoff_s,
            request_timeout_s=request_timeout_s, health_config=hc,
            replica_groups=cfg["replica_groups"], wire=wire,
            wire_host_caps=cfg.get("wire_host_caps"))
    else:
        if standbys:
            raise ValueError("standby hosts (slab handoff) apply to "
                             "routed pods only — a replicate-mode pod is "
                             "one SPMD machine")
        fanout = PodFanout(cfg["host_urls"], k=cfg["k"],
                           max_batch=cfg["max_batch"],
                           timeout_s=timeout_s, dim=cfg["dim"],
                           retries=retries, retry_backoff_s=retry_backoff_s,
                           request_timeout_s=request_timeout_s,
                           health_config=hc)
    server = FrontendServer((host, port), fanout, max_delay_s=max_delay_s,
                            pipeline_depth=pipeline_depth,
                            max_queue_rows=max_queue_rows,
                            default_timeout_s=default_timeout_s,
                            min_batch=cfg["min_batch"],
                            on_host_loss=on_host_loss,
                            qcache_rows=qcache_rows,
                            qcache_seed_rows=qcache_seed_rows,
                            verbose=verbose)
    server.monitor = HealthMonitor(fanout,
                                   fingerprints=cfg["fingerprints"],
                                   mode=cfg["routing"])
    if cfg["routing"] == "bounds":
        # the handoff brain rides the monitor's check_once cadence; a
        # bound standby is registered in the monitor's fingerprint table
        # so its own later drain/rejoin cycles get the same gate
        server.monitor.replica_manager = ReplicaManager(
            fanout, slabs=cfg["replica_groups"],
            slab_fingerprints=cfg["slab_fingerprints"],
            standbys=standbys or [], handoff_floor=handoff_floor,
            fingerprint_registry=server.monitor.fingerprints)
    if start_monitor:
        server.monitor.start()
    return server


FRONTEND_FLAGS = """
  --hosts U1,U2,... per-host slice servers (required; one per pod host, in
                    any order — mesh coverage is validated at startup)
  --port P          HTTP port (default 8080; 0 = pick a free port)
  --host H          bind address (default 127.0.0.1)
  --max-delay-ms F  micro-batch flush deadline (default 2.0)
  --pipeline-depth N  pod batches in flight between dispatch and demux
                    (default 2)
  --max-queue-rows N  admission cap on queued+running rows (default 4096)
  --timeout-ms F    default per-request deadline (default 5000)
  --wait-ready-s F  how long to wait for host warmup (default 600)
  --routing M       auto | off | bounds (default auto = adopt the hosts'
                    mode): off replicates every batch pod-wide; bounds
                    routes each query only to hosts whose shard AABBs can
                    beat its current k-th distance, with certified
                    escalation (docs/SERVING.md "Shard-local routing")
  --on-host-loss P  fail | degrade (default fail): what happens to queries
                    whose certified routing set touches a DRAINED host —
                    fail answers them 503 + Retry-After (exactness
                    preserved), degrade serves the surviving hosts' fold
                    flagged exact:false (docs/SERVING.md "Failure
                    handling & degraded mode")
  --retries N       bounded retries per routed sub-batch on transient
                    failures: connect errors, timeouts, 5xx (default 2)
  --retry-backoff-ms F  base of the capped-exponential retry backoff
                    (default 50; deterministic jitter rides on top)
  --request-timeout-ms F  per-TRY budget for routed host posts (default:
                    the pod-wide --fanout-timeout); one slow host burns at
                    most this per attempt instead of poisoning the batch
  --probe-interval-s F  health monitor probe cadence for healthy hosts
                    (default 5; drained hosts re-probe on capped
                    exponential backoff + jitter)
  --fail-threshold N  consecutive failures that drain a host (default 3)
  --standbys U1,U2,...  warm standby hosts (serve_main --standby; routed
                    pods only): when a slab's live-replica count falls
                    below --handoff-floor the monitor directs one to
                    ADOPT the slab (POST /adopt_slab), fingerprint-gated
                    before it serves (docs/SERVING.md "Replication &
                    slab handoff")
  --handoff-floor N live replicas per slab below which a handoff starts
                    (default 1 = hand off only when a slab is fully
                    down; R with --handoff-floor R keeps full replication
                    through any single loss)
  --wire M          auto | f32 | q16 (default auto): candidate-exchange
                    codec policy for routed pods — auto negotiates the
                    compressed q16 wire per host (served bits stay
                    identical: exact f32 re-merge), f32 forces the
                    uncompressed exchange (docs/SERVING.md "Wire formats
                    & negotiation")
  --qcache-rows N   certified query cache capacity in cached rows
                    (default 4096; 0 disables the cache entirely —
                    serve/qcache.py, docs/SERVING.md "Query cache &
                    radius seeding"). Exact-hit reuse and in-flight
                    dedup are byte-identical by construction
  --qcache-seed-rows N  triangle-inequality seed pool rows per tenant
                    (default 512; 0 disables radius seeding while
                    keeping the hit/dedup tiers). Seeding applies on
                    routed pods only — a replicate pod folds every host
                    regardless
  --verbose         log each HTTP request to stderr
"""


def main(argv: list[str] | None = None) -> int:
    import sys

    args = sys.argv[1:] if argv is None else argv
    opt = {"hosts": "", "port": 8080, "host": "127.0.0.1",
           "max_delay_ms": 2.0, "pipeline_depth": 2,
           "max_queue_rows": 4096, "timeout_ms": 5000.0,
           "wait_ready_s": 600.0, "routing": "auto",
           "on_host_loss": "fail", "retries": 2,
           "retry_backoff_ms": 50.0, "request_timeout_ms": 0.0,
           "probe_interval_s": 5.0, "fail_threshold": 3,
           "standbys": "", "handoff_floor": 1, "wire": "auto",
           "qcache_rows": 4096, "qcache_seed_rows": 512,
           "verbose": False}
    i = 0
    try:
        while i < len(args):
            a = args[i]
            if a == "--hosts":
                i += 1; opt["hosts"] = args[i]
            elif a == "--port":
                i += 1; opt["port"] = int(args[i])
            elif a == "--host":
                i += 1; opt["host"] = args[i]
            elif a == "--max-delay-ms":
                i += 1; opt["max_delay_ms"] = float(args[i])
            elif a == "--pipeline-depth":
                i += 1; opt["pipeline_depth"] = int(args[i])
            elif a == "--max-queue-rows":
                i += 1; opt["max_queue_rows"] = int(args[i])
            elif a == "--timeout-ms":
                i += 1; opt["timeout_ms"] = float(args[i])
            elif a == "--wait-ready-s":
                i += 1; opt["wait_ready_s"] = float(args[i])
            elif a == "--routing":
                i += 1; opt["routing"] = args[i]
            elif a == "--on-host-loss":
                i += 1; opt["on_host_loss"] = args[i]
            elif a == "--retries":
                i += 1; opt["retries"] = int(args[i])
            elif a == "--retry-backoff-ms":
                i += 1; opt["retry_backoff_ms"] = float(args[i])
            elif a == "--request-timeout-ms":
                i += 1; opt["request_timeout_ms"] = float(args[i])
            elif a == "--probe-interval-s":
                i += 1; opt["probe_interval_s"] = float(args[i])
            elif a == "--fail-threshold":
                i += 1; opt["fail_threshold"] = int(args[i])
            elif a == "--standbys":
                i += 1; opt["standbys"] = args[i]
            elif a == "--handoff-floor":
                i += 1; opt["handoff_floor"] = int(args[i])
            elif a == "--wire":
                i += 1; opt["wire"] = args[i]
            elif a == "--qcache-rows":
                i += 1; opt["qcache_rows"] = int(args[i])
            elif a == "--qcache-seed-rows":
                i += 1; opt["qcache_seed_rows"] = int(args[i])
            elif a == "--verbose":
                opt["verbose"] = True
            else:
                raise ValueError(f"unknown cmdline arg '{a}'")
            i += 1
        hosts = [h for h in opt["hosts"].split(",") if h]
        if not hosts:
            raise ValueError("--hosts is required (comma-separated URLs)")
    except (IndexError, ValueError) as e:
        sys.stderr.write(f"Error: {e}\n\ntpuknn-frontend --hosts <urls> "
                         f"[options]\n{FRONTEND_FLAGS}")
        return 1

    print(f"waiting for {len(hosts)} host(s) to warm up...")
    wait_hosts_ready(hosts, timeout_s=opt["wait_ready_s"])
    server = build_frontend(
        hosts, host=opt["host"], port=opt["port"],
        max_delay_s=opt["max_delay_ms"] / 1e3,
        pipeline_depth=opt["pipeline_depth"],
        max_queue_rows=opt["max_queue_rows"],
        default_timeout_s=opt["timeout_ms"] / 1e3,
        routing=opt["routing"], on_host_loss=opt["on_host_loss"],
        retries=opt["retries"],
        retry_backoff_s=opt["retry_backoff_ms"] / 1e3,
        request_timeout_s=(opt["request_timeout_ms"] / 1e3
                           if opt["request_timeout_ms"] > 0 else None),
        probe_interval_s=opt["probe_interval_s"],
        fail_threshold=opt["fail_threshold"],
        standbys=[s for s in opt["standbys"].split(",") if s],
        handoff_floor=opt["handoff_floor"], wire=opt["wire"],
        qcache_rows=opt["qcache_rows"],
        qcache_seed_rows=opt["qcache_seed_rows"],
        verbose=opt["verbose"])
    server.ready = True
    h, p = server.server_address[:2]
    mode = getattr(server.fanout, "routing_mode", "off")
    print(f"pod front end on http://{h}:{p} fanning to {len(hosts)} host(s) "
          f"(routing={mode}, on-host-loss={opt['on_host_loss']})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
