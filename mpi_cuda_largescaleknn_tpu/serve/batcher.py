"""Dynamic micro-batching: coalesce a request stream into engine batches.

TPU-KNN's throughput argument (arXiv:2206.14286) cuts against serving one
query at a time: the engine's fixed-shape programs want the widest batch the
latency budget allows. This batcher sits between N concurrent callers and
the single-threaded engine: requests queue; the worker flushes when the
queued rows reach ``max_batch`` OR the oldest request has waited
``max_delay_s`` — the classic throughput/latency dial. A flush concatenates
whole requests (never splitting one across engine calls keeps demux
trivial), pads to the smallest covering shape bucket inside the engine, and
demuxes per-request slices back to each caller.

Deadlines: a request whose deadline passed while queued is completed with
``DeadlineExceeded`` instead of burning engine time on an answer nobody is
waiting for.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from mpi_cuda_largescaleknn_tpu.serve.admission import DeadlineExceeded


@dataclass
class _Request:
    queries: np.ndarray
    deadline: float | None
    enqueued: float
    done: threading.Event = field(default_factory=threading.Event)
    result: tuple | None = None
    error: Exception | None = None

    @property
    def rows(self) -> int:
        return len(self.queries)


class DynamicBatcher:
    """Single worker thread draining a request queue through ``query_fn``.

    ``query_fn(queries f32[n,3]) -> (dists f32[n], neighbors i32[n,k])`` —
    typically ``admission.GracefulQueryFn`` wrapping a ResidentKnnEngine.
    """

    def __init__(self, query_fn, *, max_batch: int,
                 max_delay_s: float = 0.002, timers=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._query_fn = query_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        self._timers = timers
        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._queued_rows = 0
        self._shutdown = False
        # counters (under _cond)
        self.batches = 0
        self.rows_served = 0
        self.rows_expired = 0
        self.flush_full = 0
        self.flush_deadline = 0
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="knn-batcher")
        self._worker.start()

    # ------------------------------------------------------------------ submit

    def submit(self, queries: np.ndarray, timeout_s: float | None = None):
        """Block until the batch containing ``queries`` executes; returns
        ``(dists, neighbors)`` or raises the request's error."""
        queries = np.asarray(queries, np.float32).reshape(-1, 3)
        now = time.monotonic()
        req = _Request(queries=queries, enqueued=now,
                       deadline=(now + timeout_s) if timeout_s else None)
        with self._cond:
            if self._shutdown:
                raise RuntimeError("batcher is shut down")
            self._queue.append(req)
            self._queued_rows += req.rows
            self._cond.notify_all()
        # grace beyond the deadline: the worker completes expired requests
        # with DeadlineExceeded itself; the extra wait covers an in-flight
        # engine call that started before the deadline passed
        wait = None if timeout_s is None else timeout_s + 30.0
        if not req.done.wait(wait):
            raise DeadlineExceeded("request stuck in batcher")
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------------ worker

    def _take_batch(self) -> list[_Request] | None:
        """Wait for a flushable batch; None on shutdown."""
        with self._cond:
            while True:
                if self._shutdown and not self._queue:
                    return None
                if self._queue:
                    oldest = self._queue[0]
                    flush_at = oldest.enqueued + self.max_delay_s
                    now = time.monotonic()
                    if (self._queued_rows >= self.max_batch
                            or now >= flush_at or self._shutdown):
                        break
                    self._cond.wait(flush_at - now)
                else:
                    self._cond.wait()
            # pop whole requests while they fit; a single over-wide request
            # (> max_batch rows) was rejected upstream by admission sizing,
            # but guard anyway by always taking at least one
            batch = [self._queue.popleft()]
            rows = batch[0].rows
            while self._queue and rows + self._queue[0].rows <= self.max_batch:
                r = self._queue.popleft()
                batch.append(r)
                rows += r.rows
            self._queued_rows -= rows
            self.batches += 1
            if rows >= self.max_batch:
                self.flush_full += 1
            else:
                self.flush_deadline += 1
            return batch

    def _run(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live, expired = [], []
            for r in batch:
                (expired if (r.deadline is not None and now > r.deadline)
                 else live).append(r)
            for r in expired:
                with self._cond:
                    self.rows_expired += r.rows
                r.error = DeadlineExceeded(
                    f"deadline passed after {now - r.enqueued:.3f}s in queue")
                r.done.set()
            if not live:
                continue
            try:
                t0 = time.perf_counter()
                merged = (live[0].queries if len(live) == 1 else
                          np.concatenate([r.queries for r in live]))
                dists, nbrs = self._query_fn(merged)
                if self._timers is not None:
                    self._timers.hist("batch_exec_seconds").record(
                        time.perf_counter() - t0)
                off = 0
                for r in live:
                    r.result = (dists[off:off + r.rows],
                                nbrs[off:off + r.rows])
                    off += r.rows
                    r.done.set()
                with self._cond:
                    self.rows_served += len(merged)
            except Exception as e:  # noqa: BLE001 - delivered per request
                for r in live:
                    r.error = e
                    r.done.set()

    # ------------------------------------------------------------------- admin

    def queue_depth_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def stats(self) -> dict:
        with self._cond:
            return {
                "batches": self.batches,
                "rows_served": self.rows_served,
                "rows_expired": self.rows_expired,
                "flush_full": self.flush_full,
                "flush_deadline": self.flush_deadline,
                "queue_rows": self._queued_rows,
                "mean_batch_rows": round(
                    self.rows_served / self.batches, 2) if self.batches else 0,
            }

    def shutdown(self, wait: bool = True):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        if wait:
            self._worker.join(timeout=10)
