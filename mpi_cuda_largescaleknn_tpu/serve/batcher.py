"""Dynamic micro-batching: coalesce a request stream into engine batches.

TPU-KNN's throughput argument (arXiv:2206.14286) cuts against serving one
query at a time: the engine's fixed-shape programs want the widest batch the
latency budget allows. This batcher sits between N concurrent callers and
the single-threaded engine: requests queue; the worker flushes when the
queued rows reach ``max_batch`` OR the oldest request has waited
``max_delay_s`` — the classic throughput/latency dial. A flush concatenates
whole requests (never splitting one across engine calls keeps demux
trivial), pads to the smallest covering shape bucket inside the engine, and
demuxes per-request slices back to each caller. The engine may Morton-sort
the flushed batch internally for query locality (serve/engine.py), but it
un-permutes at ``complete`` — so the offset demux here stays position-based
and order-oblivious, and coalescing MORE concurrent requests per flush
actively helps: the sort regroups rows from different callers into
spatially tight query buckets the traversal prunes harder.

Pipelining (``pipeline_depth > 1``): when ``query_fn`` exposes the engine's
``dispatch``/``complete`` split, flushes run on a DISPATCH worker that
launches batch t+1's device traversal while a COMPLETION worker blocks on
batch t's fetch, merges, and demuxes — device compute overlaps host
staging/merge instead of serializing behind it. A semaphore bounds the
batches in flight between dispatch and demux at ``pipeline_depth``; the
time the dispatch worker spends blocked on that bound is the pipeline's
stall metric (recorded in the shared obs/timers.py histogram geometry).
Completion order is FIFO in batch order, so per-request demux slices can
never cross batches. ``pipeline_depth=1`` (the default) keeps the original
single-worker serialized path bit-for-bit.

Deadlines: a request whose deadline passed while queued is completed with
``DeadlineExceeded`` instead of burning engine time on an answer nobody is
waiting for.

Routed fan-outs (serve/frontend.py ``RoutedPodFanout``) stress the
``complete`` side: a routed batch's completion performs NETWORK waves
(fold + escalation re-dispatch), so a pipeline slot can be held well past
the device time and the per-host sub-batches vary in width. Two
consequences live here: the routed front end passes ``min_batch=1``
(a sliver CAN start immediately — independent hosts have no pod-wide
program to queue behind, so the stall-aware flush floor must not hold it),
and ``complete`` wall-clock is accounted separately
(``batch_complete_seconds`` histogram, ``complete_seconds_total`` in
stats) so escalation cost is attributable instead of vanishing into
dispatch stalls.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.obs.timers import LatencyHistogram
from mpi_cuda_largescaleknn_tpu.serve.admission import DeadlineExceeded


@dataclass
class _Request:
    queries: np.ndarray
    deadline: float | None
    enqueued: float
    #: recall-SLO execution plan (serve/recall.py RecallPlan; None = exact).
    #: Requests only coalesce with plan-compatible neighbors — see
    #: ``_plan_key`` / ``_take_batch``.
    plan: object | None = None
    #: tenant namespace (serve/tenancy.py; None = single-index serving).
    #: Joins the coalescing key — one engine batch never mixes indexes.
    tenant: str | None = None
    #: certified per-row init radii (serve/qcache.py seed_for; None =
    #: unseeded). f32[rows]; +inf rows are unseeded. Exact-tier only.
    seeds: np.ndarray | None = None
    done: threading.Event = field(default_factory=threading.Event)
    result: tuple | None = None
    error: Exception | None = None

    @property
    def rows(self) -> int:
        return len(self.queries)


def _plan_key(req: _Request):
    """The coalescing key: requests whose plans execute identical bits ON
    THE SAME INDEX may share an engine batch. The plan part is None (exact,
    its own key) or the plan's ``batch_key()``, which deliberately EXCLUDES
    ``recall_target`` — two requests on the same plan at different targets
    coalesce. The tenant part keeps multi-index traffic in per-tenant
    sub-batches (None for single-index serving, so legacy keys are
    unchanged tuples-of-None)."""
    return (req.tenant,
            None if req.plan is None else req.plan.batch_key())


class DynamicBatcher:
    """Worker thread(s) draining a request queue through ``query_fn``.

    ``query_fn(queries f32[n,3]) -> (dists f32[n], neighbors i32[n,k])`` —
    typically ``admission.GracefulQueryFn`` wrapping a ResidentKnnEngine.
    With ``pipeline_depth > 1`` the wrapper's ``dispatch``/``complete``
    split is used instead (falling back to the serialized path when the
    callable lacks it — e.g. test doubles that are plain functions).
    """

    def __init__(self, query_fn, *, max_batch: int,
                 max_delay_s: float = 0.002, timers=None,
                 pipeline_depth: int = 1, min_batch: int | None = None,
                 dim: int | None = None, qcache=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self._query_fn = query_fn
        #: serve/qcache.py QueryCache (None = reuse layer off): submit()
        #: resolves every row through it — exact hits are served from the
        #: LRU with zero device work, duplicate rows join the in-flight
        #: owner, and exact-tier misses dispatch with certified radius
        #: seeds. When set, query_fn must accept ``seed_radius=``.
        self.qcache = qcache
        #: point dimensionality for normalizing flat submit() inputs;
        #: taken from the query_fn's engine/fanout when not given (3 as
        #: the last-resort legacy default)
        self.dim = int(dim) if dim else int(getattr(
            getattr(query_fn, "engine", None), "dim", 0)
            or getattr(query_fn, "dim", 0) or 3)
        self.max_batch = int(max_batch)
        #: stall-aware flush floor: while the device pipeline is BUSY (but
        #: not full), a deadline flush is worth dispatching only for at
        #: least this many rows — narrower slivers keep coalescing until
        #: the pipe drains. The default (= max_batch) reproduces the old
        #: batch-while-busy policy exactly (deadline flushes only on an
        #: idle pipe); the server passes the engine's narrowest shape
        #: bucket, which is what the padded program pays for anyway.
        self.min_batch = int(min_batch) if min_batch else self.max_batch
        self.max_delay_s = float(max_delay_s)
        self._timers = timers
        self.pipeline_depth = int(pipeline_depth)
        self.pipelined = (self.pipeline_depth > 1
                          and hasattr(query_fn, "dispatch")
                          and hasattr(query_fn, "complete"))
        #: streaming engines (serve/slabpool.py) expose ``prefetch_hint``:
        #: after each dispatch the worker announces the still-QUEUED rows
        #: — the next batch's content — so the engine's slab pool promotes
        #: that batch's routed slab set under the in-flight batch's
        #: compute (the graceful wrapper is looked through: hints go to
        #: the engine, not the degradation shim)
        self._prefetch_fn = (
            getattr(query_fn, "prefetch_hint", None)
            or getattr(getattr(query_fn, "engine", None),
                       "prefetch_hint", None))
        self._cond = threading.Condition()
        # queue + counters shared between submitter threads and the
        # dispatch/completion workers: every access is under _cond
        # (proven by lskcheck's guarded_by pass)
        self._queue: guarded_by("_cond") = deque()
        self._queued_rows: guarded_by("_cond") = 0
        self._shutdown: guarded_by("_cond") = False
        self.batches: guarded_by("_cond") = 0
        self.rows_served: guarded_by("_cond") = 0
        self.rows_expired: guarded_by("_cond") = 0
        self.flush_full: guarded_by("_cond") = 0
        self.flush_deadline: guarded_by("_cond") = 0
        # recall-SLO tier accounting: batches/rows that executed under an
        # approximate plan (subset of batches/rows_served)
        self.batches_approx: guarded_by("_cond") = 0
        self.rows_served_approx: guarded_by("_cond") = 0
        # pipeline occupancy/stall accounting (under _cond); the stall
        # histogram shares the loadgen/server bucket geometry so the three
        # render identical /metrics buckets
        self._inflight_batches: guarded_by("_cond") = 0
        self._inflight_rows: guarded_by("_cond") = 0
        self.dispatch_stalls: guarded_by("_cond") = 0
        self.dispatch_stall_seconds: guarded_by("_cond") = 0.0
        self.prefetch_hint_errors: guarded_by("_cond") = 0
        self.stall_hist = (timers.hist("pipeline_stall_seconds")
                           if timers is not None else LatencyHistogram())
        # time spent blocked inside query_fn.complete — for routed
        # fan-outs this includes fold + escalation waves, the number that
        # explains a long-held pipeline slot
        self.complete_hist = (timers.hist("batch_complete_seconds")
                              if timers is not None else LatencyHistogram())
        self._workers: list[threading.Thread] = []
        if self.pipelined:
            self._inflight: queue.Queue = queue.Queue()
            self._slots = threading.Semaphore(self.pipeline_depth)
            self._workers = [
                threading.Thread(target=self._run_dispatch, daemon=True,
                                 name="knn-batcher-dispatch"),
                threading.Thread(target=self._run_complete, daemon=True,
                                 name="knn-batcher-complete"),
            ]
        else:
            self._workers = [threading.Thread(target=self._run, daemon=True,
                                              name="knn-batcher")]
        for w in self._workers:
            w.start()

    # ------------------------------------------------------------------ submit

    def submit(self, queries: np.ndarray, timeout_s: float | None = None,
               plan=None, tenant: str | None = None):
        """Block until the batch containing ``queries`` executes; returns
        ``(dists, neighbors)`` or raises the request's error. ``plan``
        (serve/recall.py RecallPlan, None = exact) rides the request and
        restricts coalescing to plan-compatible neighbors — mixed-SLO
        traffic splits into per-plan sub-batches instead of forcing the
        strictest plan on everyone. ``tenant`` (serve/tenancy.py, None =
        single-index) does the same per index: a flush never mixes two
        tenants' rows in one engine batch.

        With a query cache attached (``qcache``), every row first resolves
        through the reuse tiers: an exact HIT is answered from the LRU
        (byte-identical, zero device work), a duplicate of an in-flight
        row JOINs its owner's entry, and the remaining rows dispatch as
        this request's own sub-batch — seeded with certified init radii
        on the exact tier. An all-hit request never touches the queue."""
        # normalize to [n, dim] rows (flat inputs carry n*dim floats — the
        # legacy direct-caller contract, now D-generic via self.dim)
        queries = np.asarray(queries, np.float32).reshape(-1, self.dim)
        qc = self.qcache
        if qc is None or len(queries) == 0:
            return self._submit_rows(queries, timeout_s, plan, tenant, None)
        n = len(queries)
        plan_token = None if plan is None else plan.batch_key()
        actions = qc.begin(queries, plan_token, tenant)
        own_idx = [i for i, a in enumerate(actions) if a[0] == "own"]
        owned_keys = [actions[i][1] for i in own_idx]
        rows: list = [None] * n
        try:
            if own_idx:
                sub_q = queries[own_idx] if len(own_idx) < n else queries
                seeds = qc.seed_for(sub_q, tenant) if plan is None else None
                outs = self._submit_rows(sub_q, timeout_s, plan, tenant,
                                         seeds)
                # publish BEFORE waiting on other owners' entries: owners
                # that publish before they park can never deadlock
                qc.publish(owned_keys, outs, sub_q, plan_token, tenant)
                if len(own_idx) == n:
                    return outs  # pure miss: no reassembly needed
                for j, i in enumerate(own_idx):
                    rows[i] = tuple(a[j] for a in outs)
        except Exception as e:  # noqa: BLE001 - joiners must not hang
            qc.abort(owned_keys, e)
            raise
        grace = None if timeout_s is None else timeout_s + 30.0
        retry = []
        for i, a in enumerate(actions):
            if a[0] == "hit":
                rows[i] = a[1]
            elif a[0] == "join":
                if not a[1].event.wait(grace):
                    raise DeadlineExceeded(
                        "deduplicated row stuck behind its in-flight owner")
                if a[1].error is not None:
                    # owner failed: retry the row as our own sub-batch,
                    # bypassing the cache (the aborted entries are gone,
                    # and a re-join could chain onto another failing owner)
                    retry.append(i)
                else:
                    rows[i] = a[1].result
        if retry:
            outs = self._submit_rows(queries[retry], timeout_s, plan,
                                     tenant, None)
            for j, i in enumerate(retry):
                rows[i] = tuple(a[j] for a in outs)
        for i, a in enumerate(actions):
            if a[0] == "local":
                rows[i] = rows[a[1]]
        return tuple(np.stack([r[c] for r in rows])
                     for c in range(len(rows[0])))

    def _submit_rows(self, queries: np.ndarray,
                     timeout_s: float | None, plan, tenant,
                     seeds: np.ndarray | None):
        """Enqueue one device sub-batch and block for its result — the
        pre-cache submit path, verbatim."""
        now = time.monotonic()
        req = _Request(queries=queries, enqueued=now,
                       deadline=(now + timeout_s) if timeout_s else None,
                       plan=plan, tenant=tenant, seeds=seeds)
        with self._cond:
            if self._shutdown:
                raise RuntimeError("batcher is shut down")
            self._queue.append(req)
            self._queued_rows += req.rows
            self._cond.notify_all()
        # grace beyond the deadline: the worker completes expired requests
        # with DeadlineExceeded itself; the extra wait covers an in-flight
        # engine call that started before the deadline passed
        wait = None if timeout_s is None else timeout_s + 30.0
        if not req.done.wait(wait):
            raise DeadlineExceeded("request stuck in batcher")
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------------ worker

    def _take_batch(self) -> list[_Request] | None:
        """Wait for a flushable batch; None on shutdown.

        Batch-while-busy, stall-aware: a full queue (``max_batch`` rows)
        always flushes. The ``max_delay_s`` deadline flush fires when the
        pipe is idle, or — pipelined, with a free slot already reserved by
        the dispatch worker — when at least ``min_batch`` rows are queued:
        a sliver narrower than the engine's narrowest shape bucket cannot
        start any sooner than the in-flight work it would queue behind, so
        it keeps accumulating until the device frees up (the completion
        worker notifies). The dispatch worker acquires its pipeline slot
        BEFORE calling this, so while the pipe is FULL nothing is popped at
        all and late arrivals coalesce into the stalled batch instead of
        queueing behind it.
        """
        with self._cond:
            while True:
                if self._shutdown and not self._queue:
                    return None
                if self._queue:
                    oldest = self._queue[0]
                    flush_at = oldest.enqueued + self.max_delay_s
                    now = time.monotonic()
                    busy_ok = (self._inflight_batches == 0
                               or (self.pipelined
                                   and self._queued_rows >= self.min_batch))
                    if (self._queued_rows >= self.max_batch
                            or (now >= flush_at and busy_ok)
                            or self._shutdown):
                        break
                    self._cond.wait((flush_at - now) if busy_ok else None)
                else:
                    self._cond.wait()
            # pop whole requests while they fit; a single over-wide request
            # (> max_batch rows) was rejected upstream by admission sizing,
            # but guard anyway by always taking at least one. Plan-keyed
            # sub-batching: only coalesce while the next request shares the
            # head's plan batch_key — and never skip over a queued request
            # (strict FIFO: a mixed-SLO queue flushes as consecutive
            # per-plan runs, so no plan can starve another)
            batch = [self._queue.popleft()]
            rows = batch[0].rows
            pkey = _plan_key(batch[0])
            while (self._queue
                   and rows + self._queue[0].rows <= self.max_batch
                   and _plan_key(self._queue[0]) == pkey):
                r = self._queue.popleft()
                batch.append(r)
                rows += r.rows
            self._queued_rows -= rows
            self.batches += 1
            if batch[0].plan is not None:
                self.batches_approx += 1
            if rows >= self.max_batch:
                self.flush_full += 1
            else:
                self.flush_deadline += 1
            return batch

    def _split_expired(self, batch: list[_Request]) -> list[_Request]:
        """Fail deadline-expired requests now; return the live remainder."""
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                with self._cond:
                    self.rows_expired += r.rows
                r.error = DeadlineExceeded(
                    f"deadline passed after {now - r.enqueued:.3f}s in queue")
                r.done.set()
            else:
                live.append(r)
        return live

    @staticmethod
    def _deliver(live: list[_Request], outs: tuple) -> None:
        """Offset-demux every array of the result tuple per request. The
        tuple is ``(dists, neighbors)`` for engines and the replicate pod,
        ``(dists, neighbors, exact)`` for routed fan-outs (the per-row
        exactness mask under degraded serving) — the demux is shape-generic
        so a new result column never touches this code again."""
        off = 0
        for r in live:
            r.result = tuple(a[off:off + r.rows] for a in outs)
            off += r.rows
            r.done.set()

    @staticmethod
    def _fail(live: list[_Request], err: Exception) -> None:
        for r in live:
            r.error = err
            r.done.set()

    @staticmethod
    def _merged_seeds(live: list[_Request]) -> np.ndarray | None:
        """Concatenated per-row init radii for a flush, or None when no
        request in it carries seeds (the common case — and the ONLY case
        for legacy/test-double query_fns, which are never handed a
        ``seed_radius`` kwarg they don't know). Unseeded requests pad
        with +inf rows — the engine treats +inf as its static radius."""
        if all(r.seeds is None for r in live):
            return None
        parts = [r.seeds if r.seeds is not None
                 else np.full(r.rows, np.inf, np.float32) for r in live]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    # -------------------------------------------------- serialized (depth 1)

    def _run(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            live = self._split_expired(batch)
            if not live:
                continue
            try:
                t0 = time.perf_counter()
                merged = (live[0].queries if len(live) == 1 else
                          np.concatenate([r.queries for r in live]))
                # exact single-index requests call the legacy single-arg
                # form so plain test doubles (and the pre-tier wire) stay
                # compatible; tenant/plan kwargs only appear when set
                plan, tenant = live[0].plan, live[0].tenant
                kw = {}
                seeds = self._merged_seeds(live)
                if seeds is not None:
                    kw["seed_radius"] = seeds
                if tenant is not None:
                    outs = self._query_fn(merged, plan=plan, tenant=tenant,
                                          **kw)
                elif plan is None:
                    outs = self._query_fn(merged, **kw)
                else:
                    outs = self._query_fn(merged, plan=plan, **kw)
                if self._timers is not None:
                    self._timers.hist("batch_exec_seconds").record(
                        time.perf_counter() - t0)
                self._deliver(live, outs)
                with self._cond:
                    self.rows_served += len(merged)
                    if plan is not None:
                        self.rows_served_approx += len(merged)
            except Exception as e:  # noqa: BLE001 - delivered per request
                self._fail(live, e)

    # -------------------------------------------------- pipelined (depth > 1)

    def _wait_for_work(self) -> bool:
        """Park until at least one request is queued; False on shutdown
        with an empty queue."""
        with self._cond:
            while not self._queue:
                if self._shutdown:
                    return False
                self._cond.wait()
            return True

    def _run_dispatch(self):
        """Flush loop: launch device work, hand futures to the completer.

        Stall-aware ordering: the pipeline slot is reserved BEFORE a batch
        is popped. When ``pipeline_depth`` batches are already between
        dispatch and demux the worker blocks here (recording stall time)
        with the requests still IN the queue — so they keep coalescing
        toward a full batch, and deadline-expired ones are failed at pop
        time instead of going stale behind the semaphore. The old policy
        popped first and stalled holding a batch whose width was frozen
        (BENCH_serve.json depth-2 regression: 68 stalls / 1.57 s on the
        smoke fixture). The bound itself is unchanged — it is what keeps a
        fast producer from piling unmerged device results without limit.
        """
        while True:
            if not self._wait_for_work():
                # FIFO sentinel: the completer drains everything already
                # dispatched, then exits — a clean pipeline drain
                self._inflight.put(None)
                return
            if not self._slots.acquire(blocking=False):
                t0 = time.perf_counter()
                self._slots.acquire()
                stall = time.perf_counter() - t0
                self.stall_hist.record(stall)
                with self._cond:
                    self.dispatch_stalls += 1
                    self.dispatch_stall_seconds += stall
            batch = self._take_batch()
            if batch is None:
                self._slots.release()
                self._inflight.put(None)
                return
            live = self._split_expired(batch)
            if not live:
                self._slots.release()
                continue
            merged = (live[0].queries if len(live) == 1 else
                      np.concatenate([r.queries for r in live]))
            with self._cond:
                self._inflight_batches += 1
                self._inflight_rows += len(merged)
                inflight = self._inflight_batches
            if self._timers is not None:
                self._timers.gauge("pipeline_inflight_batches", inflight)
            try:
                t0 = time.perf_counter()
                plan, tenant = live[0].plan, live[0].tenant
                kw = {}
                seeds = self._merged_seeds(live)
                if seeds is not None:
                    kw["seed_radius"] = seeds
                if tenant is not None:
                    handle = self._query_fn.dispatch(merged, plan=plan,
                                                     tenant=tenant, **kw)
                elif plan is None:
                    handle = self._query_fn.dispatch(merged, **kw)
                else:
                    handle = self._query_fn.dispatch(merged, plan=plan, **kw)
            except Exception as e:  # noqa: BLE001 - delivered per request
                self._fail(live, e)
                with self._cond:
                    self._inflight_batches -= 1
                    self._inflight_rows -= len(merged)
                    inflight = self._inflight_batches
                    self._cond.notify_all()
                if self._timers is not None:
                    self._timers.gauge("pipeline_inflight_batches", inflight)
                self._slots.release()
                continue
            self._inflight.put((live, len(merged), handle, t0))
            self._announce_prefetch()

    def _announce_prefetch(self):
        """Announce the queued rows — the NEXT batch's likely content —
        to a streaming engine's prefetcher right after a dispatch, so
        slab promotions overlap the batch just launched
        (serve/slabpool.py). Advisory only: a hint failure is counted,
        never allowed to fail the dispatched batch."""
        if self._prefetch_fn is None:
            return
        with self._cond:
            if not self._queue:
                return
            # group by tenant: each index's prefetcher should only see its
            # own rows (single-index queues collapse to one None group)
            groups, rows = {}, 0
            for r in self._queue:
                if rows + r.rows > self.max_batch:
                    break
                groups.setdefault(r.tenant, []).append(r.queries)
                rows += r.rows
        for tenant, pending in groups.items():
            try:
                merged = (pending[0] if len(pending) == 1
                          else np.concatenate(pending))
                if tenant is None:
                    self._prefetch_fn(merged)
                else:
                    self._prefetch_fn(merged, tenant=tenant)
            except Exception:  # noqa: BLE001 - advisory; counted below
                with self._cond:
                    self.prefetch_hint_errors += 1

    def _run_complete(self):
        """Completion loop: block on the oldest in-flight batch, demux.

        FIFO order means a batch's demux can start the moment ITS device
        work lands, while later batches are still traversing — and request
        ordering within a batch is preserved by the offset demux.
        """
        while True:
            item = self._inflight.get()
            if item is None:
                return
            live, rows, handle, t0 = item
            try:
                tc = time.perf_counter()
                outs = self._query_fn.complete(handle)
                self.complete_hist.record(time.perf_counter() - tc)
                if self._timers is not None:
                    self._timers.hist("batch_exec_seconds").record(
                        time.perf_counter() - t0)
                self._deliver(live, outs)
                with self._cond:
                    self.rows_served += rows
                    if live[0].plan is not None:
                        self.rows_served_approx += rows
            except Exception as e:  # noqa: BLE001 - delivered per request
                self._fail(live, e)
            finally:
                with self._cond:
                    self._inflight_batches -= 1
                    self._inflight_rows -= rows
                    inflight = self._inflight_batches
                    # wake a dispatch worker parked on batch-while-busy: the
                    # device freed a slot, so a deadline flush is allowed now
                    self._cond.notify_all()
                if self._timers is not None:
                    self._timers.gauge("pipeline_inflight_batches", inflight)
                self._slots.release()

    # ------------------------------------------------------------------- admin

    def queue_depth_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def inflight_rows(self) -> int:
        """Rows dispatched on the device but not yet demuxed (0 when
        serialized — the single worker holds no futures between flushes)."""
        with self._cond:
            return self._inflight_rows

    def inflight_batches(self) -> int:
        with self._cond:
            return self._inflight_batches

    def stats(self) -> dict:
        with self._cond:
            return {
                "batches": self.batches,
                "rows_served": self.rows_served,
                "rows_expired": self.rows_expired,
                "flush_full": self.flush_full,
                "flush_deadline": self.flush_deadline,
                "batches_approx": self.batches_approx,
                "rows_served_approx": self.rows_served_approx,
                "queue_rows": self._queued_rows,
                "mean_batch_rows": round(
                    self.rows_served / self.batches, 2) if self.batches else 0,
                "pipeline_depth": self.pipeline_depth,
                "pipelined": self.pipelined,
                "min_batch": self.min_batch,
                "inflight_batches": self._inflight_batches,
                "inflight_rows": self._inflight_rows,
                "dispatch_stalls": self.dispatch_stalls,
                "dispatch_stall_seconds": round(
                    self.dispatch_stall_seconds, 6),
                "prefetch_hint_errors": self.prefetch_hint_errors,
                "complete_seconds_total": round(
                    self.complete_hist.sum_seconds, 6),
            }

    def shutdown(self, wait: bool = True):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        if wait:
            for w in self._workers:
                w.join(timeout=10)
