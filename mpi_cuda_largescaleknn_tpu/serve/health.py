"""Health-driven host lifecycle: healthy -> suspect -> drained -> rejoining.

The reference is fail-stop by MPI design (one dead rank kills the job) and
PR 5's pod front end inherited that contract. PR 7's routed mode made pod
hosts INDEPENDENT slab engines, so host loss can finally be a *partial*
event — this module supplies the supervision that turns "a host died" from
``PodBrokenError`` into a state transition the fan-out routes around:

- ``HostHealth`` is the per-host state machine. Dispatch failures and probe
  failures feed ``note_failure`` (``fail_threshold`` consecutive failures
  drain the host); successes reset to healthy. All timing runs through an
  injectable monotonic ``clock`` so tests drive transitions without sleeps.
- ``Backoff`` is capped exponential delay with DETERMINISTIC jitter: the
  jitter fraction is a hash of (seed, key, attempt), not a shared RNG, so
  concurrent callers cannot perturb each other's schedules and a test can
  predict every delay exactly.
- ``HealthMonitor`` is the background supervisor: it probes each endpoint's
  ``/healthz`` when due (healthy hosts at ``probe_interval_s``; drained
  hosts on the capped-exponential backoff schedule), and drives REJOIN:
  a drained host that answers its probe moves to ``rejoining``, its
  ``/stats`` is scraped and its config/bounds fingerprint compared against
  the pod table captured at front-end startup — only a bitwise-matching
  fingerprint undrains it (a restarted host serving different rows or a
  different k would silently corrupt the fold). Replicate-mode (routing
  off) pods are one SPMD machine, so rejoin there is pod-wide: when the
  pod is broken and EVERY host probes healthy with matching fingerprints
  and a consistent ``next_seq``, the monitor resets the fan-out's sequence
  stream (drain-then-fail with a clean restart path, instead of the old
  restart-everything-and-the-frontend-too wedge).

The monitor's probe/scrape transports are injectable (``probe_fn`` /
``stats_fn``) so the state machine is unit-testable without HTTP; the
defaults use urllib against the real endpoints.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import zlib

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

STATES = ("healthy", "suspect", "drained", "rejoining")
STATE_CODE = {s: i for i, s in enumerate(STATES)}

#: engine-stats keys that must survive a host restart unchanged for the
#: host to rejoin a ROUTED pod: the result contract (k/dim/radius/score),
#: the slab identity (row_offset/n_points), and the routing bounds the
#: front end's table was built from — a mismatch means the front end's
#: routing decisions no longer describe the host's data.
ROUTED_FINGERPRINT_KEYS = (
    "k", "dim", "max_batch", "score_dtype", "max_radius", "row_offset",
    "n_points", "emit", "bucket_size", "shape_buckets", "canonical_ties",
    "shard_bounds",
)

#: replicate-mode pods additionally pin the AOT program identity — every
#: host must re-enter the SAME collective program after a restart.
POD_FINGERPRINT_KEYS = ROUTED_FINGERPRINT_KEYS + (
    "merge", "num_shards", "engine", "query_buckets", "sort_queries",
    "process_count", "my_positions",
)


def host_fingerprint(engine_stats: dict, mode: str) -> dict:
    """Canonical identity of a host's serving config + bounds, from its
    /stats ``engine`` block. Both sides of every comparison come through
    the same JSON round trip, so plain ``==`` is exact."""
    keys = (POD_FINGERPRINT_KEYS if mode == "off"
            else ROUTED_FINGERPRINT_KEYS)
    return {k: engine_stats.get(k) for k in keys}


class Backoff:
    """Capped exponential backoff with deterministic jitter.

    ``delay(attempt, key)`` for attempt 1, 2, ... is
    ``min(cap, base * factor**(attempt-1)) * (1 + jitter * u)`` where
    ``u in [0, 1)`` is a hash of (seed, key, attempt) — stateless, so
    concurrent users can't skew each other and tests can predict delays.
    """

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.1, seed: int = 0):
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delay(self, attempt: int, key: str = "") -> float:
        d = min(self.cap_s,
                self.base_s * self.factor ** max(0, int(attempt) - 1))
        if self.jitter:
            u = zlib.crc32(f"{self.seed}:{key}:{attempt}".encode()) / 2 ** 32
            d *= 1.0 + self.jitter * u
        return d


class HostHealth:
    """Per-host lifecycle state machine (thread-safe; injectable clock).

    Fed from two directions: the fan-out's dispatch path reports
    per-request outcomes (``note_success`` / ``note_failure``) and the
    monitor reports probe outcomes through the same calls — both sides see
    the same truth. Draining happens HERE (``fail_threshold`` consecutive
    failures); undraining only happens through ``mark_rejoined`` because it
    requires the monitor's fingerprint validation.
    """

    def __init__(self, *, fail_threshold: int = 3,
                 probe_interval_s: float = 5.0,
                 backoff_base_s: float = 0.5, backoff_cap_s: float = 30.0,
                 jitter: float = 0.1, seed: int = 0,
                 clock=time.monotonic):
        self.fail_threshold = int(fail_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.backoff = Backoff(backoff_base_s, backoff_cap_s,
                               jitter=jitter, seed=seed)
        self._clock = clock
        self._lock = threading.Lock()
        # lifecycle state fed from BOTH the dispatch path and the monitor
        # thread: every access goes through _lock (lskcheck-proven);
        # external readers use snapshot()/is_drained()/drained_seconds()
        self.state: guarded_by("_lock") = "healthy"
        self.consecutive_failures: guarded_by("_lock") = 0
        self.last_error: guarded_by("_lock") = None
        self.last_probe_at: guarded_by("_lock") = None
        self.next_probe_at: guarded_by("_lock") = 0.0  # due immediately
        #: drained-probe counter (backoff exponent)
        self.probe_attempt: guarded_by("_lock") = 0
        self.drained_at: guarded_by("_lock") = None
        self._drained_seconds: guarded_by("_lock") = 0.0
        self.transitions: guarded_by("_lock") = 0

    # ------------------------------------------------------------ transitions

    def _enter(self, state: str) -> None:  # lsk: holds[_lock]
        if state == self.state:
            return
        now = self._clock()
        if self.state == "drained" and state not in ("drained", "rejoining"):
            if self.drained_at is not None:
                self._drained_seconds += now - self.drained_at
                self.drained_at = None
        if state == "drained" and self.drained_at is None:
            self.drained_at = now
            self.probe_attempt = 0
        self.state = state
        self.transitions += 1

    def note_success(self) -> None:
        """A request or probe succeeded."""
        with self._lock:
            if self.state in ("healthy", "suspect"):
                self._enter("healthy")
                self.consecutive_failures = 0

    def note_failure(self, err: str) -> None:
        """A request or probe failed; drains at ``fail_threshold``."""
        with self._lock:
            self.last_error = str(err)
            if self.state in ("healthy", "suspect"):
                self.consecutive_failures += 1
                if self.consecutive_failures >= self.fail_threshold:
                    self._enter("drained")
                else:
                    self._enter("suspect")
            elif self.state == "rejoining":
                self._enter("drained")

    def force_drain(self, err: str) -> None:
        """Drain immediately (replicate-mode pods: one failure IS fatal)."""
        with self._lock:
            self.last_error = str(err)
            self.consecutive_failures = max(self.consecutive_failures,
                                            self.fail_threshold)
            self._enter("drained")

    def mark_rejoining(self) -> None:
        with self._lock:
            if self.state == "drained":
                self._enter("rejoining")

    def mark_rejoined(self) -> None:
        """Fingerprint validated: the host is healthy again."""
        with self._lock:
            # a rejoining host's drained spell ends where the drain began
            if self.drained_at is not None:
                self._drained_seconds += self._clock() - self.drained_at
                self.drained_at = None
            self._enter("healthy")
            self.consecutive_failures = 0
            self.probe_attempt = 0

    def rejoin_failed(self, err: str) -> None:
        with self._lock:
            self.last_error = str(err)
            self._enter("drained")

    # ------------------------------------------------------------- scheduling

    def probe_due(self, now: float | None = None) -> bool:
        with self._lock:
            return (now if now is not None
                    else self._clock()) >= self.next_probe_at

    def schedule_next_probe(self, key: str = "",
                            now: float | None = None) -> float:
        """Set + return the next probe time: steady interval while
        healthy/suspect, capped-exponential backoff while drained."""
        with self._lock:
            now = now if now is not None else self._clock()
            self.last_probe_at = now
            if self.state in ("drained", "rejoining"):
                self.probe_attempt += 1
                delay = self.backoff.delay(self.probe_attempt, key)
            else:
                delay = self.probe_interval_s
            self.next_probe_at = now + delay
            return self.next_probe_at

    # ------------------------------------------------------------------ admin

    def is_drained(self) -> bool:
        with self._lock:
            return self.state in ("drained", "rejoining")

    def drained_seconds(self) -> float:
        with self._lock:
            live = ((self._clock() - self.drained_at)
                    if self.drained_at is not None else 0.0)
            return self._drained_seconds + live

    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            # the NEXT drained-probe delay (jitter excluded — the per-host
            # jitter key is the fan-out's url, which this state machine
            # does not know): 0.0 while healthy/suspect. A successful
            # rejoin resets probe_attempt, so a later flap reads the BASE
            # interval here again, never the cap (tests/test_failover.py
            # pins that reset).
            backoff_now = (
                round(min(self.backoff.cap_s,
                          self.backoff.base_s
                          * self.backoff.factor ** self.probe_attempt), 3)
                if self.state in ("drained", "rejoining") else 0.0)
            return {
                "state": self.state,
                "state_code": STATE_CODE[self.state],
                "consecutive_failures": self.consecutive_failures,
                "fail_threshold": self.fail_threshold,
                "backoff_current_s": backoff_now,
                "last_error": self.last_error,
                "last_probe_age_s": (round(now - self.last_probe_at, 3)
                                     if self.last_probe_at is not None
                                     else None),
                "drained_seconds_total": round(
                    self._drained_seconds
                    + ((now - self.drained_at)
                       if self.drained_at is not None else 0.0), 3),
                "transitions": self.transitions,
            }


# ------------------------------------------------------------------ monitor


def _http_probe(url: str, timeout_s: float):
    """GET /healthz -> (ok, info dict). Down IS an answer, never a raise."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                    timeout=timeout_s) as r:
            return r.status == 200, json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001 - any transport failure = down
        return False, {"error": f"{type(e).__name__}: {e}"}


def _http_stats(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/stats",
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode())


class HealthMonitor:
    """Background supervisor driving every endpoint's HostHealth.

    ``check_once(now)`` is the whole brain — the thread just calls it on a
    poll loop; tests call it directly with a fake ``now`` and injected
    ``probe_fn`` / ``stats_fn`` transports, so no test ever sleeps.
    """

    def __init__(self, fanout, *, fingerprints: dict | None = None,
                 mode: str = "bounds", probe_timeout_s: float = 2.0,
                 probe_fn=None, stats_fn=None, clock=time.monotonic,
                 poll_s: float = 0.25):
        self.fanout = fanout
        self.fingerprints = dict(fingerprints or {})
        self.mode = mode
        self.probe_timeout_s = float(probe_timeout_s)
        self._probe = probe_fn or (
            lambda url: _http_probe(url, self.probe_timeout_s))
        self._stats = stats_fn or (
            lambda url: _http_stats(url, self.probe_timeout_s))
        self._clock = clock
        self.poll_s = float(poll_s)
        #: slab-handoff supervisor (serve/replica.py ReplicaManager),
        #: attached by build_frontend on routed pods BEFORE start();
        #: driven from check_once so handoffs ride the same cadence (and
        #: the same fake-now test harness) as drain/rejoin
        self.replica_manager = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # monitor counters are read by /stats scrapes while check_once
        # runs on the monitor thread
        self.probes: guarded_by("_lock") = 0
        self.rejoins: guarded_by("_lock") = 0
        self.rejoin_rejections: guarded_by("_lock") = 0
        self.stream_resets: guarded_by("_lock") = 0
        #: bounded transition log (stats/debug)
        self.events: guarded_by("_lock") = []

    # ----------------------------------------------------------------- driver

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="knn-health-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 - supervisor must survive
                self._event(f"monitor error: {type(e).__name__}: {e}")

    def _event(self, msg: str) -> None:
        with self._lock:
            self.events.append(msg)
            del self.events[:-50]

    # ------------------------------------------------------------------ brain

    def check_once(self, now: float | None = None) -> None:
        """Probe every endpoint that is due; drive drain/rejoin."""
        now = now if now is not None else self._clock()
        probe_ok: dict[str, tuple[bool, dict]] = {}
        for ep in self.fanout.endpoints:
            h = ep.health
            if not h.probe_due(now):
                continue
            ok, info = self._probe(ep.url)
            with self._lock:
                self.probes += 1
            probe_ok[ep.url] = (ok, info)
            was = h.state
            if h.state in ("healthy", "suspect"):
                if ok:
                    h.note_success()
                else:
                    h.note_failure(info.get("error", "healthz not ok"))
            else:  # drained / rejoining
                if ok:
                    h.mark_rejoining()
                    if (self.mode == "off"
                            and self._fanout_broken() is not None):
                        # the broken replicate stream rejoins pod-wide
                        # (below); the host stays rejoining until the
                        # whole pod resets
                        pass
                    else:
                        # routed hosts — and replicate hosts drained by
                        # probe blips while the stream never broke —
                        # rejoin individually on a fingerprint match
                        self._try_rejoin(ep)
                else:
                    h.rejoin_failed(info.get("error", "healthz not ok"))
            if h.state != was:
                self._event(f"{ep.url}: {was} -> {h.state}")
            h.schedule_next_probe(key=ep.url, now=now)
        if self.mode == "off":
            self._try_pod_reset(probe_ok)
        rm = self.replica_manager
        if rm is not None:
            try:
                rm.check_once(now)
            except Exception as e:  # noqa: BLE001 - supervisor must survive
                self._event(f"handoff error: {type(e).__name__}: {e}")

    def _fanout_broken(self) -> str | None:
        """The fan-out's broken marker through its LOCKED accessor —
        ``broken`` is guarded_by the fan-out's lock, and the monitor
        thread is exactly the kind of cross-thread reader the guard
        exists for (plain fakes in tests may lack the accessor)."""
        fn = getattr(self.fanout, "broken_reason", None)
        return fn() if fn is not None else getattr(self.fanout, "broken",
                                                   None)

    def _try_rejoin(self, ep) -> bool:
        """Routed-mode rejoin: revalidate the host's config/bounds
        fingerprint against the pod table before undraining."""
        try:
            stats = self._stats(ep.url)
            fp = host_fingerprint(stats.get("engine", {}), self.mode)
        except Exception as e:  # noqa: BLE001 - scrape failure = not yet
            ep.health.rejoin_failed(f"rejoin stats scrape failed: "
                                    f"{type(e).__name__}: {e}")
            return False
        want = self.fingerprints.get(ep.url)
        if want is not None and fp != want:
            diff = sorted(k for k in want if fp.get(k) != want.get(k))
            ep.health.rejoin_failed(
                f"rejoin rejected: fingerprint mismatch on {diff} — the "
                "returning host does not serve the slab/config the pod "
                "table was built from")
            with self._lock:
                self.rejoin_rejections += 1
            self._event(f"{ep.url}: rejoin rejected ({diff})")
            return False
        ep.health.mark_rejoined()
        with self._lock:
            self.rejoins += 1
        self._event(f"{ep.url}: rejoined")
        return True

    def _try_pod_reset(self, probe_ok: dict) -> None:
        """Replicate-mode recovery: the pod is one SPMD machine, so rejoin
        is all-or-nothing — when the stream is broken and every host
        answers healthy with a matching fingerprint and ONE consistent
        ``next_seq``, reset the fan-out's sequence stream and undrain
        everyone (the clean-restart path). Paced by the main loop's probe
        schedule: a reset is only attempted when at least one endpoint
        was actually due for a probe this cycle, so a long outage costs
        the drained hosts' capped-exponential cadence, not one full pod
        probe + stats scrape per poll tick."""
        if self._fanout_broken() is None or not probe_ok:
            return
        seqs = []
        for ep in self.fanout.endpoints:
            # reuse this cycle's probe result where one exists
            ok, info = probe_ok.get(ep.url) or self._probe(ep.url)
            if not ok:
                return
            try:
                stats = self._stats(ep.url)
                fp = host_fingerprint(stats.get("engine", {}), "off")
            except Exception:  # noqa: BLE001 - not yet
                return
            want = self.fingerprints.get(ep.url)
            if want is not None and fp != want:
                ep.health.rejoin_failed(
                    "pod reset rejected: fingerprint mismatch")
                with self._lock:
                    self.rejoin_rejections += 1
                return
            seqs.append(int(info.get("next_seq", -1)))
        if len(set(seqs)) != 1 or seqs[0] < 0:
            self._event(f"pod reset blocked: next_seq disagree {seqs}")
            return
        self.fanout.reset_stream(seqs[0])
        for ep in self.fanout.endpoints:
            if ep.health.state != "healthy":
                ep.health.mark_rejoined()
        with self._lock:
            self.stream_resets += 1
        self._event(f"pod stream reset to seq {seqs[0]}")

    def stats(self) -> dict:
        rm = self.replica_manager
        handoff = rm.stats() if rm is not None else None
        with self._lock:
            return {"probes": self.probes, "rejoins": self.rejoins,
                    "rejoin_rejections": self.rejoin_rejections,
                    "stream_resets": self.stream_resets,
                    "running": self.running,
                    "handoff": handoff,
                    "events": list(self.events[-10:])}
