"""Replica groups + slab handoff: host loss costs capacity, not exactness.

PR 8 made host loss survivable (drain/rejoin, degraded answers); this
module makes it FREE for any slab with a spare copy. Two pieces:

- ``ReplicaSet`` — the slab -> endpoint-group table the routed fan-out
  (serve/frontend.py ``RoutedPodFanout``) dispatches through. Each slab
  (one contiguous row range of the global index) is served by R >= 1 host
  endpoints running IDENTICAL engines (validated replica-for-replica by
  the routed ``host_fingerprint`` at front-end build — same rows, same
  config, same shard bounds, so any member's answer is byte-equal to any
  other's). ``pick`` chooses one healthy member per (slab, sub-batch)
  with health-weighted spreading: per-batch failure penalties first (a
  replica that just failed this batch is deprioritized immediately), then
  the PR-8 lifecycle state, then cumulative drained-seconds and observed
  latency (coarse buckets, so noise cannot flap the choice), then a
  least-picked spread counter, with a deterministic ``crc32(seed, slab,
  url)`` tie-break — no RNG, so a fixed seed reproduces the exact pick
  sequence (tests/test_replica.py). A slab is DOWN only when every member
  is drained: that is the only remaining way a routed query goes
  ``exact: false`` under the PR-8 contract.

- ``ReplicaManager`` — the slab-HANDOFF brain, driven from the PR-8
  ``HealthMonitor``'s ``check_once`` loop. When a slab's live-replica
  count falls below ``handoff_floor``, an idle WARM STANDBY host (a
  ``serve_main --standby`` process holding no slab) is directed to adopt
  the rows via ``POST /adopt_slab``: the standby re-materializes the slab
  from the source file (the reference's ``read_file_portion`` split —
  identical integer arithmetic, so the adopted rows are byte-equal to the
  lost host's) or pulls them from a surviving replica
  (``pull_slab_rows``), builds the routed slab engine, and AOT-warms
  every shape bucket before reporting ready. The adopted slab NEVER
  serves un-proven: the manager compares its /stats fingerprint against
  the pod table captured at front-end build and only a bitwise match is
  bound into the ``ReplicaSet`` (``fanout.bind_replica``) — a standby
  that came up on the wrong slab or config stays out of rotation with
  the diff in ``last_error``, exactly the PR-8 rejoin-gate discipline.
  Re-binding is the array-redistribution insight (PAPERS.md, arXiv
  2112.01075) applied to serving: slab movement between hosts is a
  validated data-plane operation, not a topology rebuild — rejoin no
  longer requires the same host back.

All transports are injectable (``probe_fn`` / ``stats_fn`` /
``adopt_fn``) and time rides an injectable monotonic clock, so every
handoff transition is unit-testable without HTTP or sleeps (the PR-8
monitor discipline).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import zlib

import numpy as np

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.serve.health import (
    STATE_CODE,
    host_fingerprint,
)
from mpi_cuda_largescaleknn_tpu.serve.wire import (
    WireError,
    decode_slab_chunk,
    read_frames,
)

# ------------------------------------------------------------- replica set


class ReplicaSet:
    """Slab -> replica-endpoint-group table with deterministic spreading.

    ``endpoints`` is the owning fan-out's live endpoint list, shared BY
    REFERENCE: ``bind_replica`` appends to it at runtime and ``rebind``
    here records the new member index, so the set always sees the same
    endpoints the dispatch path uses. ``groups`` come from
    ``group_routed_hosts`` (slab-major, validated); ``None`` builds the
    trivial R=1 set — one slab per endpoint, which reduces the routed
    fan-out to its exact pre-replica behavior.
    """

    def __init__(self, endpoints, groups=None, *, seed: int = 0):
        self._endpoints = endpoints
        self.seed = int(seed)
        if groups is None:
            groups = [{"row_offset": None, "n_points": None,
                       "urls": [ep.url]} for ep in endpoints]
        url_to_i = {ep.url: i for i, ep in enumerate(endpoints)}
        members, meta, covered = [], [], set()
        for g in groups:
            idxs = []
            for u in g["urls"]:
                if u not in url_to_i:
                    raise ValueError(f"replica group references unknown "
                                     f"endpoint {u!r}")
                if url_to_i[u] in covered:
                    raise ValueError(f"endpoint {u!r} appears in more than "
                                     "one replica group")
                covered.add(url_to_i[u])
                idxs.append(url_to_i[u])
            if not idxs:
                raise ValueError("empty replica group")
            members.append(idxs)
            meta.append({"row_offset": g.get("row_offset"),
                         "n_points": g.get("n_points")})
        if covered != set(range(len(endpoints))):
            raise ValueError("replica groups do not cover every endpoint")
        #: immutable per-slab identity (row range); the member lists are
        #: the mutable part
        self.slab_meta = meta
        self._lock = threading.Lock()
        # the slab->members table grows at runtime (bind_replica) while
        # dispatch threads read it and /stats scrapes snapshot it; the
        # spread counters are bumped per pick from dispatch/completion
        # threads — all access under _lock (lskcheck-proven)
        self._members: guarded_by("_lock") = members
        self.picks: guarded_by("_lock") = {}
        self.rebinds: guarded_by("_lock") = 0

    @property
    def num_slabs(self) -> int:
        return len(self.slab_meta)

    def members(self, slab: int) -> list[int]:
        with self._lock:
            return list(self._members[slab])

    def _usable(self, i: int, penalties, budget) -> bool:
        if (penalties is not None and budget is not None
                and penalties.get(i, 0) > budget):
            return False
        return not self._endpoints[i].health.is_drained()

    def pick(self, slab: int, *, penalties: dict | None = None,
             budget: int | None = None) -> int | None:
        """Choose a live member endpoint index for one sub-batch, or None
        when the slab has no usable replica.

        Order of preference (lexicographic key, smallest wins): per-batch
        failure penalty, lifecycle state (healthy < suspect), cumulative
        drained seconds (whole-second buckets — a historically flaky
        replica loses ties), observed p50 latency (ms buckets), pick
        count (the spreader: least-picked wins among equals), then the
        deterministic ``crc32(seed, slab, url)`` tie-break. No RNG and no
        wall-clock, so the sequence is a pure function of the health
        state and the pick history."""
        with self._lock:
            cand = list(self._members[slab])
            picks = dict(self.picks)
        best, best_key = None, None
        for i in cand:
            if not self._usable(i, penalties, budget):
                continue
            ep = self._endpoints[i]
            h = ep.health.snapshot()
            lat = ep.latency.percentile(50.0)
            key = ((penalties or {}).get(i, 0),
                   STATE_CODE[h["state"]],
                   int(h["drained_seconds_total"]),
                   int(lat * 1e3) if np.isfinite(lat) else 0,
                   picks.get(i, 0),
                   zlib.crc32(f"{self.seed}:{slab}:{ep.url}".encode()))
            if best_key is None or key < best_key:
                best, best_key = i, key
        if best is not None:
            with self._lock:
                self.picks[best] = self.picks.get(best, 0) + 1
        return best

    def slab_live_mask(self, *, penalties: dict | None = None,
                       budget: int | None = None) -> np.ndarray:
        """bool[S]: slab has at least one usable replica. With
        ``penalties``/``budget`` the mask additionally excludes members
        over their per-batch failure budget — the same predicate ``pick``
        uses, so a True slab always yields a pick (modulo races, which
        the wave loop's no-progress escape covers)."""
        with self._lock:
            members = [list(m) for m in self._members]
        out = np.zeros(len(members), bool)
        for s, idxs in enumerate(members):
            out[s] = any(self._usable(i, penalties, budget) for i in idxs)
        return out

    def live_counts(self) -> list[int]:
        with self._lock:
            members = [list(m) for m in self._members]
        return [sum(1 for i in idxs
                    if not self._endpoints[i].health.is_drained())
                for idxs in members]

    def rebind(self, slab: int, ep_index: int) -> None:
        """Add a (handoff-validated) endpoint as a member of ``slab`` —
        the runtime re-bind of a slab's endpoint set. Only the replica
        manager calls this, after the fingerprint gate."""
        with self._lock:
            if ep_index not in self._members[slab]:
                self._members[slab].append(ep_index)
                self.rebinds += 1

    def stats(self) -> dict:
        with self._lock:
            members = [list(m) for m in self._members]
            picks = dict(self.picks)
            rebinds = self.rebinds
        per_slab = []
        spread = {}
        for s, idxs in enumerate(members):
            live = sum(1 for i in idxs
                       if not self._endpoints[i].health.is_drained())
            row = {"slab": s,
                   "row_offset": self.slab_meta[s]["row_offset"],
                   "n_points": self.slab_meta[s]["n_points"],
                   "members": [self._endpoints[i].url for i in idxs],
                   "live": live,
                   "picks": {self._endpoints[i].url: picks.get(i, 0)
                             for i in idxs}}
            per_slab.append(row)
            spread.update(row["picks"])
        return {"num_slabs": len(members), "rebinds": rebinds,
                "per_slab": per_slab, "spread": spread}


# ------------------------------------------------------- grouping/validation


def group_routed_hosts(host_urls: list[str], stats: list[dict],
                       fingerprints: dict) -> dict:
    """Group routed hosts into replica slabs and validate the groups.

    Hosts with the same ``(row_offset, n_points)`` are replicas of one
    slab; replicas must carry IDENTICAL routed fingerprints (config +
    shard bounds — they claim the same rows, so any divergence means one
    of them would serve different bytes) and the slab groups must tile
    [0, N) with no gap or overlap, exactly the PR-7 single-copy rule.
    Pure function of the scraped /stats (testable without HTTP); returns
    ``{"slabs", "host_urls" (slab-major), "bounds_hosts",
    "slab_fingerprints", "n_points"}``.
    """
    groups: dict[tuple, list[int]] = {}
    for i, e in enumerate(stats):
        key = (int(e.get("row_offset", 0)), int(e.get("n_points", 0)))
        groups.setdefault(key, []).append(i)
    offset = 0
    slabs, bounds_hosts, slab_fps, urls_out = [], [], [], []
    for (off, npts), idxs in sorted(groups.items()):
        if off != offset:
            raise ValueError(
                f"routed host slabs do not tile the index: slab at row "
                f"{off} (host {host_urls[idxs[0]]}), expected {offset} — "
                "a gap or overlap would drop or double-count neighbors")
        fp0 = fingerprints[host_urls[idxs[0]]]
        for j in idxs[1:]:
            fpj = fingerprints[host_urls[j]]
            if fpj != fp0:
                diff = sorted(k for k in fp0
                              if fp0.get(k) != fpj.get(k))
                raise ValueError(
                    f"replica mismatch for slab rows [{off}:{off + npts}): "
                    f"host {host_urls[j]} differs from "
                    f"{host_urls[idxs[0]]} on {diff} — replicas must be "
                    "byte-interchangeable (same config, same shard bounds)")
        urls = [host_urls[j] for j in idxs]
        slabs.append({"row_offset": off, "n_points": npts, "urls": urls})
        bounds_hosts.append({"row_offset": off, "n_points": npts,
                             "shards": stats[idxs[0]]["shard_bounds"]})
        slab_fps.append(fp0)
        urls_out.extend(urls)
        offset += npts
    return {"slabs": slabs, "host_urls": urls_out,
            "bounds_hosts": bounds_hosts,
            "slab_fingerprints": slab_fps, "n_points": offset}


# ------------------------------------------------------------ slab transfer


def pull_slab_rows(url: str, *, timeout_s: float = 120.0,
                   wire: str = "d16", begin: int | None = None,
                   end: int | None = None,
                   throttle_bps: float | None = None):
    """Fetch a surviving replica's host-side slab rows
    (``GET /slab_rows``). Returns ``(points f32[n, dim], row_offset)``;
    raises on a torn transfer (short body / frame, fingerprint mismatch)
    so a half-copied or corrupt slab can never be adopted.

    ``wire`` asks for the chunk-streamed codec path (``d16`` delta codec
    or chunked ``f32``); an OLD host ignores the query string and answers
    the legacy single-shot body with no ``X-Knn-Wire`` header — the
    response header, not the request, selects the parse, so mixed pods
    interop with zero config. New-style responses are verified against
    the host's crc32 fingerprint of the raw f32 bytes after decode (the
    d16 transform is lossless; this catches torn/corrupt transport).
    ``begin``/``end`` pull a row sub-range (cold-tier reads);
    ``throttle_bps`` paces the pull to a byte budget (bench use:
    emulated DCN bandwidth — decode overlaps the pacing sleep exactly
    like real transfer overlaps decode)."""
    q = [("wire", wire)] if wire in ("d16", "f32") else []
    if begin is not None:
        q.append(("begin", str(int(begin))))
    if end is not None:
        q.append(("end", str(int(end))))
    qs = ("?" + "&".join(f"{k}={v}" for k, v in q)) if q else ""
    t0 = time.perf_counter()
    with urllib.request.urlopen(url.rstrip("/") + "/slab_rows" + qs,
                                timeout=timeout_s) as r:
        rows = int(r.headers.get("X-Knn-Rows", "-1"))
        dim = int(r.headers.get("X-Knn-Dim", "0"))
        off = int(r.headers.get("X-Knn-Row-Offset", "-1"))
        codec = r.headers.get("X-Knn-Wire")
        if codec is None:
            # legacy host: single raw f32 body (pre-codec binary)
            payload = r.read()
            if (rows < 0 or off < 0 or dim < 1
                    or len(payload) != 4 * rows * dim):
                raise ValueError(
                    f"torn slab transfer from {url}: rows={rows} "
                    f"dim={dim} bytes={len(payload)}")
            return (np.frombuffer(payload, "<f4").reshape(rows, dim)
                    .copy(), off)
        if rows < 0 or off < 0 or dim < 1:
            raise ValueError(f"torn slab transfer from {url}: "
                             f"rows={rows} dim={dim}")
        want_crc = int(r.headers.get("X-Knn-Fingerprint", "0"), 16)
        parts = []
        crc = 0
        wire_bytes = 0
        try:
            for nrows, payload in read_frames(r.read, rows):
                pts = decode_slab_chunk(payload, nrows, dim)
                parts.append(pts)
                crc = zlib.crc32(memoryview(pts).cast("B"), crc)
                wire_bytes += 8 + len(payload)
                if throttle_bps:
                    # pace AFTER decode against the cumulative byte
                    # deadline: decode rides inside the bandwidth gap,
                    # the way real transfer overlaps decode
                    target = t0 + wire_bytes / float(throttle_bps)
                    delay = target - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
        except WireError as e:
            raise ValueError(f"torn slab transfer from {url}: {e}") from e
        r.read()  # drain the terminal chunk so the close is graceful
        if crc != want_crc:
            raise ValueError(
                f"slab fingerprint mismatch from {url}: decoded rows "
                f"crc32 {crc:08x} != advertised {want_crc:08x}")
    out = (np.concatenate(parts, axis=0) if parts
           else np.zeros((0, dim), "<f4"))
    return np.ascontiguousarray(out, "<f4"), off


def _http_adopt(url: str, req: dict, timeout_s: float) -> dict:
    r = urllib.request.Request(
        url.rstrip("/") + "/adopt_slab", data=json.dumps(req).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


# ---------------------------------------------------------- handoff manager


class ReplicaManager:
    """The slab-handoff supervisor, driven from ``HealthMonitor.check_once``.

    ``check_once(now)`` is the whole brain (the PR-8 monitor discipline):
    it first advances in-flight adoptions — probing the adopting standby,
    and on readiness scraping its /stats and holding its fingerprint
    against the slab's pod-table entry before ``fanout.bind_replica``
    brings it into rotation — then starts a new handoff for any slab
    whose live-replica count sits below ``handoff_floor`` and has no
    adoption already in flight. Standbys are single-shot: a bound standby
    IS now a replica (supervised by the health monitor like any other),
    and a failed/rejected one stays out with the reason in
    ``last_error``.
    """

    def __init__(self, fanout, *, slabs: list[dict],
                 slab_fingerprints: list[dict],
                 standbys: list[str] | None = None,
                 handoff_floor: int = 1, adopt_timeout_s: float = 600.0,
                 probe_timeout_s: float = 5.0, probe_fn=None, stats_fn=None,
                 adopt_fn=None, fingerprint_registry: dict | None = None,
                 clock=time.monotonic):
        from mpi_cuda_largescaleknn_tpu.serve.health import (
            _http_probe,
            _http_stats,
        )

        self.fanout = fanout
        self.slabs = [dict(s) for s in slabs]
        self.slab_fingerprints = list(slab_fingerprints)
        self.handoff_floor = int(handoff_floor)
        self.adopt_timeout_s = float(adopt_timeout_s)
        #: the monitor's url -> fingerprint table: a bound standby is
        #: registered here so its own later drain/rejoin cycles get the
        #: same fingerprint gate as an original member
        self.fingerprint_registry = fingerprint_registry
        self._probe = probe_fn or (
            lambda url: _http_probe(url, probe_timeout_s))
        self._stats = stats_fn or (
            lambda url: _http_stats(url, probe_timeout_s))
        self._adopt = adopt_fn or (
            lambda url, req: _http_adopt(url, req, probe_timeout_s))
        self._clock = clock
        self._lock = threading.Lock()
        # standby records and handoff counters are mutated from the
        # monitor thread and snapshotted by /stats scrapes — all access
        # under _lock (lskcheck-proven)
        self.standbys: guarded_by("_lock") = [
            {"url": u, "state": "idle", "slab": None, "last_error": None,
             "t0": None} for u in (standbys or [])]
        self.inflight: guarded_by("_lock") = set()
        self.handoffs: guarded_by("_lock") = 0
        self.handoff_failures: guarded_by("_lock") = 0
        self.handoff_rejections: guarded_by("_lock") = 0
        self.handoff_seconds_total: guarded_by("_lock") = 0.0
        self.starved: guarded_by("_lock") = 0

    # ------------------------------------------------------------------ brain

    def check_once(self, now: float | None = None) -> None:
        now = now if now is not None else self._clock()
        with self._lock:
            adopting = [dict(sb) for sb in self.standbys
                        if sb["state"] == "adopting"]
        for sb in adopting:
            self._check_adoption(sb, now)
        live = self.fanout.replicas.live_counts()
        for slab, count in enumerate(live):
            if count >= self.handoff_floor:
                continue
            with self._lock:
                if slab in self.inflight:
                    continue
                idle = next((sb for sb in self.standbys
                             if sb["state"] == "idle"), None)
                if idle is None:
                    self.starved += 1
                    continue
                idle["state"] = "adopting"
                idle["slab"] = slab
                idle["t0"] = now
                idle["last_error"] = None
                url = idle["url"]
                self.inflight.add(slab)
            self._start_handoff(url, slab)

    def _start_handoff(self, standby_url: str, slab: int) -> None:
        src = None
        for i in self.fanout.replicas.members(slab):
            ep = self.fanout.endpoints[i]
            if not ep.health.is_drained():
                src = ep.url
                break
        meta = self.slabs[slab]
        req = {"host_id": slab, "num_hosts": len(self.slabs),
               "row_offset": meta["row_offset"],
               "n_points": meta["n_points"]}
        if src is not None:
            req["source_url"] = src
        try:
            self._adopt(standby_url, req)
        except Exception as e:  # noqa: BLE001 - recorded, handoff retried
            self._fail_standby(standby_url, slab,
                               f"adopt request failed: "
                               f"{type(e).__name__}: {e}")

    def _check_adoption(self, sb: dict, now: float) -> None:
        url, slab = sb["url"], sb["slab"]
        ok, info = self._probe(url)
        if ok:
            try:
                stats = self._stats(url)
                fp = host_fingerprint(stats.get("engine", {}), "bounds")
            except Exception as e:  # noqa: BLE001 - recorded, not swallowed
                self._fail_standby(url, slab,
                                   f"adopted-slab stats scrape failed: "
                                   f"{type(e).__name__}: {e}")
                return
            want = self.slab_fingerprints[slab]
            if want is not None and fp != want:
                diff = sorted(k for k in want if fp.get(k) != want.get(k))
                self._fail_standby(
                    url, slab,
                    f"handoff rejected: fingerprint mismatch on {diff} — "
                    "the adopted slab does not serve the rows/config the "
                    "pod table was built from", rejected=True)
                return
            self.fanout.bind_replica(slab, url)
            # register the adoptee's wire caps (the /stats ROOT block)
            # so the fan-out negotiates its codec like any startup host;
            # an old binary has no caps and negotiates f32
            negotiator = getattr(self.fanout, "negotiator", None)
            if negotiator is not None:
                negotiator.set_caps(url, stats.get("wire"))
            if self.fingerprint_registry is not None:
                self.fingerprint_registry[url] = (want if want is not None
                                                  else fp)
            with self._lock:
                for x in self.standbys:
                    if x["url"] == url:
                        x["state"] = "bound"
                self.inflight.discard(slab)
                self.handoffs += 1
                if sb["t0"] is not None:
                    self.handoff_seconds_total += max(0.0, now - sb["t0"])
            return
        if info.get("status") == "adopt-failed":
            self._fail_standby(url, slab,
                               info.get("adopt_error") or "adoption failed")
        elif sb["t0"] is not None and now - sb["t0"] > self.adopt_timeout_s:
            self._fail_standby(url, slab,
                               f"adoption timed out after "
                               f"{self.adopt_timeout_s:.0f}s")
        # else: still materializing/warming — check again next cycle

    def _fail_standby(self, url: str, slab: int, msg: str,
                      rejected: bool = False) -> None:
        with self._lock:
            for x in self.standbys:
                if x["url"] == url:
                    x["state"] = "failed"
                    x["last_error"] = msg
            self.inflight.discard(slab)
            if rejected:
                self.handoff_rejections += 1
            else:
                self.handoff_failures += 1

    def stats(self) -> dict:
        live = self.fanout.replicas.live_counts()
        with self._lock:
            return {
                "handoff_floor": self.handoff_floor,
                "slab_live": list(live),
                "standbys": [dict(sb) for sb in self.standbys],
                "inflight_slabs": sorted(self.inflight),
                "handoffs": self.handoffs,
                "handoff_failures": self.handoff_failures,
                "handoff_rejections": self.handoff_rejections,
                "handoff_seconds_total": round(self.handoff_seconds_total,
                                               3),
                "starved": self.starved,
            }
