"""Multi-index tenancy: one slab pool, many indexes.

Millions of users means many DATASETS, not one. This module generalizes
the serving stack from "slabs of one index" to "(tenant, slab) pages of
many indexes" behind one shared byte budget:

- ``TenantRegistry`` maps tenant id -> that tenant's engine view; the
  HTTP surface resolves ``/v1/<tenant>/knn`` through it (unknown tenant
  = 404, never a silent fallthrough to someone else's index).
- ``MultiTenantEngine`` builds one ``SlabPool`` (serve/slabpool.py) and
  registers every tenant's ``SlabSource`` + engine factory into it, so
  all tenants compete for ONE device byte budget and ONE host tier: hot
  tenants naturally occupy the device tier, cold tenants fall to
  host-RAM/mmap and ride the existing promotion + d16 cold-read path.
  Per-tenant pin/prefetch/stall accounting rides the pool's tuple keys.
- Shared-shape AOT reuse: every tenant's slab engines pad to ONE shape
  class (the max per-shard slab rows across ALL tenants) and share one
  ``ExecutableCache`` — the TPU-KNN lesson (arXiv:2206.14286: peak MXU
  throughput comes from a few hot compiled programs) applied across
  tenants, so tenant count never becomes compile count (gated by test).
- ``TenantQuotas`` slices the PR-1 row-budget admission controller per
  tenant (the PANDA-style isolation of concurrent query streams,
  arXiv:1607.08220): one hot tenant cannot starve the rest; an
  over-quota request gets the same 429 + Retry-After contract as global
  overload.

Exactness contract per tenant: each tenant's answers are bit-identical
to a single-tenant ``StreamingKnnEngine`` over the same points at every
budget — the shared pool changes WHEN a slab is resident, never what its
engine computes, and the per-tenant fold is the same commutative
candidate merge. A cold tenant STALLS (counted per tenant), it is never
served from another tenant's rows.
"""

from __future__ import annotations

import threading
import time

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by
from mpi_cuda_largescaleknn_tpu.obs.timers import PhaseTimers
from mpi_cuda_largescaleknn_tpu.serve.admission import (
    AdmissionController,
    OverloadError,
)
from mpi_cuda_largescaleknn_tpu.serve.slabpool import (
    SlabPool,
    SlabSource,
    StreamingKnnEngine,
)

#: the tenant legacy single-index URLs (``POST /knn``) resolve to when a
#: multi-tenant server has no explicit default
DEFAULT_TENANT = "default"


class UnknownTenantError(KeyError):
    """No such tenant — the HTTP layer maps this to 404."""


class TenantSpec:
    """One tenant's index source + slab layout (immutable config)."""

    __slots__ = ("name", "path", "points", "url", "num_slabs")

    def __init__(self, name: str, *, path: str | None = None,
                 points=None, url: str | None = None, num_slabs: int = 1):
        if not name or "/" in name:
            raise ValueError(f"bad tenant name {name!r} (non-empty, "
                             f"no '/' — it rides in URLs)")
        self.name = name
        self.path = path
        self.points = points
        self.url = url
        self.num_slabs = int(num_slabs)


class TenantRegistry:
    """tenant id -> engine view, the HTTP surface's routing table.

    Registration happens at startup (before serving), lookups on every
    request — the lock keeps the pair safe if a future PR adds live
    tenant onboarding, and lets lskcheck prove the discipline now."""

    def __init__(self):
        self._lock = threading.Lock()
        # tenant name -> engine view; shared between the registration
        # path and every handler thread's resolve()
        self._engines: guarded_by("_lock") = {}

    def add(self, name: str, engine) -> None:
        with self._lock:
            self._engines[name] = engine

    def get(self, name: str):
        """The tenant's engine view; raises ``UnknownTenantError``."""
        with self._lock:
            if name in self._engines:
                return self._engines[name]
        raise UnknownTenantError(name)

    def names(self) -> list:
        with self._lock:
            return sorted(self._engines)

    def __contains__(self, name) -> bool:
        with self._lock:
            return name in self._engines

    def __len__(self) -> int:
        with self._lock:
            return len(self._engines)


class TenantQuotas:
    """Per-tenant row-budget slices over one ``AdmissionController``.

    The global controller still caps TOTAL queued+in-flight rows (it is
    always consulted second); this layer additionally caps each tenant's
    share so one hot tenant cannot occupy the whole queue. ``quota_rows
    <= 0`` means unsliced — that tenant only sees the global cap. An
    over-quota request raises ``OverloadError`` with the same
    Retry-After contract as global overload (HTTP 429)."""

    def __init__(self, controller: AdmissionController, *,
                 default_quota_rows: int = 0, quotas: dict | None = None,
                 retry_after_s: float = 0.05):
        self.controller = controller
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        # per-tenant reservation state, shared across handler threads:
        # quota table, in-flight rows, and rejection counters
        self._quota: guarded_by("_lock") = dict(quotas or {})
        self._default_quota: guarded_by("_lock") = int(default_quota_rows)
        self._inflight: guarded_by("_lock") = {}
        self._rejected: guarded_by("_lock") = {}

    def set_quota(self, tenant: str, rows: int) -> None:
        with self._lock:
            self._quota[tenant] = int(rows)

    def quota(self, tenant: str) -> int:
        with self._lock:
            return int(self._quota.get(tenant, self._default_quota))

    def admit(self, tenant: str, n_rows: int) -> None:
        """Reserve ``n_rows`` against the tenant's slice, then against
        the global cap (rolled back if the global cap rejects). Callers
        MUST pair with ``release`` (use ``admitted_rows``)."""
        with self._lock:
            q = int(self._quota.get(tenant, self._default_quota))
            used = self._inflight.get(tenant, 0)
            if q > 0 and used + n_rows > q:
                self._rejected[tenant] = self._rejected.get(tenant, 0) + 1
                raise OverloadError(
                    f"tenant '{tenant}' over quota ({used}/{q} rows "
                    f"in flight)", retry_after_s=self.retry_after_s)
            self._inflight[tenant] = used + n_rows
        try:
            self.controller.admit(n_rows)
        except BaseException:
            with self._lock:
                self._inflight[tenant] -= n_rows
            raise

    def release(self, tenant: str, n_rows: int) -> None:
        self.controller.release(n_rows)
        with self._lock:
            self._inflight[tenant] = self._inflight.get(tenant, 0) - n_rows

    def admitted_rows(self, tenant: str, n_rows: int):
        """Context manager form of admit/release."""
        return _TenantAdmitted(self, tenant, n_rows)

    def stats(self) -> dict:
        with self._lock:
            tenants = sorted(set(self._quota) | set(self._inflight)
                             | set(self._rejected))
            return {
                "default_quota_rows": self._default_quota,
                "tenants": {
                    t: {"quota_rows": int(self._quota.get(
                            t, self._default_quota)),
                        "inflight_rows": self._inflight.get(t, 0),
                        "rejected": self._rejected.get(t, 0)}
                    for t in tenants},
            }


class _TenantAdmitted:
    def __init__(self, quotas: TenantQuotas, tenant: str, n_rows: int):
        self._q = quotas
        self._tenant = tenant
        self._n = n_rows

    def __enter__(self):
        self._q.admit(self._tenant, self._n)
        return self

    def __exit__(self, *exc):
        self._q.release(self._tenant, self._n)
        return False


class _TenantHandle:
    """A dispatched multi-tenant batch: the tenant namespace plus the
    underlying streaming handle. Forwards the attributes the pipeline's
    degradation replay reads (``queries``/``engine_name``/``plan``) —
    the inner handle is ``__slots__``-bound, so the tenant tag lives
    here instead."""

    __slots__ = ("tenant", "inner")

    def __init__(self, tenant: str, inner):
        self.tenant = tenant
        self.inner = inner

    @property
    def queries(self):
        return self.inner.queries

    @property
    def engine_name(self):
        return self.inner.engine_name

    @property
    def plan(self):
        return self.inner.plan

    @property
    def n(self):
        return self.inner.n


class MultiTenantEngine:
    """Engine facade over N tenants sharing one ``SlabPool`` + AOT cache.

    Speaks the same ``dispatch``/``complete``/``query`` contract as the
    single-index engines with an added ``tenant=`` kwarg (None resolves
    to ``default_tenant`` — the legacy ``/knn`` route). The batcher,
    graceful wrapper, and HTTP server drive it like any other engine;
    per-tenant views are full ``StreamingKnnEngine`` instances sharing
    the pool, timers, and executable cache, so every single-tenant
    behavior (routing, escalation, recall plans, degradation) holds
    per tenant unchanged."""

    def __init__(self, specs, *, k: int, mesh=None,
                 device_slab_budget: int = 0, host_pool_slabs: int = 0,
                 host_pool_bytes: int = 0, prefetch_depth: int = 1,
                 faults=None, default_tenant: str | None = None,
                 skip_cold_stall_limit: float = 0.25,
                 clock=time.perf_counter, **engine_kw):
        from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, get_mesh
        from mpi_cuda_largescaleknn_tpu.serve.engine import ExecutableCache

        specs = list(specs)
        if not specs:
            raise ValueError("need at least one TenantSpec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in {names}")
        self.mesh = mesh if mesh is not None else get_mesh(None)
        num_shards = self.mesh.shape[AXIS]
        # cold sources first: the shared shape class must cover every
        # tenant's largest slab BEFORE any engine compiles, or tenants
        # would land in different executable-cache classes
        sources = {
            s.name: SlabSource(path=s.path, points=s.points, url=s.url,
                               num_slabs=s.num_slabs)
            for s in specs}
        pad = max(-(-max(e - b for b, e in src.bounds) // num_shards)
                  for src in sources.values())
        self.slab_pool = SlabPool(
            device_budget_bytes=device_slab_budget,
            host_pool_slabs=host_pool_slabs,
            host_pool_bytes=host_pool_bytes, faults=faults, clock=clock)
        self._exec_cache = ExecutableCache()
        self.timers = PhaseTimers()
        self.tenants = TenantRegistry()
        self._names = list(names)
        self.default_tenant = (default_tenant if default_tenant is not None
                               else names[0])
        if self.default_tenant not in names:
            raise ValueError(f"default tenant {self.default_tenant!r} "
                             f"not in {names}")
        for s in specs:
            view = StreamingKnnEngine(
                source=sources[s.name], k=k, mesh=self.mesh,
                prefetch_depth=prefetch_depth, pool=self.slab_pool,
                tenant=s.name, shared_exec_cache=self._exec_cache,
                pad_shard_rows=pad, timers=self.timers,
                skip_cold_stall_limit=skip_cold_stall_limit,
                clock=clock, **engine_kw)
            self.tenants.add(s.name, view)
        self.n_points = sum(self.tenants.get(n).n_points for n in names)
        self.device_slab_budget = int(device_slab_budget)

    # ------------------------------------------------------------- resolution

    def resolve(self, tenant: str | None):
        """(name, engine view) for a request's tenant (None = default);
        raises ``UnknownTenantError`` for strangers."""
        name = tenant if tenant is not None else self.default_tenant
        return name, self.tenants.get(name)

    def _default_engine(self):
        return self.tenants.get(self.default_tenant)

    def __getattr__(self, name):
        # the long tail of read-only engine surface (dim, k, max_batch,
        # shape_buckets, score_dtype, ...) — every tenant view shares the
        # same knobs, so the default tenant's answer is the pool's
        if name.startswith("_") or name == "tenants":
            raise AttributeError(name)
        return getattr(self._default_engine(), name)

    # -------------------------------------------------------------- query API

    def dispatch(self, queries, plan=None, tenant: str | None = None,
                 seed_radius=None):
        name, eng = self.resolve(tenant)
        kw = {} if seed_radius is None else {"seed_radius": seed_radius}
        return _TenantHandle(name, eng.dispatch(queries, plan=plan, **kw))

    def complete(self, handle: _TenantHandle):
        return self.tenants.get(handle.tenant).complete(handle.inner)

    def query(self, queries, plan=None, tenant: str | None = None,
              seed_radius=None):
        return self.complete(self.dispatch(queries, plan=plan,
                                           tenant=tenant,
                                           seed_radius=seed_radius))

    def prefetch_hint(self, queries, tenant: str | None = None) -> None:
        _name, eng = self.resolve(tenant)
        eng.prefetch_hint(queries)

    # ------------------------------------------------------------ engine mgmt

    def warmup(self) -> dict:
        """Compile every shape bucket once via the DEFAULT tenant (into
        the shared cache), then warm the remaining tenants — their slab
        engines reuse the same executables, so warmup cost is one
        compile pass plus data motion (the compile-count-flat gate)."""
        info = {"tenants": {}}
        order = [self.default_tenant] + [n for n in self._names
                                         if n != self.default_tenant]
        for name in order:
            info["tenants"][name] = self.tenants.get(name).warmup()
        info["compile_count"] = self._exec_cache.stats()["compiles"]
        return info

    def can_degrade(self) -> bool:
        return self._default_engine().can_degrade()

    def degrade(self, reason: str) -> None:
        for name in self._names:
            eng = self.tenants.get(name)
            if eng.can_degrade():
                eng.degrade(reason)

    def set_launch_workers(self, n: int) -> None:
        for name in self._names:
            self.tenants.get(name).set_launch_workers(n)

    def close(self) -> None:
        # tenant views share the pool (none owns it) — close it once here
        self.slab_pool.close()

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict:
        """The default tenant's full stats dict (the /stats "engine"
        block keeps its single-tenant shape) with pool-wide n_points and
        a per-tenant namespace — per-tenant residency/stall shares from
        the pool plus each view's index geometry."""
        out = self._default_engine().stats()
        out["n_points"] = self.n_points
        out["default_tenant"] = self.default_tenant
        pool_tenants = out.get("slab_pool", {}).get("tenants", {})
        per = {}
        for name in self._names:
            eng = self.tenants.get(name)
            per[name] = dict(
                pool_tenants.get(name, {}),
                n_points=eng.n_points, num_slabs=eng.num_slabs,
                k=eng.k, dim=eng.dim)
        out["tenants"] = per
        return out
