"""Deterministic fault injection for the serving stack.

The fault-tolerance machinery (serve/health.py drain/rejoin, the routed
fan-out's retry/degrade paths) is only trustworthy if every failure mode it
claims to handle can be produced ON DEMAND, deterministically, in-process —
no real process kills, no flaky sleep races. This module is that layer: a
``FaultInjector`` holds an ordered list of ``FaultSpec`` rules and the HTTP
handlers consult it once per request (``JsonHttpHandler._apply_fault``).
A matching rule makes the handler

- ``latency``  : sleep ``delay_s`` before handling normally (slow host),
- ``error``    : answer ``code`` (default 500) without touching the engine,
- ``drop``     : close the connection without writing a response byte
                 (process-kill stand-in: the client sees a reset/EOF),
- ``close_mid_body``: send 200 headers claiming a body, write a short
                 prefix, close (torn transfer — exercises the client's
                 malformed-payload path).

Determinism: each spec carries its own ``random.Random(seed)`` and fires by
(a) a skip count ``after``, (b) a fire budget ``n`` (-1 = unlimited), and
(c) probability ``p`` drawn from that seeded stream — so for a given
sequence of matching requests the decision sequence is a pure function of
the spec. Tests and the chaos bench drive injectors either programmatically,
via the ``KNN_FAULTS`` env var at server start, or at runtime through the
host servers' ``POST /faults`` admin endpoint (always exempt from
injection).

Spec string grammar (env var / admin endpoint)::

    spec      := rule (';' rule)*
    rule      := op [':' kv (',' kv)*]
    op        := 'latency' | 'error' | 'drop' | 'close_mid_body'
    kv        := key '=' value      # path=/route_knn p=0.5 n=3 after=10
                                    # code=503 delay_s=0.2 seed=7
                                    # method=POST

``path`` is a substring match against the request path ('' matches all);
``method`` restricts a rule to one HTTP verb ('' matches all) — e.g.
``drop:path=/route_knn,method=POST`` kills the serving path while
``GET /healthz`` keeps answering, the probes-lie failure mode the routed
fan-out's per-batch failure budget exists for (serve/frontend.py).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from mpi_cuda_largescaleknn_tpu.analysis import guarded_by

FAULT_OPS = ("latency", "error", "drop", "close_mid_body")
FAULTS_ENV = "KNN_FAULTS"


class FaultSpec:
    """One injection rule + its deterministic firing state."""

    def __init__(self, op: str, *, path: str = "", method: str = "",
                 p: float = 1.0, n: int = -1, after: int = 0,
                 code: int = 500, delay_s: float = 0.05, seed: int = 0):
        if op not in FAULT_OPS:
            raise ValueError(f"unknown fault op {op!r} (one of {FAULT_OPS})")
        self.op = op
        self.path = str(path)
        self.method = str(method).upper()
        self.p = float(p)
        self.n = int(n)
        self.after = int(after)
        self.code = int(code)
        self.delay_s = float(delay_s)
        self.seed = int(seed)
        # firing state (under the injector's lock)
        self.seen = 0
        self.fires = 0
        self._rng = random.Random(self.seed)

    def config(self) -> dict:
        return {"op": self.op, "path": self.path, "method": self.method,
                "p": self.p, "n": self.n,
                "after": self.after, "code": self.code,
                "delay_s": self.delay_s, "seed": self.seed,
                "seen": self.seen, "fires": self.fires}


def parse_fault_specs(text: str) -> list[FaultSpec]:
    """Parse the ``op:key=val,...;op2:...`` grammar into specs.

    An empty/whitespace string parses to no specs (= injection off)."""
    specs = []
    for rule in (text or "").split(";"):
        rule = rule.strip()
        if not rule:
            continue
        op, _, kvs = rule.partition(":")
        kwargs: dict = {}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, _, val = kv.partition("=")
            key = key.strip()
            if key in ("path", "method"):
                kwargs[key] = val.strip()
            elif key in ("n", "after", "code", "seed"):
                kwargs[key] = int(val)
            elif key in ("p", "delay_s"):
                kwargs[key] = float(val)
            else:
                raise ValueError(f"unknown fault spec key {key!r}")
        specs.append(FaultSpec(op.strip(), **kwargs))
    return specs


class FaultInjector:
    """Ordered fault rules consulted once per HTTP request.

    ``decide(path)`` returns the first matching spec that fires (or None);
    thread-safe, and deterministic for a given request order. ``set_specs``
    replaces the whole rule set atomically (the admin-endpoint contract:
    a POST replaces, an empty POST clears)."""

    def __init__(self, specs: list[FaultSpec] | None = None):
        self._lock = threading.Lock()
        # the spec list AND each spec's firing state (seen/fires/_rng) are
        # mutated under this lock — decide() is the only mutator and
        # config() the only reader of spec counters, both locked below
        self._specs: guarded_by("_lock") = list(specs or [])

    @classmethod
    def from_env(cls, env_var: str = FAULTS_ENV) -> "FaultInjector":
        return cls(parse_fault_specs(os.environ.get(env_var, "")))

    def set_specs(self, specs: str | list[FaultSpec]) -> None:
        if isinstance(specs, str):
            specs = parse_fault_specs(specs)
        with self._lock:
            self._specs = list(specs)

    def clear(self) -> None:
        self.set_specs([])

    def active(self) -> bool:
        with self._lock:
            return bool(self._specs)

    def decide(self, path: str, method: str = "") -> FaultSpec | None:
        """First matching spec that fires for this request, else None.
        ``method`` (the HTTP verb; '' in a spec matches all) is part of
        the match, BEFORE the skip/budget counters — a method-filtered
        rule only counts the requests it could fire on."""
        with self._lock:
            for spec in self._specs:
                if spec.path and spec.path not in path:
                    continue
                if spec.method and spec.method != method.upper():
                    continue
                spec.seen += 1
                if spec.seen <= spec.after:
                    continue
                if spec.n >= 0 and spec.fires >= spec.n:
                    continue
                if spec.p < 1.0 and spec._rng.random() >= spec.p:
                    continue
                spec.fires += 1
                return spec
        return None

    def config(self) -> list[dict]:
        with self._lock:
            return [s.config() for s in self._specs]


def apply_http_fault(handler, spec: FaultSpec | None) -> bool:
    """Apply a fired spec to a BaseHTTPRequestHandler-style handler.

    Returns True when the fault CONSUMED the request (the handler must not
    write its normal response); ``latency`` only delays and returns False.
    """
    if spec is None:
        return False
    if spec.op == "latency":
        time.sleep(spec.delay_s)
        return False
    if spec.op == "error":
        body = json.dumps({"error": "injected-fault",
                           "fault": spec.op}).encode()
        handler.send_response(spec.code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        # the request body was never read: close so the unread bytes can't
        # poison a kept-alive connection's next request
        handler.send_header("Connection", "close")
        handler.close_connection = True
        handler.end_headers()
        handler.wfile.write(body)
        return True
    if spec.op == "drop":
        # no response bytes at all; closing the socket gives the client a
        # clean connection-level failure (the kill stand-in)
        handler.close_connection = True
        return True
    if spec.op == "close_mid_body":
        handler.send_response(200)
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header("Content-Length", "4096")
        handler.end_headers()
        handler.wfile.write(b"\x00" * 64)  # 64 of the promised 4096
        handler.close_connection = True
        return True
    raise AssertionError(f"unhandled fault op {spec.op!r}")
