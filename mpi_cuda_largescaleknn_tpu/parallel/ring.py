"""Ring exchange engine — the unordered variant's communication core.

TPU-native re-design of the reference's MPI ring
(unorderedDataVariant.cu:173-205): R ranks each hold a tree shard and a set of
stationary queries with persistent candidate heaps; each round every rank
queries the currently-resident shard(s), then rotates them. After a full
sweep every shard has visited every rank and each heap holds the global
top-k. This is the same communication/accumulation shape as ring attention
(stationary Q, rotating K/V, running accumulator) and maps 1:1 onto
``lax.ppermute`` over the ICI ring inside ``shard_map`` — here with TWO
counter-rotating copies per tree (see ``_make_ring_fns``): ICI links are
full-duplex, so both directions carry trees simultaneously and the sweep
takes R//2+1 rounds instead of the reference's R.

Deliberate improvements over the reference (not bugs to replicate):

- The reference serializes each round: ``MPI_Waitall`` completes before the
  kernel launches and ``cudaDeviceSynchronize`` before the next Isend
  (unorderedDataVariant.cu:187-204). Here the next shard's ``ppermute`` is
  issued *before* the current shard's query update and depends only on the
  incoming buffer, so XLA's latency-hiding scheduler overlaps communication
  with compute.
- The reference exchanges per-round point counts as a separate message pair
  (unorderedDataVariant.cu:183-186). Static SPMD shapes make counts
  compile-time constants: every shard is padded to a uniform size with
  sentinel points whose distances are +inf (core/types.py), generalizing the
  reference's own ``N+1`` slack alloc (:156-158) and the prepartitioned
  variant's pad-to-max trick (prePartitionedDataVariant.cu:251-266).
- 64-bit-safe sizing throughout (the reference's ``int`` arithmetic overflows
  beyond ~2^31 bytes of candidates — SURVEY.md appendix).

Two drivers share one set of per-round builders (``_make_ring_fns``): the
fused ``ring_knn`` (whole ring in one ``lax.fori_loop`` — the default) and
the host-stepped ``ring_knn_stepwise`` (one jitted step per round, enabling
checkpoint/resume between rounds).
"""

from __future__ import annotations

import logging
import math
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    BucketedPoints,
    _partition_level,
    choose_buckets,
    coarsen_buckets,
    partition_finalize,
    partition_prep,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import (
    knn_update_tiled,
    warm_start_self,
)
from mpi_cuda_largescaleknn_tpu.ops.traverse import knn_update_tree
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary
from mpi_cuda_largescaleknn_tpu.utils.math import next_pow2


@lru_cache(maxsize=32)  # bounded: chunked drivers with varying chunk shapes
def _partition_smaps(mesh, num_buckets, bucket_size, dim):  # or fresh Mesh
    # objects must not pin compiled programs + device refs forever
    spec = P(AXIS)

    def smap(fn, in_specs, out_specs):
        # pure-XLA programs: vma checking always on (the engines' pallas
        # interpret-mode exemption does not apply here)
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs))

    kw = dict(num_buckets=num_buckets, bucket_size=bucket_size)
    ncols = dim + 2  # D coordinate columns + ids + pos
    prep = smap(partial(partition_prep, **kw), (spec, spec), (spec,) * ncols)
    # num_seg rides replicated so every level reuses the ONE compiled sort
    level = smap(partial(_partition_level, **kw), (spec,) * ncols + (P(),),
                 (spec,) * ncols)
    fin = smap(partial(partition_finalize, **kw), (spec,) * ncols, spec)
    return prep, level, fin


def partition_sharded(points_sharded, ids_sharded, mesh,
                      bucket_size) -> BucketedPoints:
    """Per-shard spatial partition, hoisted OUT of the ring's fused jit.

    Equivalent to ``shard_map(partition_points)`` but compiled as one prep
    program + ONE level program reused for all log2(B) sort passes + one
    finalize — tracing the partition inside the ring jit instead compiles a
    distinct million-row 7-operand sort per level, which dominated the
    1M-point compile time. Returns a BucketedPoints of global sharded
    arrays (leaf i of shard r at row block r*B_local).
    """
    num_shards = mesh.shape[AXIS]
    npad_local = points_sharded.shape[0] // num_shards
    b, s = choose_buckets(npad_local, bucket_size)
    prep, level, fin = _partition_smaps(mesh, b, s,
                                        int(points_sharded.shape[-1]))

    sharding = NamedSharding(mesh, P(AXIS))
    pts = jax.device_put(points_sharded, sharding)
    ids = jax.device_put(ids_sharded, sharding)
    cols = prep(pts, ids)
    for lvl in range(int(math.log2(b))):
        cols = level(*cols, jnp.int32(1 << lvl))
    return fin(*cols)


def _engine_fn(engine: str, query_tile: int, point_tile: int,
               score_dtype: str = "f32"):
    # flat-engine dispatch only; "auto"/"tiled"/"pallas_tiled" take the
    # bucketed data path (_make_ring_fns tiled branch, the q/shard_state
    # branch in demand_knn) before this
    if engine == "bruteforce":
        return partial(knn_update_bruteforce, query_tile=query_tile,
                       point_tile=point_tile, score_dtype=score_dtype)
    if score_dtype != "f32":
        raise ValueError(
            f"engine '{engine}' has no score_dtype='{score_dtype}' path "
            "(MXU scoring exists for bruteforce and the tiled engines)")
    if engine == "tree":
        return knn_update_tree
    if engine == "pallas":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
                knn_update_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas' is unavailable in this build") from e
        return partial(knn_update_pallas, query_tile=query_tile,
                       point_tile=point_tile)
    raise ValueError(f"unknown engine '{engine}'")


def resolve_engine(engine: str) -> str:
    """Map ``auto`` to the fastest engine for the current backend.

    On a real TPU, ``auto`` means the fused Pallas traversal kernel
    (``pallas_tiled``) — the component built for exactly this hardware;
    if its import fails the XLA twin is the clean fallback. Off-TPU,
    ``auto`` stays on the XLA twin (the Pallas kernels would only run in
    interpreter mode there, which is far slower than compiled XLA).
    Explicit engine names are honored unchanged."""
    if engine != "auto":
        return engine
    from mpi_cuda_largescaleknn_tpu.ops.pallas import is_tpu_backend

    if not is_tpu_backend():
        return "tiled"
    try:
        from mpi_cuda_largescaleknn_tpu.ops.pallas import knn_tiled  # noqa: F401
    except ImportError:
        return "tiled"
    return "pallas_tiled"


def resolve_merge(merge: str, num_shards: int) -> str:
    """Resolve the cross-shard top-k merge placement.

    ``device`` keeps the R-way reduction inside the SPMD program
    (``device_merge_final``: an ``all_to_all`` reduce-scatter by default,
    the log2(R) ``ppermute`` tree of ops/candidates.py
    ``tree_merge_candidates`` as the all-reduce form), so the host fetches
    one final [Q, k] result instead of R partial ones; ``host`` fetches all R
    partials and merges them in numpy. ``auto`` picks ``device`` whenever
    the reduction is available — every power-of-two mesh, single- or
    multi-host (the collectives ride the GLOBAL pod-mesh axis either way) —
    and falls back to ``host`` with a logged warning otherwise (recursive
    doubling needs the blocks to tile the axis), so an odd pod shape never
    hard-fails a startup that ``auto`` was supposed to keep portable.
    Results are bit-identical either way (same tie discipline); the choice
    is pure data movement. An explicit ``device`` on a non-power-of-two
    mesh still raises rather than silently degrading.
    """
    if merge == "auto":
        if num_shards & (num_shards - 1) == 0:
            return "device"
        if num_shards > 1:
            logging.getLogger(__name__).warning(
                "merge='auto': mesh of %d shards is not a power of two — "
                "falling back to the host-side merge (the device "
                "reduce-scatter needs the row blocks to tile the axis)",
                num_shards)
        return "host"
    if merge == "device":
        if num_shards & (num_shards - 1):
            raise ValueError(
                f"merge='device' needs a power-of-two shard count, got "
                f"{num_shards} (use merge='auto' to fall back to host)")
        return "device"
    if merge == "host":
        return "host"
    raise ValueError(f"unknown merge mode '{merge}' "
                     "(expected host | device | auto)")


def resolve_query_buckets(query_buckets: int, qpad: int, k: int) -> int:
    """Resolve the serving engine's query-bucket count for one padded batch
    shape (0 = auto). Like ``resolve_bucket_size``, the auto value encodes
    the measured tradeoff core/config.py names: FINE query buckets tighten
    the per-bucket prune radius (each bucket's radius is the max over only
    ITS queries — ops/tiled.py ``_worst2``) and give ``nearest_first_order``
    a tight AABB to schedule against, while buckets below ~k rows shrink
    the [S, k] candidate tile past what the sublane padding and the
    per-bucket schedule overhead repay. Auto therefore targets
    ``next_pow2(max(8, k))`` queries per bucket.

    The result always divides ``qpad`` (both are powers of two) and leaves
    at least 8 rows per bucket; explicit values are rounded up to a power
    of two and clamped into that range. 1 = the single whole-batch bucket
    (the pre-locality serving behavior, and the B=1 baseline of
    ``tools/serve_smoke.py --locality-bench``)."""
    if qpad < 16:
        return 1
    cap = qpad // 8
    if query_buckets < 1:  # auto
        b = qpad // next_pow2(max(8, k))
    else:
        b = next_pow2(query_buckets)
    return max(1, min(b, cap))


def device_merge_final(heap: CandidateState, num_shards: int,
                       via: str = "a2a"):
    """Device-side finale of a replicate-traverse-merge program (inside
    ``shard_map``): reduce the R per-shard candidate states for the SAME
    replicated queries to the global top-k and have each device emit its
    1/R row-slice of the final answer — the stitched global arrays are
    exactly [Q] dists / [Q, k] candidates, so the host fetch shrinks R x
    (the reference materializes once per run for the same reason,
    unorderedDataVariant.cu extractFinalResult; here it is per batch).

    Two reductions, bit-identical outputs:

    - ``a2a`` (default): a reduce-scatter — ONE ``all_to_all`` hands every
      device all R shards' candidate blocks for only ITS 1/R rows
      (shard-major), then a single width-R*k ``top_k`` finishes. ``top_k``
      prefers the lower column at equal (negated) keys, which over
      shard-major columns IS the host merge's stable tie discipline
      (earlier shard, then earlier slot — verified against
      ``np.argsort(kind="stable")`` in tests). Moves (R-1)/R of each
      state once and sorts each row once: less traffic AND less sort work
      than the tree, and ~30x faster on XLA:CPU, whose row-sort emits a
      scalar comparator loop while its TopK is a tuned custom call.
    - ``tree``: the log2(R) ``ppermute`` recursive-doubling all-reduce
      (ops/candidates.py ``tree_merge_candidates``) followed by a slice —
      every device transiently holds the FULL merged state, the all-reduce
      form the multi-host serving level runs on the global pod-mesh axis
      (the mesh decides whether the hops ride ICI or DCN; the program is
      the same either way).

    Returns (dists, dist2, idx) of ``Q // num_shards`` rows; Q must be
    divisible by num_shards (callers pad the batch to a bucket that is).
    Unused outputs are dead-code-eliminated by XLA, so callers that only
    fetch (dists, idx) pay nothing for the dist2 slice.
    """
    from mpi_cuda_largescaleknn_tpu.ops.candidates import (
        tree_merge_candidates,
    )

    rows, k = heap.dist2.shape
    if rows % num_shards:
        raise ValueError(f"{rows} query rows do not tile {num_shards} "
                         "shards (pad the batch to a multiple)")
    rp = rows // num_shards
    if num_shards == 1:
        return extract_final_result(heap), heap.dist2, heap.idx
    if via == "tree":
        st = tree_merge_candidates(heap, AXIS, num_shards)
        off = jax.lax.axis_index(AXIS) * rp
        return (jax.lax.dynamic_slice_in_dim(extract_final_result(st),
                                             off, rp),
                jax.lax.dynamic_slice_in_dim(st.dist2, off, rp),
                jax.lax.dynamic_slice_in_dim(st.idx, off, rp))
    if via != "a2a":
        raise ValueError(f"unknown device merge reduction '{via}'")

    def scatter(x):
        # [Q, k] -> [R*rp, k]: block j holds shard j's candidates for MY
        # rp rows -> [rp, R*k] with columns in shard-major order
        x = jax.lax.all_to_all(x, AXIS, 0, 0, tiled=True)
        return x.reshape(num_shards, rp, k).transpose(1, 0, 2).reshape(
            rp, num_shards * k)

    cat_d2 = scatter(heap.dist2)
    cat_idx = scatter(heap.idx)
    neg, cols = jax.lax.top_k(-cat_d2, k)
    top_d2 = -neg  # -(-0.0) == 0.0, -(-inf) == inf: values round-trip
    top_idx = jnp.take_along_axis(cat_idx, cols, axis=1)
    return jnp.sqrt(top_d2[:, k - 1]), top_d2, top_idx


def resolve_bucket_size(bucket_size: int, engine: str) -> int:
    """0 = auto, resolved per engine from measured data: the XLA twin is
    pair-budget-bound on its low-overhead backend (CPU wall-clock tracks
    pairs/query 1:1 — bucket 128 doubled 250K/k=8 throughput over 512,
    round-5 geometry sweep + pair_budget_report.json), while the Pallas
    kernel pays a real per-while-step cost that favors wider tiles —
    tpu_tune.py's on-chip sweep ranked 256 (with G2) first.

    Checkpoint note: stepwise fingerprints record the RESOLVED values (a
    different bucket geometry is genuinely non-resumable state — the
    partitioned shard arrays change shape), so changing an auto default
    here (or in _effective_group) makes older default-flag checkpoints
    resumable only by passing the explicit flags of the recorded
    geometry: for pallas runs from before the round-5 retune, both
    `--bucket-size 512` and `--point-group 1`."""
    if bucket_size:
        return bucket_size
    if engine == "tiled":
        return 128
    if engine == "pallas_tiled":
        # tpu_tune.py on-chip sweep (round 5, v5e, 500K/k=8): 256-bucket
        # cells beat the old 512 default at every LSK_CHUNK_LANES, and
        # the 256/G2 geometry won the whole grid (552.7K q/s vs 512/G1's
        # 356.3K) — see tpu_tune_report.json; G2 comes from the
        # point_group auto below.
        return 256
    return 512


def _tiled_engine_fn(engine: str):
    """Bucket-granular fold for the tiled data path: the fused Pallas
    traversal kernel for ``pallas_tiled``, the XLA twin otherwise."""
    if engine == "pallas_tiled":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
                knn_update_tiled_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas_tiled' is unavailable in this build") from e
        return knn_update_tiled_pallas
    return knn_update_tiled


def _make_ring_fns(k, max_radius, engine, query_tile, point_tile, bucket_size,
                   num_shards, warm_start=False, point_group=1,
                   score_dtype="f32"):
    """(init_fn, round_fn, final_fn, shard_init_fn, query_init_fn) — the
    per-round pieces every ring driver executes, defined once so the fused,
    stepwise and chunked paths cannot diverge.

    - init_fn(pts_local, ids_local) -> (stationary, shard_pair, heap)
      (classic path: the slab is both tree shard and queries)
    - shard_init_fn(pts_local, ids_local) -> shard (tree side only; drivers
      pair it as (shard, shard) — see below)
    - query_init_fn(qpts_local, qids_local) -> (stationary, heap)
      (query side only — may be a chunk of the slab)
    - round_fn(stationary, shard_pair, heap, rnd)
        -> (next_pair, new_heap, tiles)
      (issues the rotations before the folds so XLA overlaps them; ``tiles``
      is i32[1]: distance tiles this device actually computed — real counts
      for the pruned tiled engines, 0 for flat engines whose all-pairs count
      is analytic and added by the drivers)
    - final_fn(stationary, heap, npad) -> (dists, hd2, hidx) in input-row
      order per shard

    The ring is BIDIRECTIONAL: two copies of each tree counter-rotate, one
    ``ppermute`` per direction, so the full sweep takes
    ``ring_total_rounds(R) = R//2 + 1`` rounds of (up to) two folds instead
    of R rounds of one. Same total bytes and folds — but ICI links are
    full-duplex, so using both directions at once halves the exchange
    wall-clock the reference's one-direction ring pays
    (unorderedDataVariant.cu:178-193), and the loop/dispatch overhead
    halves with the round count. ``rnd`` disambiguates the two duplicate
    deliveries (round 0: both copies are the own shard; round R/2 for even
    R: both copies are the antipodal shard) — the backward fold is skipped
    there, keeping every shard folded exactly once.
    """
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    # warm start only applies to self-joins on ONE shared partition (query
    # bucket b IS point bucket b in round 0) — and only pays where the
    # fold's PASS count is the cost: the Pallas kernel. The XLA twin's
    # width-2k sort-merge saves nothing from a warm heap, and the warm
    # start's own top_k+merge cost REGRESSED it 20% at 500K/k=100 on the
    # CPU fixture (round-5 A/B vs the round-4 tree) — so the twin stays
    # cold. Chunked drivers partition queries separately and always stay
    # cold.
    warm_start = warm_start and engine == "pallas_tiled"
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    bwd = [(i, (i - 1) % num_shards) for i in range(num_shards)]

    def rotate_pair(shard_pair):
        f, b = shard_pair
        return (jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, fwd), f),
                jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, bwd), b))

    def is_dup(rnd):
        # round 0 (own shard twice) and, for even R, round R/2 (antipode)
        return (rnd == 0) | (2 * rnd == num_shards)

    if use_tiled:
        tiled_update = _tiled_engine_fn(engine)

        def query_from_q(q):
            # heap init for an ALREADY-partitioned query side (the drivers
            # hoist the partition out of the jit — see partition_sharded)
            heap = pvary(init_candidates(q.pts.shape[0] * q.pts.shape[1], k,
                                         max_radius))
            return q, heap

        def init_from_q(q):
            # the rotating point side is a GROUP-coarsened view of the
            # same partition (ops/partition.py coarsen_buckets): fine
            # query buckets keep the prune radius tight while the resident
            # tiles stay point_group x wider for DMA/fold efficiency
            pc = coarsen_buckets(q, point_group)
            if warm_start:
                # exact top-k of every query's own (containing) resident
                # bucket, folded before the traversal — round 0's kernel
                # then masks that bucket (skip_self below). Rows come back
                # in fine order: the coarsening is a reshape
                heap = warm_start_self(pc, k, max_radius)
            else:
                _, heap = query_from_q(q)
            shard = (pc.pts, pc.ids, pc.lower, pc.upper)
            return q, (shard, shard), heap

        def fold_one(q, shard, heap, sskip=None):
            # the resident shard keeps its OWN bucket geometry (it may differ
            # from the query side's under chunked queries); pos is
            # query-side-only metadata, ids stand in for it
            resident = BucketedPoints(shard[0], shard[1], shard[2], shard[3],
                                      shard[1])
            return tiled_update(heap, q, resident, with_stats=True,
                                skip_self=sskip, self_group=point_group,
                                score_dtype=score_dtype)

        def round_fn(q, shard_pair, heap, rnd, rotate=True):
            # the final round's rotation would be discarded — callers pass
            # rotate=False there (static flag: collectives cannot sit
            # under a traced cond)
            nxt = rotate_pair(shard_pair) if rotate else shard_pair
            f, b = shard_pair
            # round 0's forward fold is the own shard: with a warm-started
            # heap its self buckets are already folded and must be masked
            sskip = ((rnd == 0).astype(jnp.int32) if warm_start else None)
            st, tiles_f = fold_one(q, f, heap, sskip)

            def fold_b(_):
                st2, t2 = fold_one(q, b, st)
                return st2.dist2, st2.idx, t2

            hd2, hidx, tiles_b = jax.lax.cond(
                # tiles_f * 0, not a fresh zero: the constant would be
                # replicated and mismatch fold_b's axis-varying count
                is_dup(rnd), lambda _: (st.dist2, st.idx, tiles_f * 0),
                fold_b, None)
            return nxt, CandidateState(hd2, hidx), (tiles_f + tiles_b)[None]

        def final_fn(q, heap, npad):
            kk = heap.dist2.shape[-1]
            bs = (q.num_buckets, q.bucket_size)
            dists = scatter_back(extract_final_result(heap).reshape(bs),
                                 q.pos, npad, fill=jnp.inf)
            hd2 = scatter_back(heap.dist2.reshape(bs + (kk,)), q.pos, npad,
                               fill=jnp.inf)
            hidx = scatter_back(heap.idx.reshape(bs + (kk,)), q.pos, npad,
                                fill=-1)
            return dists, hd2, hidx

        # the partition itself is hoisted out of the drivers' jits
        # (partition_sharded), so the in-jit init path only exists in the
        # *_from_q form — no tiled init_fn/shard_init_fn/query_init_fn
        init_fn = shard_init_fn = query_init_fn = None
    else:
        update = _engine_fn(engine, query_tile, point_tile, score_dtype)
        use_tree = engine == "tree"

        def query_init_fn(qpts_local, qids_local):
            heap = pvary(init_candidates(qpts_local.shape[0], k, max_radius))
            return qpts_local, heap

        def round_fn(queries, shard_pair, heap, rnd, rotate=True):
            nxt = rotate_pair(shard_pair) if rotate else shard_pair
            f, b = shard_pair
            st = update(heap, queries, f[0], f[1])
            hd2, hidx = jax.lax.cond(
                is_dup(rnd), lambda _: (st.dist2, st.idx),
                lambda _: (lambda s2: (s2.dist2, s2.idx))(
                    update(st, queries, b[0], b[1])), None)
            st = CandidateState(hd2, hidx)
            # flat engines score every pair: the count is analytic
            # (n_q * n_p per device-fold), added host-side by the drivers
            return nxt, st, pvary(jnp.zeros((1,), jnp.int32))

        def final_fn(_queries, heap, _npad):
            return extract_final_result(heap), heap.dist2, heap.idx

        def shard_init_fn(pts_local, ids_local):
            if use_tree:
                return build_tree(pts_local, ids_local)
            return (pts_local, ids_local)

        def init_fn(pts_local, ids_local):
            q, heap = query_init_fn(pts_local, ids_local)
            shard = shard_init_fn(pts_local, ids_local)
            return q, (shard, shard), heap

        init_from_q = query_from_q = None  # flat engines have no partition

    return (init_fn, round_fn, final_fn, shard_init_fn, query_init_fn,
            init_from_q, query_from_q)


def _pair_step_fn(round_fn, rotate=True):
    """Flat-argument step wrapper shared by the stepwise and chunked
    drivers (shard_map wants leaf-wise specs; the pair and round counter
    ride as separate arguments and the counter self-increments).
    ``rotate=False`` builds the final-round variant whose (discarded)
    rotation is skipped."""
    def step_fn(stationary, f_state, b_state, heap, rnd_arr):
        nxt, st, t = round_fn(stationary, (f_state, b_state), heap,
                              rnd_arr[0], rotate=rotate)
        return nxt[0], nxt[1], st, t, rnd_arr + 1
    return step_fn


def _folds_in_rounds(start: int, stop: int, num_shards: int) -> int:
    """Folds the bidirectional ring executes in rounds [start, stop):
    1 in round 0 and in the even-R antipodal round, else 2."""
    return sum(1 if (r == 0 or 2 * r == num_shards) else 2
               for r in range(start, stop))


def ring_total_rounds(num_shards: int) -> int:
    """Rounds for a full bidirectional sweep: the own shard at round 0,
    then offsets +-1, ..., +-floor(R/2)."""
    return num_shards // 2 + 1


def _effective_group(point_group: int, npad_local: int,
                     bucket_size: int, engine: str) -> int:
    """Clamp the point-side coarsening factor to the actual bucket count
    (both are powers of two, so the clamped value always divides).

    0 = auto per engine, like resolve_bucket_size: the Pallas kernel's
    tune-sweep winner pairs its 256-bucket default with G2 (fine prune
    radius, full-width 512-lane tiles — tpu_tune_report.json round 5);
    the XLA twin's lock-step visit loop measurably loses from grouping
    (BASELINE.md round-5 A/B), so every other engine resolves to 1.
    ``engine`` is deliberately required: a call site that forgot it
    would silently resolve auto to 1 instead of the engine's tuned
    group (checkpoint-recovery implications in resolve_bucket_size)."""
    if point_group == 0:
        point_group = 2 if engine == "pallas_tiled" else 1
    if point_group <= 1:
        return 1
    assert point_group & (point_group - 1) == 0, point_group
    return min(point_group, choose_buckets(npad_local, bucket_size)[0])


def _warm_tiles(engine: str, npad_local: int, bucket_size: int,
                num_shards: int) -> int:
    """[S, S] tiles the warm start scores (one per bucket, every device) —
    counted into executed-work stats alongside the kernel's measured tile
    counts, since warm_start_self does that distance work in XLA before
    the traversal ever runs (pallas_tiled self-join drivers only — the
    twin stays cold, see _make_ring_fns)."""
    if engine != "pallas_tiled":
        return 0
    return num_shards * choose_buckets(npad_local, bucket_size)[0]


def _ring_stats(engine: str, tiles_total: int, bucket_size: int,
                n_q_device_rounds: int, *, q_rows: int | None = None,
                p_rows: int | None = None, point_group: int = 1) -> dict:
    """Executed-work stats: distance pairs actually scored.

    Tiled engines report measured tile counts (pruning makes the count
    data-dependent); one tile is [S_q, S_p] where S are the ACTUAL padded
    bucket sizes from ``choose_buckets`` (the nominal ``bucket_size``
    overstated pair_evals ~6% at 1M points). ``q_rows``/``p_rows`` are the
    per-device query/point row counts the buckets were built from. Flat
    engines score every pair, so the count is analytic:
    ``n_q_device_rounds`` = sum over device-rounds of
    n_queries_local * n_points_local.

    Granularity note: the two tiled engines count DIFFERENT things and
    their pair_evals are not comparable as pruning quality. The XLA twin
    counts chunk*V tiles for every chunk with >=1 active bucket (executed
    VPU work — its dense tile really covers masked buckets,
    ops/tiled.py body). The Pallas kernel counts only KEPT buckets (its
    nvis masks chunk-tail and skip_self buckets before the fold), so its
    broadcast FLOPs over masked lanes go uncounted — pair_evals-derived
    MFU is a lower bound there. Compare engines on wall-clock."""
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    if use_tiled:
        _, s_q = choose_buckets(q_rows or 1, bucket_size)
        _, s_p = choose_buckets(p_rows or q_rows or 1, bucket_size)
        # coarsened point side: one visited tile spans point_group fine
        # buckets' lanes (ops/partition.py coarsen_buckets)
        pair_evals = int(tiles_total) * s_q * s_p * point_group
    elif engine == "tree":
        # the stack-free traversal is bounds-pruned and uninstrumented:
        # all-pairs would overstate executed work by orders of magnitude
        return {"pair_evals": 0, "tiles": 0, "flops_per_pair": 8,
                "note": "tree engine work is pruned and not counted"}
    else:
        pair_evals = int(n_q_device_rounds)
    return {"pair_evals": pair_evals, "tiles": int(tiles_total),
            "flops_per_pair": 8}


def ring_knn(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray, k: int,
             mesh, *, max_radius: float = jnp.inf, engine: str = "auto",
             query_tile: int = 2048, point_tile: int = 2048,
             bucket_size: int = 0, point_group: int = 0,
             score_dtype: str = "f32",
             return_candidates: bool = False,
             return_stats: bool = False):
    """Run the full R-round ring on a 1-D mesh (fused ``lax.fori_loop``).

    Args:
      points_sharded: f32[R*Npad, 3], shard-major (device i owns rows
        [i*Npad, (i+1)*Npad)), sentinel-padded. Device i's rows serve as both
        its tree shard and its stationary queries (the reference uploads the
        same slab twice — unorderedDataVariant.cu:159-167).
      ids_sharded: i32[R*Npad] global point ids (-1 for padding) that travel
        with the rotating shards so candidate lists can report neighbor
        identities (the reference computes these but discards them).
      k / max_radius: the `-k` / `-r` CLI parameters.

    Returns:
      f32[R*Npad] k-th-NN distances in the same shard-major order (inf for
      padding rows), plus the CandidateState if ``return_candidates``.
    """
    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    total_rounds = ring_total_rounds(num_shards)
    npad_local = points_sharded.shape[0] // num_shards
    point_group = _effective_group(point_group, npad_local, bucket_size, engine)
    init_fn, round_fn, final_fn, _sif, _qif, init_from_q, _qfq = \
        _make_ring_fns(k, max_radius, engine, query_tile, point_tile,
                       bucket_size, num_shards, warm_start=True,
                       point_group=point_group, score_dtype=score_dtype)

    def body(pts_local, ids_local, q_local=None):
        if q_local is not None:
            stationary, pair, heap = init_from_q(q_local)
        else:
            stationary, pair, heap = init_fn(pts_local, ids_local)

        def round_body(i, carry):
            pair, hd2, hidx, tiles = carry
            nxt, st, t = round_fn(stationary, pair,
                                  CandidateState(hd2, hidx), i)
            # one slot per round, not a running i32 sum: a single round's
            # count fits int32 comfortably, but the total at reference
            # scale does not — the host sums the slots in int64
            tiles = jax.lax.dynamic_update_index_in_dim(tiles, t[0], i, 0)
            return nxt, st.dist2, st.idx, tiles

        pair, hd2, hidx, tiles = jax.lax.fori_loop(
            0, total_rounds - 1, round_body,
            (pair, heap.dist2, heap.idx,
             pvary(jnp.zeros((total_rounds,), jnp.int32))))
        # final round: fold only — its rotation would be discarded
        _, st, t = round_fn(stationary, pair, CandidateState(hd2, hidx),
                            jnp.int32(total_rounds - 1), rotate=False)
        tiles = jax.lax.dynamic_update_index_in_dim(
            tiles, t[0], total_rounds - 1, 0)
        return final_fn(stationary, st, pts_local.shape[0]) + (tiles,)

    shard_spec = P(AXIS)
    # interpret-mode pallas kernels re-evaluate a vma-less kernel jaxpr with
    # varying operands, which trips shard_map's vma checker (JAX's own
    # guidance: pass check_vma=False); XLA engines keep the strict typing
    check_vma = not engine.startswith("pallas")
    n_args = 3 if init_from_q is not None else 2
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec,) * n_args,
        out_specs=(shard_spec, shard_spec, shard_spec, shard_spec),
        check_vma=check_vma))

    sharding = NamedSharding(mesh, shard_spec)
    points_sharded = jax.device_put(points_sharded, sharding)
    ids_sharded = jax.device_put(ids_sharded, sharding)
    if init_from_q is not None:
        # tiled path: the log2(B) partition sort passes compile ONCE outside
        # the fused program instead of once per level inside it
        q_parts = partition_sharded(points_sharded, ids_sharded, mesh,
                                    bucket_size)
        dists, hd2, hidx, tiles = mapped(points_sharded, ids_sharded,
                                         q_parts)
    else:
        dists, hd2, hidx, tiles = mapped(points_sharded, ids_sharded)
    out = (dists,)
    if return_candidates:
        out += (CandidateState(hd2, hidx),)
    if return_stats:
        out += (_ring_stats(
            engine, int(np.asarray(tiles).sum())
            + _warm_tiles(engine, npad_local, bucket_size, num_shards),
            bucket_size,
            num_shards * num_shards * npad_local * npad_local,
            q_rows=npad_local, p_rows=npad_local,
            point_group=point_group),)
    return out if len(out) > 1 else out[0]


def ring_knn_stepwise(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray,
                      k: int, mesh, *, max_radius: float = jnp.inf,
                      engine: str = "auto", query_tile: int = 2048,
                      point_tile: int = 2048, bucket_size: int = 0,
                      point_group: int = 0, score_dtype: str = "f32",
                      checkpoint_dir: str | None = None,
                      checkpoint_every: int = 1,
                      max_rounds: int | None = None,
                      return_candidates: bool = False,
                      return_stats: bool = False):
    """``ring_knn`` with host-controlled rounds + checkpoint/resume.

    Identical results to ``ring_knn`` (literally the same ``_make_ring_fns``
    per-round pieces), but the round loop runs on the host — one jitted
    shard_map step per round — so the persistent heaps and the resident
    rotating shard can be snapshotted between rounds and a preempted run
    resumed at the exact round it lost. The reference cannot do this (one
    pass, output only at the end, SURVEY.md §5); its candidate buffer is the
    natural checkpoint state and here it literally is the checkpoint.

    The checkpoint fingerprint includes a sampled digest of the input data;
    a successful full run clears its checkpoint so a later run cannot
    silently reuse stale results. ``max_rounds`` stops early (state saved if
    checkpointing), for staged runs and interruption tests.

    Returns f32[R*Npad] k-th-NN distances (numpy), shard-major like
    ``ring_knn``.
    """
    from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt

    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    spec = P(AXIS)
    check_vma = not engine.startswith("pallas")
    npad_local = points_sharded.shape[0] // num_shards
    point_group = _effective_group(point_group, npad_local, bucket_size, engine)

    def smap(fn, n_in, out_structs):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * n_in, out_specs=out_structs,
            check_vma=check_vma))

    sharding = NamedSharding(mesh, spec)
    pts = jax.device_put(points_sharded, sharding)
    ids = jax.device_put(ids_sharded, sharding)

    fp = None
    resuming = False
    if checkpoint_dir:
        fp = ckpt.fingerprint(
            n=int(pts.shape[0]), k=int(k), shards=num_shards, engine=engine,
            max_radius=float(max_radius), bucket_size=bucket_size,
            # key present only when active (G>1): G1 runs keep
            # resumability of checkpoints written before the knob
            # existed. Since the round-5 retune, pallas DEFAULT runs
            # resolve to G2 and so do record the key — older default
            # checkpoints need the explicit flags resolve_bucket_size's
            # docstring names.
            **({"point_group": point_group} if point_group > 1 else {}),
            # key present only for non-default scoring: f32 checkpoints
            # written before the knob existed stay resumable
            **({"score_dtype": score_dtype} if score_dtype != "f32" else {}),
            query_tile=query_tile, point_tile=point_tile, ring="bidir",
            data=ckpt.data_digest(points_sharded, ids_sharded))
        # decide resume BEFORE init: a resumed run's heap comes from the
        # checkpoint, and the warm start's [S,S]-per-bucket work would be
        # computed only to be thrown away
        resuming = ckpt.peek_round(checkpoint_dir, fp) is not None

    init_fn, round_fn, final_fn, _sif, _qif, init_from_q, _qfq = \
        _make_ring_fns(k, max_radius, engine, query_tile, point_tile,
                       bucket_size, num_shards, warm_start=not resuming,
                       point_group=point_group, score_dtype=score_dtype)

    if init_from_q is not None:
        q_parts = partition_sharded(pts, ids, mesh, bucket_size)
        stationary, pair, heap = smap(init_from_q, 1,
                                      (spec, spec, spec))(q_parts)
    else:
        stationary, pair, heap = smap(init_fn, 2,
                                      (spec, spec, spec))(pts, ids)

    step = smap(_pair_step_fn(round_fn), 5, (spec, spec, spec, spec, spec))
    step_last = smap(_pair_step_fn(round_fn, rotate=False), 5,
                     (spec, spec, spec, spec, spec))

    start = 0
    if checkpoint_dir:
        got = ckpt.load_pytree(checkpoint_dir, fp, (pair, heap), sharding)
        if got is not None:
            start, (pair, heap) = got

    total_rounds = ring_total_rounds(num_shards)
    tiles_parts = []  # device arrays; materialized ONCE after the loop so
    rounds_run = 0    # the non-stats path keeps its async round dispatch
    stop = (total_rounds if max_rounds is None
            else min(max_rounds, total_rounds))
    rnd_arr = jax.device_put(np.full(num_shards, start, np.int32), sharding)
    for r in range(start, stop):
        fn = step_last if r == total_rounds - 1 else step
        f_state, b_state, heap, tiles, rnd_arr = fn(
            stationary, pair[0], pair[1], heap, rnd_arr)
        pair = (f_state, b_state)
        if return_stats:
            tiles_parts.append(tiles)
        rounds_run += 1
        if checkpoint_dir and ((r + 1) % checkpoint_every == 0
                               or r + 1 == stop):
            ckpt.save_pytree(checkpoint_dir, r + 1, (pair, heap), fp)

    dists, hd2, hidx = smap(
        lambda s, h: final_fn(s, h, npad_local), 2,
        (spec, spec, spec))(stationary, heap)
    if checkpoint_dir and stop == total_rounds:
        # done: clear so a later (possibly different-data) run in the same
        # dir can never resume past its own work
        ckpt.clear(checkpoint_dir)
    out = (np.asarray(dists),)
    if return_candidates:
        out += (CandidateState(hd2, hidx),)
    if return_stats:
        tiles_total = int(np.sum([np.asarray(t).sum() for t in tiles_parts]))
        if not resuming:
            # the warm start ran in THIS session (a resumed run's heap
            # already carries it — its tiles belong to the first session).
            # Guarded on the same flag that gated the warm start, NOT on
            # start == 0: a checkpoint that passes peek_round but vanishes
            # before load leaves start at 0 with a COLD round 0, and the
            # kernel then counts the self-bucket tiles itself
            tiles_total += _warm_tiles(engine, npad_local, bucket_size,
                                       num_shards)
        # analytic fold count for flat engines, exact for resumed
        # sessions too (round 0 and the even-R antipodal round fold once)
        folds = _folds_in_rounds(start, stop, num_shards)
        out += (_ring_stats(
            engine, tiles_total, bucket_size,
            folds * num_shards * npad_local * npad_local,
            q_rows=npad_local, p_rows=npad_local,
            point_group=point_group),)
    return out if len(out) > 1 else out[0]


def ring_knn_chunked(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray,
                     k: int, mesh, *, chunk_rows: int,
                     max_radius: float = jnp.inf, engine: str = "auto",
                     query_tile: int = 2048, point_tile: int = 2048,
                     bucket_size: int = 0, point_group: int = 0,
                     score_dtype: str = "f32",
                     checkpoint_dir: str | None = None,
                     checkpoint_every: int = 1,
                     max_chunks: int | None = None,
                     pipeline_depth: int = 2,
                     merge: str = "host",
                     return_candidates: bool = False,
                     return_stats: bool = False):
    """``ring_knn`` with the query side streamed in fixed-size chunks.

    The memory wall at reference scale is the candidate heaps, not the
    points: N*k*8 bytes (SURVEY.md §7 hard part #4 — at k=100 the heaps are
    ~67x the size of the points, which is why the reference moves trees, not
    heaps). This driver keeps every device's FULL tree shard resident (N/R
    points) but holds heaps for only ``chunk_rows`` queries per device at a
    time: per chunk, the whole R-round ring runs against the same rotating
    shards — after R ``ppermute`` rounds each shard is back home, so the next
    chunk starts from clean state with zero re-setup. Peak heap memory drops
    from Npad*k to chunk_rows*k per device at the cost of R rotations per
    chunk (tree bytes are the small term: the reference's own trade).

    Every chunk is padded to the same ``chunk_rows`` shape, so all chunks
    share one compiled step. With ``checkpoint_dir``, completed chunks'
    results are persisted and a relaunch resumes at the first unfinished
    chunk (coarser-grained than ring_knn_stepwise's per-round snapshots, and
    far smaller state: results, not heaps).

    Host/device pipelining (``pipeline_depth``, default 2): chunk c+1's
    host staging (sentinel-pad + partition dispatch) runs while chunk c's
    rounds are still in flight, and chunk c's result fetch (the only
    blocking host sync in the loop) is deferred until up to
    ``pipeline_depth`` chunks are pending — so the device never idles
    waiting for numpy. Results are bit-identical at any depth (the pipeline
    reorders nothing); depth 1 restores the fully serialized loop. Each
    pending chunk holds one extra set of result buffers on device
    (~``R * chunk_rows * k * 8`` bytes with candidates), the usual
    double-buffering cost. A due checkpoint forces a full drain first, so
    snapshots only ever record fully materialized chunks.

    Merge placement (``merge``, default ``host``): ``host`` is the ring —
    per chunk, tree shards rotate R times past stationary per-device query
    heaps, and each device's heap ends global with no cross-shard merge at
    all. ``device`` replaces the rotation with the serving engine's
    replicate-traverse-merge shape: the whole chunk is REPLICATED to every
    device, each traverses only its own resident shard (zero ``ppermute``
    rotations of tree data, one program dispatch per chunk instead of
    R//2+1 stepped rounds), and the R partial candidate states reduce to
    the final answer in-program (``device_merge_final``'s reduce-scatter)
    before ``extract_final_result`` — the deferred per-chunk fetch then
    carries final rows only. Result and candidate DISTANCES are bit-identical to
    the ring's; at equal distances the two strategies order neighbor ids
    differently (the ring in fold-arrival order — own shard first, per
    device — the device merge in ascending (shard, slot) order, the
    serving engine's discipline), both exact top-k. The trade: candidate states
    hold ALL R*chunk_rows chunk queries per device (R x the ring's heap
    memory) and the queries ride one coarse prune bucket, so device merge
    wins at SMALL chunks — the round-dispatch-bound regime — while the
    ring's fine-bucketed prune wins large ones. ``auto`` resolves like the
    engine's (``resolve_merge``: device on power-of-two meshes, host with
    a logged warning otherwise). Both placements run multi-host: the chunk
    is staged sharded (each host uploads its own rows) and the device-merge
    program all_gathers it, so ``device_merge_final``'s reduction runs on
    the GLOBAL pod-mesh axis and each host fetches only its 1/R slices of
    the pod-final rows.

    Returns like ``ring_knn``: f32[R*Npad] shard-major distances (numpy),
    plus (dist2, idx) candidate arrays when ``return_candidates``.
    """
    from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL
    from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt

    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    merge = resolve_merge(merge, num_shards)
    _init, round_fn, final_fn, shard_init_fn, query_init_fn, _ifq, \
        query_from_q = _make_ring_fns(
            k, max_radius, engine, query_tile, point_tile, bucket_size,
            num_shards, score_dtype=score_dtype)
    dim = int(points_sharded.shape[-1])
    spec = P(AXIS)
    check_vma = not engine.startswith("pallas")
    sharding = NamedSharding(mesh, spec)

    # multi-host: the input is a GLOBAL sharded jax.Array; each host sees
    # (and chunks) only its addressable blocks, checkpoints its own rows,
    # and returns {mesh position: rows} instead of the flat global vector
    # no host could hold at reference scale
    multi = jax.process_count() > 1
    if multi:
        if not isinstance(points_sharded, jax.Array):
            raise ValueError("multi-host chunked ring needs global sharded "
                             "jax.Arrays (see cli/multihost.py)")
        npad_local = points_sharded.shape[0] // num_shards
        pts_glob, ids_glob = points_sharded, ids_sharded

        def blocks(garr, width):
            out = {}
            for sh in garr.addressable_shards:
                pos = int(sh.index[0].start) // npad_local
                out[pos] = np.asarray(sh.data).reshape((npad_local,) + width)
            return out

        pts_b = blocks(pts_glob, (dim,))
        ids_b = blocks(ids_glob, ())
    else:
        points_sharded = np.asarray(points_sharded, np.float32)
        ids_sharded = np.asarray(ids_sharded, np.int32)
        npad_local = points_sharded.shape[0] // num_shards
        pts_glob = jax.device_put(points_sharded, sharding)
        ids_glob = jax.device_put(ids_sharded, sharding)
        pts_g3 = points_sharded.reshape(num_shards, npad_local, dim)
        ids_g2 = ids_sharded.reshape(num_shards, npad_local)
        pts_b = {s: pts_g3[s] for s in range(num_shards)}
        ids_b = {s: ids_g2[s] for s in range(num_shards)}

    my_pos = sorted(pts_b)
    n_my = len(my_pos)
    n_chunks = max(1, -(-npad_local // chunk_rows))
    point_group = _effective_group(point_group, npad_local, bucket_size, engine)

    def to_global(local, global_rows):
        if multi:
            return jax.make_array_from_process_local_data(
                sharding, local, (global_rows,) + local.shape[1:])
        return jax.device_put(local, sharding)

    def smap(fn, n_in, out_specs):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                                     out_specs=out_specs,
                                     check_vma=check_vma))

    def local_rows(garr, width):
        if multi:
            rows = np.empty((n_my, chunk_rows) + width, garr.dtype)
            got = {int(sh.index[0].start) // chunk_rows:
                   np.asarray(sh.data) for sh in garr.addressable_shards}
            for j, s in enumerate(my_pos):
                rows[j] = got[s].reshape((chunk_rows,) + width)
            return rows
        return np.asarray(garr).reshape((num_shards, chunk_rows) + width)

    if query_from_q is not None:
        # tiled: hoisted partitions — ONE compiled sort pass shared by all
        # levels of the shard partition, another shared by every chunk's
        # query partition (see partition_sharded). The RESIDENT side is
        # group-coarsened (wide tiles); no warm start / skip-self here —
        # chunk queries fold the whole resident shard including their own
        # points exactly once, the normal self-inclusion. Coarsening runs
        # per device inside shard_map: group boundaries never straddle
        # shards (B_local is a power of two and the group is clamped to
        # it), and the reshape stays communication-free by construction
        qf = partition_sharded(pts_glob, ids_glob, mesh, bucket_size)
        if point_group > 1:
            qf = smap(partial(coarsen_buckets, group=point_group),
                      1, spec)(qf)
        shard0 = (qf.pts, qf.ids, qf.lower, qf.upper)
        _heapq = smap(query_from_q, 1, (spec, spec))

        def qinit(qp_glob, qi_glob):
            qq = partition_sharded(qp_glob, qi_glob, mesh, bucket_size)
            return _heapq(qq)
    else:
        shard0 = smap(shard_init_fn, 2, spec)(pts_glob, ids_glob)
        qinit = smap(query_init_fn, 2, (spec, spec))

    step = smap(_pair_step_fn(round_fn), 5, (spec, spec, spec, spec, spec))
    step_last = smap(_pair_step_fn(round_fn, rotate=False), 5,
                     (spec, spec, spec, spec, spec))
    final = smap(lambda s, h: final_fn(s, h, chunk_rows), 2,
                 (spec, spec, spec))
    total_rounds = ring_total_rounds(num_shards)
    rnd0 = to_global(np.zeros(n_my, np.int32), num_shards)

    use_tiled = query_from_q is not None
    if merge == "device":
        # replicate-traverse-merge chunk program (one dispatch per chunk):
        # the replicated chunk traverses each device's OWN resident shard,
        # the R partial candidate states tree-reduce in-program, and each
        # device emits its 1/R slice of the final rows — same global row
        # layout as the ring path, so drain/checkpoint logic is shared.
        # The chunk is staged SHARDED (each host uploads only its own rows,
        # exactly like the ring path) and replicated by an in-program
        # all_gather, so the same program runs on a single host and on the
        # global pod mesh — the reduction collectives below already ride
        # whatever axis the mesh spans (ICI or DCN)
        qrows = num_shards * chunk_rows
        flat_update = (None if use_tiled
                       else _engine_fn(engine, query_tile, point_tile,
                                       score_dtype))
        tiled_update_m = _tiled_engine_fn(engine) if use_tiled else None

        def merge_body(*args):
            q_local, shard = args[-1], args[:-1]
            q = jax.lax.all_gather(q_local, AXIS, tiled=True)
            heap = pvary(init_candidates(qrows, k, max_radius))
            if use_tiled:
                valid = q[:, 0] < PAD_SENTINEL / 2
                qids = jnp.where(valid,
                                 jnp.arange(qrows, dtype=jnp.int32), -1)
                qlo = jnp.min(jnp.where(valid[:, None], q, jnp.inf), axis=0)
                qhi = jnp.max(jnp.where(valid[:, None], q, -jnp.inf), axis=0)
                qb = BucketedPoints(q[None], qids[None], qlo[None],
                                    qhi[None], qids[None])
                resident = BucketedPoints(shard[0], shard[1], shard[2],
                                          shard[3], shard[1])
                st, tiles = tiled_update_m(heap, qb, resident,
                                           with_stats=True,
                                           score_dtype=score_dtype)
            else:
                st = flat_update(heap, q, *shard)
                tiles = pvary(jnp.zeros((), jnp.int32))
            dists, d2f, idxf = device_merge_final(st, num_shards)
            return dists, d2f, idxf, tiles[None]

        merge_prog = jax.jit(jax.shard_map(
            merge_body, mesh=mesh,
            in_specs=(spec,) * (5 if use_tiled else 3),
            out_specs=(spec, spec, spec, spec), check_vma=check_vma))

    out_d = np.full((n_my, npad_local), np.inf, np.float32)
    out_hd2 = (np.full((n_my, npad_local, k), np.inf, np.float32)
               if return_candidates else None)
    out_idx = (np.full((n_my, npad_local, k), -1, np.int32)
               if return_candidates else None)

    fp = None
    start_chunk = 0
    ckpt_dir = checkpoint_dir
    if checkpoint_dir:
        if multi:
            # per-host checkpoint state under a shared dir: each host owns
            # (and resumes) exactly its rows; my_pos rides in the
            # fingerprint so a relaunch with a different host->shard map
            # starts fresh instead of mixing rows
            ckpt_dir = os.path.join(checkpoint_dir,
                                    f"host{jax.process_index()}")
        fp = ckpt.fingerprint(
            n=num_shards * npad_local, k=int(k), shards=num_shards,
            engine=engine, max_radius=float(max_radius),
            bucket_size=bucket_size, chunk_rows=chunk_rows,
            query_tile=query_tile, point_tile=point_tile,
            candidates=bool(return_candidates),
            # key present only for device merge: host-merge checkpoints
            # written before the knob existed stay resumable (results are
            # bit-identical across modes, but resuming records the plan)
            **({"merge": merge} if merge == "device" else {}),
            **({"score_dtype": score_dtype} if score_dtype != "f32" else {}),
            my_pos=",".join(str(s) for s in my_pos),
            data=ckpt.data_digest(
                np.concatenate([pts_b[s].reshape(-1) for s in my_pos]),
                np.concatenate([ids_b[s].reshape(-1) for s in my_pos])))
        got = ckpt.load_ring_state(ckpt_dir, fp)
        if got is not None:
            start_chunk, arrs = got
            out_d = arrs["out_d"]
            if return_candidates:
                out_hd2, out_idx = arrs["out_hd2"], arrs["out_idx"]

    # absolute cap, consistent with the stepwise drivers' max_rounds
    stop_chunk = (n_chunks if max_chunks is None
                  else min(max_chunks, n_chunks))
    tiles_parts = []  # materialized once at the end (see ring_knn_stepwise)
    chunks_run = 0
    depth = max(1, int(pipeline_depth))
    pending = []  # chunks dispatched on device, results not yet fetched

    def stage(c):
        # host staging for chunk c: sentinel-pad, upload, dispatch the query
        # partition + heap init (ring) or the replicated chunk upload
        # (device merge). Everything device-side here is async dispatch, so
        # staging chunk c+1 overlaps chunk c's in-flight work
        lo = c * chunk_rows
        hi = min(lo + chunk_rows, npad_local)
        qp = np.full((n_my, chunk_rows, dim), PAD_SENTINEL, np.float32)
        qi = np.full((n_my, chunk_rows), -1, np.int32)
        for j, s in enumerate(my_pos):
            qp[j, :hi - lo] = pts_b[s][lo:hi]
            qi[j, :hi - lo] = ids_b[s][lo:hi]
        if merge == "device":
            # ids stay host-side: result neighbor ids come from the
            # resident shard, and validity rides the sentinel coordinates;
            # each host uploads only ITS rows — the program all_gathers
            return lo, hi, to_global(qp.reshape(-1, dim),
                                     num_shards * chunk_rows), None
        stationary, heap = qinit(
            to_global(qp.reshape(-1, dim), num_shards * chunk_rows),
            to_global(qi.reshape(-1), num_shards * chunk_rows))
        return lo, hi, stationary, heap

    def drain_one():
        # materialize the OLDEST pending chunk (the only blocking sync in
        # the loop) — later chunks' rounds are already dispatched, so the
        # device stays busy while the host copies rows out
        lo, hi, d, hd2, hidx = pending.pop(0)
        out_d[:, lo:hi] = local_rows(d, ())[:, :hi - lo]
        if return_candidates:
            out_hd2[:, lo:hi] = local_rows(hd2, (k,))[:, :hi - lo]
            out_idx[:, lo:hi] = local_rows(hidx, (k,))[:, :hi - lo]

    staged = stage(start_chunk) if start_chunk < stop_chunk else None
    for c in range(start_chunk, stop_chunk):
        lo, hi, stationary, heap = staged
        chunks_run += 1
        if merge == "device":
            # one dispatch: traverse own shard, tree-reduce, slice final
            d, hd2, hidx, tiles = merge_prog(*shard0, stationary)
            if return_stats:
                tiles_parts.append(tiles)
        else:
            # pristine pair each chunk: the resident original never
            # rotates, so the traveling copies can be discarded wherever
            # the sweep ends
            pair = (shard0, shard0)
            rnd_arr = rnd0
            for _r in range(total_rounds):
                fn = step_last if _r == total_rounds - 1 else step
                f_state, b_state, heap, tiles, rnd_arr = fn(
                    stationary, pair[0], pair[1], heap, rnd_arr)
                pair = (f_state, b_state)
                if return_stats:
                    tiles_parts.append(tiles)
            d, hd2, hidx = final(stationary, heap)
        pending.append((lo, hi, d, hd2, hidx))
        # drain down to depth-1 pending BEFORE staging the next chunk: at
        # depth 1 that is exactly the serialized loop (fetch, then stage —
        # no extra device buffers held), while deeper pipelines fetch the
        # oldest chunk with this chunk's rounds still in flight, keeping the
        # result copy off the next dispatch's critical path
        while len(pending) >= depth:
            drain_one()
        if c + 1 < stop_chunk:
            # double-buffer: pre-pad + pre-partition the next chunk while
            # this chunk's rounds run
            staged = stage(c + 1)
        ckpt_due = checkpoint_dir and ((c + 1) % checkpoint_every == 0
                                       or c + 1 == stop_chunk)
        while pending and ckpt_due:
            drain_one()
        if ckpt_due:
            # snapshots are O(completed results) — at the target regime
            # (many chunks, k=100) keep checkpoint_every coarse enough that
            # write time stays small vs a chunk's ring
            arrs = {"out_d": out_d}
            if return_candidates:
                arrs.update(out_hd2=out_hd2, out_idx=out_idx)
            ckpt.save_ring_state(ckpt_dir, c + 1, arrs, fp)
    while pending:
        drain_one()

    def chunk_stats(tiles_total: int) -> dict:
        # shared by the single- and multi-host returns (only the tile-count
        # materialization differs between them)
        if merge == "device" and use_tiled:
            # device-merge tiles span the chunk's single query bucket
            # (R*chunk_rows rows), not the ring's fine query buckets
            _, s_p = choose_buckets(npad_local, bucket_size)
            return {"pair_evals": tiles_total * num_shards * chunk_rows
                    * s_p * point_group,
                    "tiles": tiles_total, "flops_per_pair": 8}
        return _ring_stats(
            engine, tiles_total, bucket_size,
            chunks_run * num_shards * num_shards * chunk_rows * npad_local,
            q_rows=chunk_rows, p_rows=npad_local, point_group=point_group)

    if checkpoint_dir and stop_chunk == n_chunks:
        ckpt.clear(ckpt_dir)
    if multi:
        out = ({s: out_d[j] for j, s in enumerate(my_pos)},)
        if return_candidates:
            out += (CandidateState(
                {s: out_hd2[j] for j, s in enumerate(my_pos)},
                {s: out_idx[j] for j, s in enumerate(my_pos)}),)
        if return_stats:
            # per-host view: only addressable shards' counts (a pod-global
            # sum would need a collective nobody asked to pay for here)
            out += (chunk_stats(int(np.sum([
                np.sum([np.asarray(sh.data).sum()
                        for sh in t.addressable_shards])
                for t in tiles_parts]))),)
        return out if len(out) > 1 else out[0]
    dists = out_d.reshape(-1)
    out = (dists,)
    if return_candidates:
        out += (CandidateState(out_hd2.reshape(-1, k),
                               out_idx.reshape(-1, k)),)
    if return_stats:
        out += (chunk_stats(int(np.sum([np.asarray(t).sum()
                                        for t in tiles_parts]))),)
    return out if len(out) > 1 else out[0]


def measure_exchange_bandwidth(mesh, npad_local: int, *, reps: int = 10,
                               bucket_size: int = 0,
                               engine: str = "auto") -> dict:
    """MEASURED per-round ring-rotation bandwidth (not analytic).

    Times the jitted rotation of a representative shard pytree (same
    shapes/dtypes the ring actually rotates — BOTH counter-rotating copies,
    one ``ppermute`` per direction, as the bidirectional ring moves them)
    in isolation: best of ``reps`` ``block_until_ready`` wall-clock deltas,
    minus a no-comm control (the same jitted program with the ppermutes
    replaced by an elementwise touch) to remove dispatch overhead. Every
    device sends its whole shard in each direction per round
    (``2 * shard_bytes``); the reported per-link figure counts both
    directions of the full-duplex link. The reference's equivalent transfer
    is the ring Isend/Irecv of tree buffers
    (unorderedDataVariant.cu:189-193), which it never times (SURVEY.md §5).
    """
    import time as _time

    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    if use_tiled:
        nb, s = choose_buckets(npad_local, bucket_size)
        shard_local = (jnp.zeros((nb, s, 3), jnp.float32),
                       jnp.zeros((nb, s), jnp.int32),
                       jnp.zeros((nb, 3), jnp.float32),
                       jnp.zeros((nb, 3), jnp.float32))
    else:
        shard_local = (jnp.zeros((npad_local, 3), jnp.float32),
                       jnp.zeros((npad_local,), jnp.int32))
    shard_bytes = sum(int(a.size) * a.dtype.itemsize for a in shard_local)
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    spec = P(AXIS)
    sharding = NamedSharding(mesh, spec)
    glob = tuple(
        jax.device_put(jnp.broadcast_to(a[None], (num_shards,) + a.shape)
                       .reshape((num_shards * a.shape[0],) + a.shape[1:]),
                       sharding)
        for a in shard_local)

    bwd = [(i, (i - 1) % num_shards) for i in range(num_shards)]

    def rotate(*shard):
        # both directions in flight, as in the real ring round
        return (tuple(jax.lax.ppermute(a, AXIS, fwd) for a in shard)
                + tuple(jax.lax.ppermute(a, AXIS, bwd) for a in shard))

    def touch(*shard):
        return (tuple(a + jnp.zeros((), a.dtype) for a in shard)
                + tuple(a + jnp.ones((), a.dtype) for a in shard))

    n_in = len(shard_local)
    rot = jax.jit(jax.shard_map(rotate, mesh=mesh, in_specs=(spec,) * n_in,
                                out_specs=(spec,) * (2 * n_in)))
    ctl = jax.jit(jax.shard_map(touch, mesh=mesh, in_specs=(spec,) * n_in,
                                out_specs=(spec,) * (2 * n_in)))

    def best_of(fn):
        out = fn(*glob)  # compile + warm
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            out = fn(*glob)
            jax.block_until_ready(out)
            best = min(best, _time.perf_counter() - t0)
        return best

    t_rot = best_of(rot)
    t_ctl = best_of(ctl)
    t_comm = max(t_rot - t_ctl, 1e-9)
    round_bytes_per_device = 2 * shard_bytes  # both directions, full duplex
    return {
        "method": "jitted bidirectional ppermute rotation, best of %d, "
                  "minus no-comm control" % reps,
        "platform": jax.devices()[0].platform,
        "num_shards": num_shards,
        "shard_bytes": shard_bytes,
        "round_seconds": round(t_comm, 6),
        "control_seconds": round(t_ctl, 6),
        "exchange_GB_per_sec_per_link": round(
            round_bytes_per_device / t_comm / 1e9, 3),
        "exchange_GB_per_sec_aggregate": round(
            num_shards * round_bytes_per_device / t_comm / 1e9, 3),
    }
