"""Ring exchange engine — the unordered variant's communication core.

TPU-native re-design of the reference's MPI ring
(unorderedDataVariant.cu:173-205): R ranks each hold a tree shard and a set of
stationary queries with persistent candidate heaps; each round every rank
queries the currently-resident shard, then passes it to ``(rank+1) % R`` and
receives from ``(rank-1+size) % R``. After R rounds every shard has visited
every rank and each heap holds the global top-k. This is the same
communication/accumulation shape as ring attention (stationary Q, rotating
K/V, running accumulator) and maps 1:1 onto a ``lax.ppermute`` over the ICI
ring inside ``shard_map``.

Deliberate improvements over the reference (not bugs to replicate):

- The reference serializes each round: ``MPI_Waitall`` completes before the
  kernel launches and ``cudaDeviceSynchronize`` before the next Isend
  (unorderedDataVariant.cu:187-204). Here the next shard's ``ppermute`` is
  issued *before* the current shard's query update and depends only on the
  incoming buffer, so XLA's latency-hiding scheduler overlaps communication
  with compute.
- The reference exchanges per-round point counts as a separate message pair
  (unorderedDataVariant.cu:183-186). Static SPMD shapes make counts
  compile-time constants: every shard is padded to a uniform size with
  sentinel points whose distances are +inf (core/types.py), generalizing the
  reference's own ``N+1`` slack alloc (:156-158) and the prepartitioned
  variant's pad-to-max trick (prePartitionedDataVariant.cu:251-266).
- 64-bit-safe sizing throughout (the reference's ``int`` arithmetic overflows
  beyond ~2^31 bytes of candidates — SURVEY.md appendix).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from mpi_cuda_largescaleknn_tpu.ops.traverse import knn_update_tree
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary


def _engine_fn(engine: str, query_tile: int, point_tile: int):
    # flat-engine dispatch only; "auto"/"tiled" take the bucketed data path
    # (body_tiled here, the q/shard_state branch in demand_knn) before this
    if engine == "bruteforce":
        return partial(knn_update_bruteforce, query_tile=query_tile,
                       point_tile=point_tile)
    if engine == "tree":
        return knn_update_tree
    if engine == "pallas":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
                knn_update_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas' is unavailable in this build") from e
        return partial(knn_update_pallas, query_tile=query_tile,
                       point_tile=point_tile)
    raise ValueError(f"unknown engine '{engine}'")


def _tiled_engine_fn(engine: str):
    """Bucket-granular fold for the tiled data path: the fused Pallas
    traversal kernel for ``pallas_tiled``, the XLA twin otherwise."""
    if engine == "pallas_tiled":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
                knn_update_tiled_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas_tiled' is unavailable in this build") from e
        return knn_update_tiled_pallas
    return knn_update_tiled


def ring_knn(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray, k: int,
             mesh, *, max_radius: float = jnp.inf, engine: str = "auto",
             query_tile: int = 2048, point_tile: int = 2048,
             bucket_size: int = 512, return_candidates: bool = False):
    """Run the full R-round ring on a 1-D mesh.

    Args:
      points_sharded: f32[R*Npad, 3], shard-major (device i owns rows
        [i*Npad, (i+1)*Npad)), sentinel-padded. Device i's rows serve as both
        its tree shard and its stationary queries (the reference uploads the
        same slab twice — unorderedDataVariant.cu:159-167).
      ids_sharded: i32[R*Npad] global point ids (-1 for padding) that travel
        with the rotating shards so candidate lists can report neighbor
        identities (the reference computes these but discards them).
      k / max_radius: the `-k` / `-r` CLI parameters.

    Returns:
      f32[R*Npad] k-th-NN distances in the same shard-major order (inf for
      padding rows), plus the CandidateState if ``return_candidates``.
    """
    num_shards = mesh.shape[AXIS]
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    update = None if use_tiled else _engine_fn(engine, query_tile, point_tile)
    tiled_update = _tiled_engine_fn(engine) if use_tiled else None
    use_tree = engine == "tree"
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    def body_tiled(pts_local, ids_local):
        npad = pts_local.shape[0]
        q = partition_points(pts_local, ids_local, bucket_size=bucket_size)
        heap = pvary(init_candidates(q.num_buckets * q.bucket_size, k,
                                     max_radius))
        # the rotating "tree" = the bucketed shard + its bucket bounds; pos
        # only matters query-side, so it does not ride the ring
        shard = (q.pts, q.ids, q.lower, q.upper)

        def round_body(_i, carry):
            shard, hd2, hidx = carry
            nxt = jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, fwd), shard)
            resident = q._replace(pts=shard[0], ids=shard[1], lower=shard[2],
                                  upper=shard[3])
            st = tiled_update(CandidateState(hd2, hidx), q, resident)
            return nxt, st.dist2, st.idx

        _, hd2, hidx = jax.lax.fori_loop(
            0, num_shards, round_body, (shard, heap.dist2, heap.idx))
        heap = CandidateState(hd2, hidx)
        bs = (q.num_buckets, q.bucket_size)
        dists = scatter_back(extract_final_result(heap).reshape(bs),
                             q.pos, npad, fill=jnp.inf)
        hd2 = scatter_back(heap.dist2.reshape(bs + (k,)), q.pos, npad,
                           fill=jnp.inf)
        hidx = scatter_back(heap.idx.reshape(bs + (k,)), q.pos, npad, fill=-1)
        return dists, hd2, hidx

    def body_flat(pts_local, ids_local):
        queries = pts_local
        if use_tree:
            shard, shard_ids = build_tree(pts_local, ids_local)
        else:
            shard, shard_ids = pts_local, ids_local
        heap = pvary(init_candidates(queries.shape[0], k, max_radius))

        def round_body(_i, carry):
            shard, shard_ids, hd2, hidx = carry
            # issue the rotation first: the permute depends only on the
            # resident shard, the update only reads it — XLA overlaps them
            nxt = jax.lax.ppermute(shard, AXIS, fwd)
            nxt_ids = jax.lax.ppermute(shard_ids, AXIS, fwd)
            st = update(CandidateState(hd2, hidx), queries, shard, shard_ids)
            return nxt, nxt_ids, st.dist2, st.idx

        _, _, hd2, hidx = jax.lax.fori_loop(
            0, num_shards, round_body,
            (shard, shard_ids, heap.dist2, heap.idx))
        heap = CandidateState(hd2, hidx)
        return extract_final_result(heap), heap.dist2, heap.idx

    body = body_tiled if use_tiled else body_flat

    shard_spec = P(AXIS)
    # interpret-mode pallas kernels re-evaluate a vma-less kernel jaxpr with
    # varying operands, which trips shard_map's vma checker (JAX's own
    # guidance: pass check_vma=False); XLA engines keep the strict typing
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec, shard_spec),
        out_specs=(shard_spec, shard_spec, shard_spec),
        check_vma=not engine.startswith("pallas")))

    sharding = NamedSharding(mesh, shard_spec)
    points_sharded = jax.device_put(points_sharded, sharding)
    ids_sharded = jax.device_put(ids_sharded, sharding)
    dists, hd2, hidx = mapped(points_sharded, ids_sharded)
    if return_candidates:
        return dists, CandidateState(hd2, hidx)
    return dists
