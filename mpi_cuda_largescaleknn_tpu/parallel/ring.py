"""Ring exchange engine — the unordered variant's communication core.

TPU-native re-design of the reference's MPI ring
(unorderedDataVariant.cu:173-205): R ranks each hold a tree shard and a set of
stationary queries with persistent candidate heaps; each round every rank
queries the currently-resident shard, then passes it to ``(rank+1) % R`` and
receives from ``(rank-1+size) % R``. After R rounds every shard has visited
every rank and each heap holds the global top-k. This is the same
communication/accumulation shape as ring attention (stationary Q, rotating
K/V, running accumulator) and maps 1:1 onto a ``lax.ppermute`` over the ICI
ring inside ``shard_map``.

Deliberate improvements over the reference (not bugs to replicate):

- The reference serializes each round: ``MPI_Waitall`` completes before the
  kernel launches and ``cudaDeviceSynchronize`` before the next Isend
  (unorderedDataVariant.cu:187-204). Here the next shard's ``ppermute`` is
  issued *before* the current shard's query update and depends only on the
  incoming buffer, so XLA's latency-hiding scheduler overlaps communication
  with compute.
- The reference exchanges per-round point counts as a separate message pair
  (unorderedDataVariant.cu:183-186). Static SPMD shapes make counts
  compile-time constants: every shard is padded to a uniform size with
  sentinel points whose distances are +inf (core/types.py), generalizing the
  reference's own ``N+1`` slack alloc (:156-158) and the prepartitioned
  variant's pad-to-max trick (prePartitionedDataVariant.cu:251-266).
- 64-bit-safe sizing throughout (the reference's ``int`` arithmetic overflows
  beyond ~2^31 bytes of candidates — SURVEY.md appendix).

Two drivers share one set of per-round builders (``_make_ring_fns``): the
fused ``ring_knn`` (whole ring in one ``lax.fori_loop`` — the default) and
the host-stepped ``ring_knn_stepwise`` (one jitted step per round, enabling
checkpoint/resume between rounds).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_cuda_largescaleknn_tpu.core.types import CandidateState
from mpi_cuda_largescaleknn_tpu.ops.brute_force import knn_update_bruteforce
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from mpi_cuda_largescaleknn_tpu.ops.traverse import knn_update_tree
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary


def _engine_fn(engine: str, query_tile: int, point_tile: int):
    # flat-engine dispatch only; "auto"/"tiled"/"pallas_tiled" take the
    # bucketed data path (_make_ring_fns tiled branch, the q/shard_state
    # branch in demand_knn) before this
    if engine == "bruteforce":
        return partial(knn_update_bruteforce, query_tile=query_tile,
                       point_tile=point_tile)
    if engine == "tree":
        return knn_update_tree
    if engine == "pallas":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_bf import (
                knn_update_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas' is unavailable in this build") from e
        return partial(knn_update_pallas, query_tile=query_tile,
                       point_tile=point_tile)
    raise ValueError(f"unknown engine '{engine}'")


def _tiled_engine_fn(engine: str):
    """Bucket-granular fold for the tiled data path: the fused Pallas
    traversal kernel for ``pallas_tiled``, the XLA twin otherwise."""
    if engine == "pallas_tiled":
        try:
            from mpi_cuda_largescaleknn_tpu.ops.pallas.knn_tiled import (
                knn_update_tiled_pallas,
            )
        except ImportError as e:
            raise ValueError(
                "engine 'pallas_tiled' is unavailable in this build") from e
        return knn_update_tiled_pallas
    return knn_update_tiled


def _make_ring_fns(k, max_radius, engine, query_tile, point_tile, bucket_size,
                   num_shards):
    """(init_fn, round_fn, final_fn) — the per-round pieces both ring
    drivers execute, defined once so the fused and stepwise paths cannot
    diverge.

    - init_fn(pts_local, ids_local) -> (stationary, shard, heap)
    - round_fn(stationary, shard, heap) -> (next_shard, new_heap)
      (issues the rotation before the fold so XLA overlaps them)
    - final_fn(stationary, heap, npad) -> (dists, hd2, hidx) in input-row
      order per shard
    """
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    if use_tiled:
        tiled_update = _tiled_engine_fn(engine)

        def init_fn(pts_local, ids_local):
            q = partition_points(pts_local, ids_local,
                                 bucket_size=bucket_size)
            heap = pvary(init_candidates(q.num_buckets * q.bucket_size, k,
                                         max_radius))
            # the rotating "tree" = the bucketed shard + its bucket bounds;
            # pos only matters query-side, so it does not ride the ring
            shard = (q.pts, q.ids, q.lower, q.upper)
            return q, shard, heap

        def round_fn(q, shard, heap):
            nxt = jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, fwd),
                               shard)
            resident = q._replace(pts=shard[0], ids=shard[1], lower=shard[2],
                                  upper=shard[3])
            return nxt, tiled_update(heap, q, resident)

        def final_fn(q, heap, npad):
            kk = heap.dist2.shape[-1]
            bs = (q.num_buckets, q.bucket_size)
            dists = scatter_back(extract_final_result(heap).reshape(bs),
                                 q.pos, npad, fill=jnp.inf)
            hd2 = scatter_back(heap.dist2.reshape(bs + (kk,)), q.pos, npad,
                               fill=jnp.inf)
            hidx = scatter_back(heap.idx.reshape(bs + (kk,)), q.pos, npad,
                                fill=-1)
            return dists, hd2, hidx
    else:
        update = _engine_fn(engine, query_tile, point_tile)
        use_tree = engine == "tree"

        def init_fn(pts_local, ids_local):
            if use_tree:
                shard = build_tree(pts_local, ids_local)
            else:
                shard = (pts_local, ids_local)
            heap = pvary(init_candidates(pts_local.shape[0], k, max_radius))
            return pts_local, shard, heap

        def round_fn(queries, shard, heap):
            nxt = jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, fwd),
                               shard)
            return nxt, update(heap, queries, shard[0], shard[1])

        def final_fn(_queries, heap, _npad):
            return extract_final_result(heap), heap.dist2, heap.idx

    return init_fn, round_fn, final_fn


def ring_knn(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray, k: int,
             mesh, *, max_radius: float = jnp.inf, engine: str = "auto",
             query_tile: int = 2048, point_tile: int = 2048,
             bucket_size: int = 512, return_candidates: bool = False):
    """Run the full R-round ring on a 1-D mesh (fused ``lax.fori_loop``).

    Args:
      points_sharded: f32[R*Npad, 3], shard-major (device i owns rows
        [i*Npad, (i+1)*Npad)), sentinel-padded. Device i's rows serve as both
        its tree shard and its stationary queries (the reference uploads the
        same slab twice — unorderedDataVariant.cu:159-167).
      ids_sharded: i32[R*Npad] global point ids (-1 for padding) that travel
        with the rotating shards so candidate lists can report neighbor
        identities (the reference computes these but discards them).
      k / max_radius: the `-k` / `-r` CLI parameters.

    Returns:
      f32[R*Npad] k-th-NN distances in the same shard-major order (inf for
      padding rows), plus the CandidateState if ``return_candidates``.
    """
    num_shards = mesh.shape[AXIS]
    init_fn, round_fn, final_fn = _make_ring_fns(
        k, max_radius, engine, query_tile, point_tile, bucket_size,
        num_shards)

    def body(pts_local, ids_local):
        stationary, shard, heap = init_fn(pts_local, ids_local)

        def round_body(_i, carry):
            shard, hd2, hidx = carry
            nxt, st = round_fn(stationary, shard, CandidateState(hd2, hidx))
            return nxt, st.dist2, st.idx

        _, hd2, hidx = jax.lax.fori_loop(
            0, num_shards, round_body, (shard, heap.dist2, heap.idx))
        return final_fn(stationary, CandidateState(hd2, hidx),
                        pts_local.shape[0])

    shard_spec = P(AXIS)
    # interpret-mode pallas kernels re-evaluate a vma-less kernel jaxpr with
    # varying operands, which trips shard_map's vma checker (JAX's own
    # guidance: pass check_vma=False); XLA engines keep the strict typing
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec, shard_spec),
        out_specs=(shard_spec, shard_spec, shard_spec),
        check_vma=not engine.startswith("pallas")))

    sharding = NamedSharding(mesh, shard_spec)
    points_sharded = jax.device_put(points_sharded, sharding)
    ids_sharded = jax.device_put(ids_sharded, sharding)
    dists, hd2, hidx = mapped(points_sharded, ids_sharded)
    if return_candidates:
        return dists, CandidateState(hd2, hidx)
    return dists


def ring_knn_stepwise(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray,
                      k: int, mesh, *, max_radius: float = jnp.inf,
                      engine: str = "auto", query_tile: int = 2048,
                      point_tile: int = 2048, bucket_size: int = 512,
                      checkpoint_dir: str | None = None,
                      checkpoint_every: int = 1,
                      max_rounds: int | None = None,
                      return_candidates: bool = False):
    """``ring_knn`` with host-controlled rounds + checkpoint/resume.

    Identical results to ``ring_knn`` (literally the same ``_make_ring_fns``
    per-round pieces), but the round loop runs on the host — one jitted
    shard_map step per round — so the persistent heaps and the resident
    rotating shard can be snapshotted between rounds and a preempted run
    resumed at the exact round it lost. The reference cannot do this (one
    pass, output only at the end, SURVEY.md §5); its candidate buffer is the
    natural checkpoint state and here it literally is the checkpoint.

    The checkpoint fingerprint includes a sampled digest of the input data;
    a successful full run clears its checkpoint so a later run cannot
    silently reuse stale results. ``max_rounds`` stops early (state saved if
    checkpointing), for staged runs and interruption tests.

    Returns f32[R*Npad] k-th-NN distances (numpy), shard-major like
    ``ring_knn``.
    """
    from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt

    num_shards = mesh.shape[AXIS]
    init_fn, round_fn, final_fn = _make_ring_fns(
        k, max_radius, engine, query_tile, point_tile, bucket_size,
        num_shards)
    spec = P(AXIS)
    check_vma = not engine.startswith("pallas")
    npad_local = points_sharded.shape[0] // num_shards

    def smap(fn, n_in, out_structs):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * n_in, out_specs=out_structs,
            check_vma=check_vma))

    sharding = NamedSharding(mesh, spec)
    pts = jax.device_put(points_sharded, sharding)
    ids = jax.device_put(ids_sharded, sharding)

    fp = None
    if checkpoint_dir:
        fp = ckpt.fingerprint(
            n=int(pts.shape[0]), k=int(k), shards=num_shards, engine=engine,
            max_radius=float(max_radius), bucket_size=bucket_size,
            data=ckpt.data_digest(points_sharded, ids_sharded))

    stationary, shard, heap = smap(init_fn, 2, (spec, spec, spec))(pts, ids)
    step = smap(round_fn, 3, (spec, spec))

    start = 0
    if checkpoint_dir:
        got = ckpt.load_ring_state(checkpoint_dir, fp)
        if got is not None:
            start, arrs = got
            flat, treedef = jax.tree.flatten((shard, heap))
            restored = [jax.device_put(arrs[f"a{i}"], sharding)
                        for i in range(len(flat))]
            shard, heap = jax.tree.unflatten(treedef, restored)

    stop = num_shards if max_rounds is None else min(max_rounds, num_shards)
    for r in range(start, stop):
        shard, heap = step(stationary, shard, heap)
        if checkpoint_dir and ((r + 1) % checkpoint_every == 0
                               or r + 1 == stop):
            flat, _ = jax.tree.flatten((shard, heap))
            jax.block_until_ready(flat)
            ckpt.save_ring_state(checkpoint_dir, r + 1,
                                 {f"a{i}": a for i, a in enumerate(flat)}, fp)

    dists, hd2, hidx = smap(
        lambda s, h: final_fn(s, h, npad_local), 2,
        (spec, spec, spec))(stationary, heap)
    if checkpoint_dir and stop == num_shards:
        # done: clear so a later (possibly different-data) run in the same
        # dir can never resume past its own work
        ckpt.clear(checkpoint_dir)
    if return_candidates:
        return np.asarray(dists), CandidateState(hd2, hidx)
    return np.asarray(dists)
