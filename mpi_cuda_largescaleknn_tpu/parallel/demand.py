"""Bounds-pruned early-exit engine — the prepartitioned variant's core.

The reference (prePartitionedDataVariant.cu:304-357) runs, per round: each
rank picks the *nearest not-yet-seen* peer whose bounding box is closer than
its current worst k-th-NN distance (``computeMyPeer``, :157-174), Allgathers
the picks, exits globally when every pick is -1 (:320-322), then transfers
trees point-to-point (one Irecv, fan-out Isends, :324-345) and re-queries.
The win on spatially-coherent data: most ranks stop after visiting a handful
of neighbors instead of all R.

TPU-native re-design (NOT a translation of the MPI matching): data-dependent
point-to-point routing does not exist under XLA's static SPMD model — and on
an ICI torus it buys little, because a neighbor ``ppermute`` is cheap and
overlaps with compute. What actually costs time is the *query kernel*. So:

- shards rotate on the same static ring as parallel/ring.py;
- each device *skips the kernel* (``lax.cond``) for any arriving shard whose
  bounding box is at least its current worst radius away — the same prune
  predicate as ``computeMyPeer`` (box-distance >= cutoff, :168), evaluated
  against ``all_gather``-ed bounds (the reference Allgathers bounds the same
  way, :290-291);
- the whole loop is a ``lax.while_loop`` whose continue flag is a ``pmax``
  over "does any device still need any unseen shard" — the global early exit
  (:320-322) without a host round-trip;
- the per-query worst-radius reduction that the reference maintains with a
  managed-memory float + ``cukd::atomicMax`` (:91-94, :297-298) is a masked
  ``jnp.max`` over the candidate state each round.

Trade-off vs the reference, stated honestly: the reference visits peers
nearest-first (tightening the prune radius fastest) and can stop after its
*own* needs are met; the ring visits in fixed order and runs until the
*slowest* device is done, but pays only a skipped-kernel's cost (~0) for
unneeded shards and keeps every transfer on neighbor ICI links instead of
arbitrary point-to-point routes. For the reference's own early-exit-friendly
regime (spatially pre-partitioned files, README.md:17-23) both stop after
max-over-ranks(#needed-peers) rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_cuda_largescaleknn_tpu.core.types import (
    PAD_SENTINEL,
    CandidateState,
    aabb_box_distance,
    aabb_of_points,
)
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    current_worst_radius,
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    partition_points,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import knn_update_tiled
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary
from mpi_cuda_largescaleknn_tpu.parallel.ring import (
    _engine_fn,
    _tiled_engine_fn,
)


def demand_knn(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray, k: int,
               mesh, *, max_radius: float = jnp.inf,
               engine: str = "auto", query_tile: int = 2048,
               point_tile: int = 2048, bucket_size: int = 512,
               return_stats: bool = False):
    """Bounds-pruned kNN over pre-partitioned shards on a 1-D mesh.

    Same data contract as ring_knn (shard-major padded rows); additionally
    returns, when ``return_stats``, the number of rounds executed and the
    per-device count of query kernels actually run — the observability the
    reference only exposes as per-round stdout prints (:306).
    """
    num_shards = mesh.shape[AXIS]
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    update = None if use_tiled else _engine_fn(engine, query_tile, point_tile)
    tiled_update = _tiled_engine_fn(engine) if use_tiled else None
    use_tree = engine == "tree"
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    def body(pts_local, ids_local):
        me = jax.lax.axis_index(AXIS)
        npad = pts_local.shape[0]
        valid = pts_local[:, 0] < PAD_SENTINEL / 2
        if use_tiled:
            # bucketed structures: queries and the rotating shard both carry
            # per-bucket bounds; the tile-level prune inside knn_update_tiled
            # subsumes most of the shard-level skip, which remains as a
            # cheap outer gate
            q = partition_points(pts_local, ids_local,
                                 bucket_size=bucket_size)
            queries = None
            shard_state = (q.pts, q.ids, q.lower, q.upper)
            heap_rows = q.num_buckets * q.bucket_size
            heap_valid = (q.ids >= 0).reshape(-1)
        elif use_tree:
            queries = pts_local
            shard, shard_ids = build_tree(pts_local, ids_local)
            shard_state = (shard, shard_ids)
            heap_rows, heap_valid = npad, valid
        else:
            queries = pts_local
            shard_state = (pts_local, ids_local)
            heap_rows, heap_valid = npad, valid

        # bounds of every shard's real points, replicated to all devices
        # (the reference's Allgather of 6-float boxes, :290-291)
        box = aabb_of_points(pts_local, valid)
        all_lower = jax.lax.all_gather(box.lower, AXIS)   # [R, 3]
        all_upper = jax.lax.all_gather(box.upper, AXIS)
        # min distance from MY queries' box to every shard's box
        box_dist = aabb_box_distance(box.lower[None, :], box.upper[None, :],
                                     all_lower, all_upper)  # [R]
        # shard s arrives at this device in round (me - s) mod R
        arrival_round = jnp.mod(me - jnp.arange(num_shards), num_shards)

        heap = pvary(init_candidates(heap_rows, k, max_radius))

        def cond(carry):
            _shard, _hd2, _hidx, rnd, keep_going, _nrun = carry
            return (rnd < num_shards) & keep_going

        def round_body(carry):
            shard_state, hd2, hidx, rnd, _kg, nrun = carry
            nxt = jax.tree.map(lambda a: jax.lax.ppermute(a, AXIS, fwd),
                               shard_state)

            cur_radius = current_worst_radius(CandidateState(hd2, hidx),
                                              heap_valid)
            src = jnp.mod(me - rnd, num_shards)
            # visit iff the resident shard's box is strictly closer than the
            # current worst k-th distance (computeMyPeer's prune, :168);
            # round 0 is the own shard at distance 0
            do_visit = jax.lax.dynamic_index_in_dim(
                box_dist, src, keepdims=False) < cur_radius

            def run(_):
                if use_tiled:
                    resident = q._replace(
                        pts=shard_state[0], ids=shard_state[1],
                        lower=shard_state[2], upper=shard_state[3])
                    st = tiled_update(CandidateState(hd2, hidx), q,
                                      resident)
                else:
                    st = update(CandidateState(hd2, hidx), queries,
                                *shard_state)
                return st.dist2, st.idx

            hd2, hidx = jax.lax.cond(do_visit, run, lambda _: (hd2, hidx), None)
            nrun = nrun + do_visit.astype(jnp.int32)

            # global early exit: does ANY device still need ANY unseen shard?
            new_radius = current_worst_radius(CandidateState(hd2, hidx),
                                              heap_valid)
            i_need_more = jnp.any((arrival_round > rnd) & (box_dist < new_radius))
            keep_going = jax.lax.pmax(i_need_more.astype(jnp.int32), AXIS) > 0
            return nxt, hd2, hidx, rnd + 1, keep_going, nrun

        # rnd and keep_going are uniform across devices (keep_going is a pmax
        # reduction, hence replicated); nrun is per-device
        init = (shard_state, heap.dist2, heap.idx,
                jnp.int32(0), jnp.bool_(True), pvary(jnp.int32(0)))
        _, hd2, hidx, rounds, _, nrun = jax.lax.while_loop(cond, round_body, init)
        heap = CandidateState(hd2, hidx)
        dists = extract_final_result(heap)
        if use_tiled:
            bs = (q.num_buckets, q.bucket_size)
            dists = scatter_back(dists.reshape(bs), q.pos, npad, fill=jnp.inf)
            hd2 = scatter_back(hd2.reshape(bs + (k,)), q.pos, npad,
                               fill=jnp.inf)
            hidx = scatter_back(hidx.reshape(bs + (k,)), q.pos, npad, fill=-1)
        return dists, hd2, hidx, pvary(rounds)[None], nrun[None]

    spec = P(AXIS)
    # see ring.py: pallas engines need check_vma=False under shard_map
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec),
        out_specs=(spec, spec, spec, spec, spec),
        check_vma=not engine.startswith("pallas")))

    sharding = NamedSharding(mesh, spec)
    points_sharded = jax.device_put(points_sharded, sharding)
    ids_sharded = jax.device_put(ids_sharded, sharding)
    dists, hd2, hidx, rounds, nrun = mapped(points_sharded, ids_sharded)
    if return_stats:
        return dists, CandidateState(hd2, hidx), {
            "rounds": rounds, "kernels_run": nrun}
    return dists
