"""Bounds-pruned early-exit engine — the prepartitioned variant's core.

The reference (prePartitionedDataVariant.cu:304-357) runs, per round: each
rank picks the *nearest not-yet-seen* peer whose bounding box is closer than
its current worst k-th-NN distance (``computeMyPeer``, :157-174), Allgathers
the picks, exits globally when every pick is -1 (:320-322), then transfers
trees point-to-point (one Irecv, fan-out Isends, :324-345) and re-queries.
The win on spatially-coherent data: most ranks stop after visiting a handful
of neighbors instead of all R.

TPU-native re-design (NOT a translation of the MPI matching): data-dependent
point-to-point routing does not exist under XLA's static SPMD model — and on
an ICI torus it buys little, because a neighbor ``ppermute`` is cheap and
overlaps with compute. What actually costs time is the *query kernel*. So:

- shards rotate on the same static ring as parallel/ring.py;
- each device *skips the kernel* (``lax.cond``) for any arriving shard whose
  bounding box is at least its current worst radius away — the same prune
  predicate as ``computeMyPeer`` (box-distance >= cutoff, :168), evaluated
  against ``all_gather``-ed bounds (the reference Allgathers bounds the same
  way, :290-291);
- the loop ends when a ``pmax`` over "does any device still need any unseen
  shard" goes to zero — the global early exit (:320-322);
- the per-query worst-radius reduction that the reference maintains with a
  managed-memory float + ``cukd::atomicMax`` (:91-94, :297-298) is a masked
  ``jnp.max`` over the candidate state each round.

The ring is BIDIRECTIONAL: two copies of each tree counter-rotate (one
``ppermute`` forward, one backward), so after round r every device has seen
all shards within ±r of its own. Round-4 measurement motivated this: with a
forward-only ring (arrival round of shard s = (me - s) mod R) on
spatially-sorted partitions, a device's following neighbor (index i+1)
arrived LAST (round R-1) even though spatial locality makes it needed on
round one — so the early exit never fired (64 rounds measured vs 33 for
the reference's best schedule at 64 shards; after this change, 21 —
benchmarks_report.json). Needed peers cluster around ±max_offset, and
counter-rotation reaches offset o in round o: the loop runs at most
floor(R/2)+1 rounds and the exit fires after max needed offset rounds.
Total bytes moved are the same (2 trees/round x ~R/2 rounds); per-round
link traffic doubles.

Trade-off vs the reference, stated honestly: the reference visits peers
nearest-first (tightening the prune radius fastest) and can stop after its
*own* needs are met; the bidirectional ring visits in ±1, ±2, ... order —
which IS nearest-first in shard-index space, the right proxy when
partitions are spatially sorted — runs until the *slowest* device is done,
pays only a skipped-kernel's cost (~0) for unneeded shards, and keeps every
transfer on neighbor ICI links instead of arbitrary point-to-point routes.
The per-rank stop the reference gets for free (:315-322) is recovered at
direction granularity: each counter-rotating copy's ``ppermute`` is gated
off (``lax.cond``) once no device needs a future delivery from that
direction, so tail rounds — including the otherwise-discarded final
rotation — stop paying exchange bytes (``rotations_run`` in the stats
measures exactly what was paid).
Visiting two peers per round, it can finish in ceil(max_needed/2)+1 rounds
where the reference's one-tree-per-round matching needs max_needed+1.

Like the ring, the fused on-device loop (``demand_knn``) and the host-stepped
checkpointable driver (``demand_knn_stepwise``) share one set of builders
(``_make_demand_fns``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_cuda_largescaleknn_tpu.core.types import (
    PAD_SENTINEL,
    CandidateState,
    aabb_box_distance,
    aabb_of_points,
)
from mpi_cuda_largescaleknn_tpu.ops.build_tree import build_tree
from mpi_cuda_largescaleknn_tpu.ops.candidates import (
    current_worst_radius,
    extract_final_result,
    init_candidates,
)
from mpi_cuda_largescaleknn_tpu.ops.partition import (
    BucketedPoints,
    coarsen_buckets,
    scatter_back,
)
from mpi_cuda_largescaleknn_tpu.ops.tiled import warm_start_self
from mpi_cuda_largescaleknn_tpu.parallel.mesh import AXIS, pvary
from mpi_cuda_largescaleknn_tpu.parallel.ring import (
    _effective_group,
    _engine_fn,
    _tiled_engine_fn,
    partition_sharded,
    resolve_bucket_size,
    resolve_engine,
    ring_total_rounds,
)


def gathered_bounds_fn(pts_local):
    """Per-shard AABB of real points, Allgather-ed to every device
    (the reference's Allgather of 6-float boxes, :290-291). Runs inside
    shard_map."""
    valid = pts_local[:, 0] < PAD_SENTINEL / 2
    box = aabb_of_points(pts_local, valid)
    all_lower = jax.lax.all_gather(box.lower, AXIS)   # [R, 3]
    all_upper = jax.lax.all_gather(box.upper, AXIS)
    return all_lower, all_upper


def _make_demand_fns(k, max_radius, engine, query_tile, point_tile,
                     bucket_size, num_shards, warm_start=False,
                     point_group=1):
    """Per-round builders shared by the fused, stepwise, and chunked demand
    drivers. Returns (init_fn, round_fn, final_fn, shard_init_fn,
    query_init_fn, init_from_q, query_init_from_q);
    for tiled engines the first/fourth/fifth are None (the partition is
    hoisted — use the *_from_q forms with ring.partition_sharded), for flat
    engines the *_from_q forms are None.

    - init_fn(pts_local, ids_local) -> (ctx, shard_state, heap)
      ctx = (stationary queries, replicated box distances, arrival schedule,
      heap validity) — everything the loop reads but never writes.
    - shard_init_fn(pts_local, ids_local) -> (shard_state, all_lo, all_hi)
      (tree side + the Allgather-ed full-shard bounds)
    - query_init_fn(qpts, qids, all_lo, all_hi) -> (ctx, heap)
      (query side only — may be a chunk of the slab; its prune distances
      use the CHUNK's own box, which is tighter than the slab's)
    - round_fn(ctx, shard_state, heap, rnd, counts)
        -> (next_shard, new_heap, rnd+1, counts', keep_going)
      counts is a per-device i32[2]: [query kernels run, direction-rotations
      run] — the second times shard_bytes is the exchange traffic actually
      paid, since each direction's ppermute is gated off once no device
      needs future deliveries from it. keep_going is replicated (pmax) —
      usable as a while_loop predicate on device or read on the host by the
      stepwise driver.
    - final_fn(ctx, heap) -> (dists, hd2, hidx) in input-row order.
    """
    use_tiled = engine in ("tiled", "auto", "pallas_tiled")
    update = None if use_tiled else _engine_fn(engine, query_tile, point_tile)
    tiled_update = _tiled_engine_fn(engine) if use_tiled else None
    # warm start needs query bucket b == resident bucket b in round 0 (the
    # self-join init path on one shared partition) and pays only where
    # fold passes are the cost — the Pallas kernel, not the sort-merge
    # twin (measured regression on the twin: see ring.py _make_ring_fns)
    warm_start = warm_start and engine == "pallas_tiled"
    use_tree = engine == "tree"
    fwd = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    bwd = [(i, (i - 1) % num_shards) for i in range(num_shards)]

    def shard_init_fn(pts_local, ids_local):
        if use_tree:
            shard_state = build_tree(pts_local, ids_local)
        else:
            shard_state = (pts_local, ids_local)
        return (shard_state,) + gathered_bounds_fn(pts_local)

    def query_init_from_q(qpts, q, all_lower, all_upper):
        # bucketed structures: queries and the rotating shard both carry
        # per-bucket bounds; the tile-level prune inside the tiled update
        # subsumes most of the shard-level skip, which remains as a
        # cheap outer gate
        heap_rows = q.pts.shape[0] * q.pts.shape[1]
        heap_valid = (q.ids >= 0).reshape(-1)
        return _query_ctx(qpts, q, heap_rows, heap_valid,
                          all_lower, all_upper)

    def query_init_fn(qpts, qids, all_lower, all_upper):
        return _query_ctx(qpts, qpts, qpts.shape[0],
                          qpts[:, 0] < PAD_SENTINEL / 2,
                          all_lower, all_upper)

    def _query_ctx(qpts, stationary, heap_rows, heap_valid,
                   all_lower, all_upper):
        me = jax.lax.axis_index(AXIS)
        valid = qpts[:, 0] < PAD_SENTINEL / 2
        # min distance from MY queries' box to every shard's box
        qbox = aabb_of_points(qpts, valid)
        box_dist = aabb_box_distance(qbox.lower[None, :], qbox.upper[None, :],
                                     all_lower, all_upper)  # [R]
        # counter-rotating copies: shard s reaches this device in round
        # min((me - s) mod R, (s - me) mod R)
        off = jnp.mod(me - jnp.arange(num_shards), num_shards)
        arrival_round = jnp.minimum(off, num_shards - off)

        heap = pvary(init_candidates(heap_rows, k, max_radius))
        ctx = (stationary, box_dist, arrival_round, heap_valid)
        return ctx, heap

    def init_from_q(pts_local, q):
        # point side: group-coarsened view of the same partition (tight
        # fine-bucket prune radius, point_group x wider resident tiles)
        pc = coarsen_buckets(q, point_group)
        shard_state = (pc.pts, pc.ids, pc.lower, pc.upper)
        all_lower, all_upper = gathered_bounds_fn(pts_local)
        ctx, heap = query_init_from_q(pts_local, q, all_lower, all_upper)
        if warm_start:
            # exact top-k of every query's own (containing) resident
            # bucket (ops/tiled.py, rows stay in fine order — the
            # coarsening is a reshape); round 0's own-shard visit then
            # masks that bucket
            heap = warm_start_self(pc, k, max_radius)
        return ctx, (shard_state, shard_state), heap

    def init_fn(pts_local, ids_local):
        shard_state, all_lower, all_upper = shard_init_fn(pts_local,
                                                          ids_local)
        ctx, heap = query_init_fn(pts_local, ids_local, all_lower, all_upper)
        # the rotating "tree" travels twice: forward and backward copies
        return ctx, (shard_state, shard_state), heap

    if use_tiled:
        init_fn = shard_init_fn = query_init_fn = None
    else:
        init_from_q = query_init_from_q = None

    def round_fn(ctx, shard_pair, heap, rnd, counts):
        stationary, box_dist, arrival_round, heap_valid = ctx
        me = jax.lax.axis_index(AXIS)
        f_state, b_state = shard_pair
        total = ring_total_rounds(num_shards)

        # Per-direction rotation gating (the per-rank stop semantics of
        # prePartitionedDataVariant.cu:315-322, recovered at direction
        # granularity): each direction's ppermute runs only while SOME device
        # still needs a FUTURE delivery from it. The need test uses the
        # ROUND-ENTRY radius — no new fold result, so XLA can still overlap
        # the rotation with this round's kernels — and radii only shrink, so
        # a False is sticky: skipping the rotation can never starve a later
        # visit (the visit gate below would evaluate False for those arrivals
        # anyway). Forward delivers offsets 1..R//2 (rounds < total);
        # backward the same except the dup round (even R) is forward-only.
        idx = jnp.arange(num_shards)
        off_f = jnp.mod(me - idx, num_shards)   # fwd copy of s arrives then
        off_b = jnp.mod(idx - me, num_shards)
        cur_radius = current_worst_radius(heap, heap_valid)
        bwd_total = total - 1 if num_shards % 2 == 0 else total
        # one pmax for both direction bits: two sequential scalar
        # collectives here would sit on the critical path ahead of the
        # very rotations the gate exists to cheapen
        need = jax.lax.pmax(jnp.stack([
            jnp.any((off_f > rnd) & (off_f < total)
                    & (box_dist < cur_radius)),
            jnp.any((off_b > rnd) & (off_b < bwd_total)
                    & (box_dist < cur_radius))]).astype(jnp.int32), AXIS)
        need_f, need_b = need[0] > 0, need[1] > 0

        def rot(perm):
            return lambda s: jax.tree.map(
                lambda a: jax.lax.ppermute(a, AXIS, perm), s)

        nxt = (jax.lax.cond(need_f, rot(fwd), lambda s: s, f_state),
               jax.lax.cond(need_b, rot(bwd), lambda s: s, b_state))

        src_f = jnp.mod(me - rnd, num_shards)
        src_b = jnp.mod(me + rnd, num_shards)
        dup = src_f == src_b  # round 0 (own shard) and round R/2 (R even)

        def run(shard_state, heap, sskip=None):
            if use_tiled:
                resident = BucketedPoints(
                    shard_state[0], shard_state[1], shard_state[2],
                    shard_state[3], shard_state[1])
                st = tiled_update(heap, stationary, resident,
                                  skip_self=sskip, self_group=point_group)
            else:
                st = update(heap, stationary, *shard_state)
            return st.dist2, st.idx

        # visit iff the resident shard's box is strictly closer than the
        # current worst k-th distance (computeMyPeer's prune, :168);
        # round 0 is the own shard at distance 0. The forward visit
        # tightens the radius before the backward visit is decided — the
        # same greedy tightening the reference gets from nearest-first.
        visit_f = jax.lax.dynamic_index_in_dim(
            box_dist, src_f, keepdims=False) < cur_radius
        # round 0's forward arrival is the own shard: with a warm-started
        # heap its self buckets are already folded and must be masked
        sskip = ((rnd == 0).astype(jnp.int32) if warm_start else None)
        hd2, hidx = jax.lax.cond(visit_f,
                                 lambda _: run(f_state, heap, sskip),
                                 lambda _: (heap.dist2, heap.idx), None)
        heap1 = CandidateState(hd2, hidx)

        radius1 = current_worst_radius(heap1, heap_valid)
        visit_b = (~dup) & (jax.lax.dynamic_index_in_dim(
            box_dist, src_b, keepdims=False) < radius1)
        hd2, hidx = jax.lax.cond(visit_b, lambda _: run(b_state, heap1),
                                 lambda _: (heap1.dist2, heap1.idx), None)
        new_heap = CandidateState(hd2, hidx)
        # counts = [kernels run, direction-rotations run] per device; the
        # second measures the bytes actually moved (x shard_bytes) so the
        # gating's savings are a reported stat, not a claim
        counts = counts + jnp.stack(
            [visit_f.astype(jnp.int32) + visit_b.astype(jnp.int32),
             need_f.astype(jnp.int32) + need_b.astype(jnp.int32)])

        # global early exit: does ANY device still need ANY unseen shard?
        new_radius = current_worst_radius(new_heap, heap_valid)
        i_need_more = jnp.any((arrival_round > rnd) & (box_dist < new_radius))
        keep_going = jax.lax.pmax(i_need_more.astype(jnp.int32), AXIS) > 0
        return nxt, new_heap, rnd + 1, counts, keep_going

    def final_fn(ctx, heap):
        stationary, _box, _arr, _hv = ctx
        dists = extract_final_result(heap)
        if use_tiled:
            q = stationary
            # scatter back to input-row order over B*S rows (an upper bound
            # on the padded slab size — input rows live in [0, npad), the
            # drivers trim with _trim_rows)
            rows = q.pos.shape[0] * q.pos.shape[1]
            kk = heap.dist2.shape[-1]
            bs = (q.num_buckets, q.bucket_size)
            dists = scatter_back(dists.reshape(bs), q.pos, rows,
                                 fill=jnp.inf)
            hd2 = scatter_back(heap.dist2.reshape(bs + (kk,)), q.pos, rows,
                               fill=jnp.inf)
            hidx = scatter_back(heap.idx.reshape(bs + (kk,)), q.pos, rows,
                                fill=-1)
            return dists, hd2, hidx
        return dists, heap.dist2, heap.idx

    return (init_fn, round_fn, final_fn, shard_init_fn, query_init_fn,
            init_from_q, query_init_from_q)


# one bidirectional-sweep definition for both engines (ring.py)
demand_total_rounds = ring_total_rounds


def demand_knn(points_sharded: jnp.ndarray, ids_sharded: jnp.ndarray, k: int,
               mesh, *, max_radius: float = jnp.inf,
               engine: str = "auto", query_tile: int = 2048,
               point_tile: int = 2048, bucket_size: int = 0,
               point_group: int = 0, return_stats: bool = False):
    """Bounds-pruned kNN over pre-partitioned shards on a 1-D mesh (fused
    on-device ``lax.while_loop``).

    Same data contract as ring_knn (shard-major padded rows); additionally
    returns, when ``return_stats``, the number of rounds executed and the
    per-device count of query kernels actually run — the observability the
    reference only exposes as per-round stdout prints (:306).
    """
    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    npad = points_sharded.shape[0] // num_shards
    point_group = _effective_group(point_group, npad, bucket_size, engine)
    init_fn, round_fn, final_fn, _sif, _qif, init_from_q, _qfq = \
        _make_demand_fns(k, max_radius, engine, query_tile, point_tile,
                         bucket_size, num_shards, warm_start=True,
                         point_group=point_group)

    def body(pts_local, ids_local, q_local=None):
        if q_local is not None:
            ctx, shard_state, heap = init_from_q(pts_local, q_local)
        else:
            ctx, shard_state, heap = init_fn(pts_local, ids_local)

        total = demand_total_rounds(num_shards)

        def cond(carry):
            _s, _h2, _hi, rnd, keep_going, _n = carry
            return (rnd < total) & keep_going

        def loop_body(carry):
            shard_state, hd2, hidx, rnd, _kg, counts = carry
            nxt, heap2, rnd2, counts2, keep_going = round_fn(
                ctx, shard_state, CandidateState(hd2, hidx), rnd, counts)
            return nxt, heap2.dist2, heap2.idx, rnd2, keep_going, counts2

        # rnd and keep_going are uniform across devices (keep_going is a pmax
        # reduction, hence replicated); counts is per-device
        init = (shard_state, heap.dist2, heap.idx,
                jnp.int32(0), jnp.bool_(True),
                pvary(jnp.zeros(2, jnp.int32)))
        _, hd2, hidx, rounds, _, counts = jax.lax.while_loop(
            cond, loop_body, init)
        d, hd2, hidx = final_fn(ctx, CandidateState(hd2, hidx))
        d, hd2, hidx = _trim_rows(d, hd2, hidx, npad)
        return d, hd2, hidx, pvary(rounds)[None], counts[None]

    spec = P(AXIS)
    n_args = 3 if init_from_q is not None else 2
    # see ring.py: pallas engines need check_vma=False under shard_map
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) * n_args,
        out_specs=(spec, spec, spec, spec, spec),
        check_vma=not engine.startswith("pallas")))

    sharding = NamedSharding(mesh, spec)
    points_sharded = jax.device_put(points_sharded, sharding)
    ids_sharded = jax.device_put(ids_sharded, sharding)
    if init_from_q is not None:
        q_parts = partition_sharded(points_sharded, ids_sharded, mesh,
                                    bucket_size)
        dists, hd2, hidx, rounds, counts = mapped(points_sharded, ids_sharded,
                                                  q_parts)
    else:
        dists, hd2, hidx, rounds, counts = mapped(points_sharded, ids_sharded)
    if return_stats:
        counts = np.asarray(counts)                   # [R, 2]
        return dists, CandidateState(hd2, hidx), {
            "rounds": rounds, "kernels_run": counts[:, 0],
            "rotations_run": counts[:, 1]}
    return dists


def _trim_rows(d, hd2, hidx, npad):
    """Cut the tiled path's scatter target (B*S rows) down to the caller's
    padded slab size; flat paths are already npad rows."""
    return d[:npad], hd2[:npad], hidx[:npad]


def demand_knn_stepwise(points_sharded: jnp.ndarray,
                        ids_sharded: jnp.ndarray, k: int, mesh, *,
                        max_radius: float = jnp.inf, engine: str = "auto",
                        query_tile: int = 2048, point_tile: int = 2048,
                        bucket_size: int = 0, point_group: int = 0,
                        checkpoint_dir: str | None = None,
                        checkpoint_every: int = 1,
                        max_rounds: int | None = None,
                        return_stats: bool = False):
    """``demand_knn`` with host-controlled rounds + checkpoint/resume.

    Same builders as the fused driver; the early-exit predicate (a replicated
    pmax) is returned from each jitted step and read on the host, so the
    adaptive round count survives intact. Checkpoint state = (round, rotating
    shard, heaps, per-device kernel counts); the prelude (bounds gather,
    arrival schedule, bucketing) is recomputed deterministically on resume.
    """
    from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt

    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    npad = points_sharded.shape[0] // num_shards
    point_group = _effective_group(point_group, npad, bucket_size, engine)
    spec = P(AXIS)
    check_vma = not engine.startswith("pallas")
    sharding = NamedSharding(mesh, spec)

    def smap(fn, n_in, out_specs):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                                     out_specs=out_specs,
                                     check_vma=check_vma))

    pts = jax.device_put(np.asarray(points_sharded, np.float32), sharding)
    ids = jax.device_put(np.asarray(ids_sharded, np.int32), sharding)

    fp = None
    resuming = False
    if checkpoint_dir:
        fp = ckpt.fingerprint(
            n=int(pts.shape[0]), k=int(k), shards=num_shards, engine=engine,
            max_radius=float(max_radius), bucket_size=bucket_size,
            # key present only when active (G>1): G1 runs keep
            # resumability of checkpoints written before the knob
            # existed; pallas DEFAULT runs resolve to G2 since the
            # round-5 retune (flags to resume older ones:
            # ring.resolve_bucket_size docstring)
            **({"point_group": point_group} if point_group > 1 else {}),
            query_tile=query_tile, point_tile=point_tile,
            # -rg: counts carry [kernels, rotations] — older single-counter
            # checkpoints must not resume into the new shape
            kind="demand-bidir-rg",
            data=ckpt.data_digest(points_sharded, ids_sharded))
        # a resumed run's heap comes from the checkpoint: skip the warm
        # start's per-bucket top-k work instead of computing and
        # discarding it (see ring_knn_stepwise)
        resuming = ckpt.peek_round(checkpoint_dir, fp) is not None

    init_fn, round_fn, final_fn, _sif, _qif, init_from_q, _qfq = \
        _make_demand_fns(k, max_radius, engine, query_tile, point_tile,
                         bucket_size, num_shards, warm_start=not resuming,
                         point_group=point_group)

    if init_from_q is not None:
        q_parts = partition_sharded(pts, ids, mesh, bucket_size)
        ctx, shard_state, heap = smap(init_from_q, 2,
                                      (spec, spec, spec))(pts, q_parts)
    else:
        ctx, shard_state, heap = smap(init_fn, 2,
                                      (spec, spec, spec))(pts, ids)
    nrun = jax.device_put(np.zeros((num_shards, 2), np.int32), sharding)

    def step_fn(ctx, shard_state, heap, rnd_arr, nrun):
        # rnd rides as a per-device [1] array so every input is sharded;
        # keep_going comes back the same way (replicated by construction)
        nxt, heap2, rnd2, counts2, keep_going = round_fn(
            ctx, shard_state, heap, rnd_arr[0], nrun[0])
        return (nxt, heap2, rnd2[None], counts2[None],
                keep_going.astype(jnp.int32)[None])

    step = smap(step_fn, 5, (spec, spec, spec, spec, spec))

    start = 0
    if checkpoint_dir:
        got = ckpt.load_pytree(checkpoint_dir, fp,
                               (shard_state, heap, nrun), sharding)
        if got is not None:
            start, (shard_state, heap, nrun) = got

    rnd_arr = jax.device_put(
        np.full(num_shards, start, np.int32), sharding)
    rounds_done = start
    total = demand_total_rounds(num_shards)
    stop = total if max_rounds is None else min(max_rounds, total)
    # "completed" = nothing left to do (early exit fired, or every shard
    # visited) — as opposed to merely truncated by the max_rounds cap
    completed = start >= total
    finished = start >= stop
    while not finished:
        shard_state, heap, rnd_arr, nrun, kg = step(
            ctx, shard_state, heap, rnd_arr, nrun)
        rounds_done += 1
        keep_going = bool(np.asarray(kg)[0])
        completed = (not keep_going) or rounds_done >= total
        finished = completed or rounds_done >= stop
        # completed runs skip the final save (their checkpoint is cleared
        # below — saving it would be wasted sync + disk IO, and a stale
        # save would make a relaunch redo already-pruned rounds); runs
        # truncated by the round cap always save so a relaunch resumes
        if checkpoint_dir and ((rounds_done % checkpoint_every == 0
                                and not completed)
                               or (finished and not completed)):
            ckpt.save_pytree(checkpoint_dir, rounds_done,
                             (shard_state, heap, nrun), fp)

    d, hd2, hidx = smap(
        lambda c, h: _trim_rows(*final_fn(c, h), npad), 2,
        (spec, spec, spec))(ctx, heap)
    # completed runs clear their checkpoint (stale-state safety); runs
    # truncated by max_rounds keep it so a relaunch resumes
    if checkpoint_dir and completed:
        ckpt.clear(checkpoint_dir)
    if return_stats:
        counts = np.asarray(nrun)                     # [R, 2]
        return (np.asarray(d), CandidateState(np.asarray(hd2),
                                              np.asarray(hidx)),
                {"rounds": np.full(num_shards, rounds_done),
                 "kernels_run": counts[:, 0],
                 "rotations_run": counts[:, 1]})
    return np.asarray(d)


def demand_knn_chunked(points_sharded: jnp.ndarray,
                       ids_sharded: jnp.ndarray, k: int, mesh, *,
                       chunk_rows: int, max_radius: float = jnp.inf,
                       engine: str = "auto", query_tile: int = 2048,
                       point_tile: int = 2048, bucket_size: int = 0,
                       point_group: int = 0,
                       checkpoint_dir: str | None = None,
                       checkpoint_every: int = 1,
                       return_candidates: bool = False,
                       return_stats: bool = False):
    """``demand_knn`` with the query side streamed in fixed-size chunks.

    The k=100-at-scale memory wall applies to the prepartitioned pipeline
    exactly as to the ring (heaps are N*k*8 bytes; at BASELINE config #4's
    full size they exceed HBM): keep every device's full shard resident,
    hold heaps for only ``chunk_rows`` queries at a time. Each chunk runs
    its own bidirectional early-exit loop from a PRISTINE shard pair (the
    original never rotates, so an early exit can leave the traveling
    copies anywhere without corrupting the next chunk), with prune
    distances from the chunk's own (tighter) bounding box. All chunks
    share one compiled step. With ``checkpoint_dir``, completed chunks'
    results persist and a relaunch resumes at the first unfinished chunk.

    Returns f32[R*Npad] shard-major distances (numpy), plus
    (CandidateState, stats) per the flags; ``stats['rounds']`` is the
    per-chunk round count list, ``kernels_run`` sums over chunks.
    """
    from mpi_cuda_largescaleknn_tpu.core.types import PAD_SENTINEL as _PS
    from mpi_cuda_largescaleknn_tpu.utils import checkpoint as ckpt

    engine = resolve_engine(engine)
    bucket_size = resolve_bucket_size(bucket_size, engine)
    num_shards = mesh.shape[AXIS]
    (_ifn, round_fn, final_fn, shard_init_fn, query_init_fn, _ifq,
     query_init_from_q) = \
        _make_demand_fns(k, max_radius, engine, query_tile, point_tile,
                         bucket_size, num_shards)
    spec = P(AXIS)
    check_vma = not engine.startswith("pallas")
    sharding = NamedSharding(mesh, spec)

    points_sharded = np.asarray(points_sharded, np.float32)
    ids_sharded = np.asarray(ids_sharded, np.int32)
    npad = points_sharded.shape[0] // num_shards
    n_chunks = max(1, -(-npad // chunk_rows))
    total_rounds = demand_total_rounds(num_shards)

    def smap(fn, n_in, out_specs):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                                     out_specs=out_specs,
                                     check_vma=check_vma))

    pts = jax.device_put(points_sharded, sharding)
    ids = jax.device_put(ids_sharded, sharding)
    if query_init_from_q is not None:
        # bounds via a tiny smap; shard0 aliases the hoisted partition's
        # arrays directly instead of round-tripping the whole point set
        # through a jit for a second device copy. The resident side is
        # group-coarsened per device (wide tiles, no skip-self needed —
        # see ring_knn_chunked)
        q_full = partition_sharded(pts, ids, mesh, bucket_size)
        pgc = _effective_group(point_group, npad, bucket_size, engine)
        if pgc > 1:
            q_full = smap(partial(coarsen_buckets, group=pgc),
                          1, spec)(q_full)
        all_lo, all_hi = smap(gathered_bounds_fn, 1, (spec, spec))(pts)
        shard0 = (q_full.pts, q_full.ids, q_full.lower, q_full.upper)
        _qinit_q = smap(query_init_from_q, 4, (spec, spec))

        def qinit(qp_glob, qi_glob, lo, hi):
            qq = partition_sharded(qp_glob, qi_glob, mesh, bucket_size)
            return _qinit_q(qp_glob, qq, lo, hi)
    else:
        shard0, all_lo, all_hi = smap(shard_init_fn, 2, (spec, spec, spec))(
            pts, ids)
        qinit = smap(query_init_fn, 4, (spec, spec))

    def step_fn(ctx, f_state, b_state, heap, rnd_arr, nrun):
        nxt, heap2, rnd2, counts2, keep_going = round_fn(
            ctx, (f_state, b_state), heap, rnd_arr[0], nrun[0])
        return (nxt[0], nxt[1], heap2, rnd2[None], counts2[None],
                keep_going.astype(jnp.int32)[None])

    step = smap(step_fn, 6, (spec,) * 6)
    final = smap(lambda c, h: _trim_rows(*final_fn(c, h), chunk_rows), 2,
                 (spec, spec, spec))

    dim = int(points_sharded.shape[-1])
    pts_g = points_sharded.reshape(num_shards, npad, dim)
    ids_g = ids_sharded.reshape(num_shards, npad)
    out_d = np.full((num_shards, npad), np.inf, np.float32)
    # candidate arrays are N*k*12 bytes — the exact memory wall this
    # driver exists to avoid — so they materialize only on request
    out_hd2 = (np.full((num_shards, npad, k), np.inf, np.float32)
               if return_candidates else None)
    out_idx = (np.full((num_shards, npad, k), -1, np.int32)
               if return_candidates else None)
    rounds_per_chunk: list[int] = []
    nrun_total = np.zeros((num_shards, 2), np.int64)

    fp = None
    start_chunk = 0
    if checkpoint_dir:
        fp = ckpt.fingerprint(
            n=int(points_sharded.shape[0]), k=int(k), shards=num_shards,
            engine=engine, max_radius=float(max_radius),
            bucket_size=bucket_size, chunk_rows=chunk_rows,
            query_tile=query_tile, point_tile=point_tile,
            kind="demand-chunked-rg", candidates=bool(return_candidates),
            data=ckpt.data_digest(points_sharded, ids_sharded))
        got = ckpt.load_ring_state(checkpoint_dir, fp)
        if got is not None:
            start_chunk, arrs = got
            out_d = arrs["out_d"]
            rounds_per_chunk = arrs["rounds_per_chunk"].tolist()
            nrun_total = arrs["nrun_total"]
            if return_candidates:
                out_hd2, out_idx = arrs["out_hd2"], arrs["out_idx"]

    for c in range(start_chunk, n_chunks):
        lo = c * chunk_rows
        hi = min(lo + chunk_rows, npad)
        qp = np.full((num_shards, chunk_rows, dim), _PS, np.float32)
        qi = np.full((num_shards, chunk_rows), -1, np.int32)
        qp[:, :hi - lo] = pts_g[:, lo:hi]
        qi[:, :hi - lo] = ids_g[:, lo:hi]
        ctx, heap = qinit(
            jax.device_put(qp.reshape(-1, dim), sharding),
            jax.device_put(qi.reshape(-1), sharding), all_lo, all_hi)
        # pristine pair each chunk: the resident original never rotates
        f_state, b_state = shard0, shard0
        rnd_arr = jax.device_put(np.zeros(num_shards, np.int32), sharding)
        nrun = jax.device_put(np.zeros((num_shards, 2), np.int32), sharding)
        rounds = 0
        while rounds < total_rounds:
            f_state, b_state, heap, rnd_arr, nrun, kg = step(
                ctx, f_state, b_state, heap, rnd_arr, nrun)
            rounds += 1
            if not bool(np.asarray(kg)[0]):
                break
        rounds_per_chunk.append(rounds)
        nrun_total += np.asarray(nrun).astype(np.int64)
        d, hd2, hidx = final(ctx, heap)
        out_d[:, lo:hi] = np.asarray(d).reshape(
            num_shards, chunk_rows)[:, :hi - lo]
        if return_candidates:
            out_hd2[:, lo:hi] = np.asarray(hd2).reshape(
                num_shards, chunk_rows, k)[:, :hi - lo]
            out_idx[:, lo:hi] = np.asarray(hidx).reshape(
                num_shards, chunk_rows, k)[:, :hi - lo]
        # never save the final chunk: the clear below follows immediately,
        # and a stale completed-run checkpoint would otherwise survive a
        # preemption in between (cf. the stepwise driver's same rule); a
        # relaunch then simply redoes the last chunk
        if checkpoint_dir and (c + 1) % checkpoint_every == 0 \
                and c + 1 < n_chunks:
            arrs = {"out_d": out_d,
                    "rounds_per_chunk": np.asarray(rounds_per_chunk,
                                                   np.int64),
                    "nrun_total": nrun_total}
            if return_candidates:
                arrs.update(out_hd2=out_hd2, out_idx=out_idx)
            ckpt.save_ring_state(checkpoint_dir, c + 1, arrs, fp)

    if checkpoint_dir:
        ckpt.clear(checkpoint_dir)
    dists = out_d.reshape(-1)
    cands = (CandidateState(out_hd2.reshape(-1, k), out_idx.reshape(-1, k))
             if return_candidates else None)
    if return_stats:
        return dists, cands, {
            "rounds": np.asarray(rounds_per_chunk),
            "kernels_run": nrun_total[:, 0],
            "rotations_run": nrun_total[:, 1]}
    return dists
