from mpi_cuda_largescaleknn_tpu.parallel.mesh import (  # noqa: F401
    get_mesh,
    initialize_distributed,
    shard_axis_size,
)
from mpi_cuda_largescaleknn_tpu.parallel.ring import ring_knn  # noqa: F401
from mpi_cuda_largescaleknn_tpu.parallel.demand import demand_knn  # noqa: F401
