"""Mesh / distributed lifecycle.

TPU-native replacement of the reference's communication layer L2
(``MPIComm`` + ``MPI_Init``/``MPI_Finalize``, unorderedDataVariant.cu:30-39,
:107, :238):

- ``MPI_Init`` / rank / size       -> ``jax.distributed.initialize`` (multi-
  host only) + a 1-D ``jax.sharding.Mesh`` over all devices; "rank" is the
  mesh axis index, "size" the axis length.
- CUDA-aware ``Isend/Irecv`` of device buffers -> XLA collectives emitted by
  the compiler for ``lax.ppermute``/``all_gather`` inside ``shard_map`` —
  device-to-device over ICI, no host hop, no explicit requests/waits.
- ``MPI_Barrier``                   -> disappears into SPMD program order.
- GPU affinity ``-g`` (``cudaSetDevice(rank % g)``,
  unorderedDataVariant.cu:138-143) -> a no-op: the TPU runtime owns the
  process<->device binding.

Single-host (including the 8-virtual-CPU-device test fixture) and multi-host
paths build the same mesh; on a pod slice the 1-D axis is laid out over ICI by
device order, so the ring permutation rides neighbor links.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh

from mpi_cuda_largescaleknn_tpu.utils.compat import install as _install_compat

_install_compat()  # older jax: bridge jax.shard_map & co before any engine

AXIS = "shards"  # the single mesh axis name used by the engines


def acquire_devices(timeout_s: float | None = None):
    """``jax.devices()`` behind a watchdog so a wedged accelerator tunnel
    fails fast with an actionable message instead of hanging a user CLI.

    Default budget is 300 s (env ``LSK_DEVICE_TIMEOUT_S``): first contact
    through the single-client TPU tunnel takes 60-240+ s even when healthy
    (the same window the bench probes allow), so a shorter default would
    kill healthy runs that a longer probe just admitted. Once the backend
    is up, subsequent calls return instantly. For a fast CPU run use
    ``env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu`` (no tunnel dial at
    all) rather than a short timeout.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("LSK_DEVICE_TIMEOUT_S", 300))
    got: list = []
    err: list = []

    def work():
        try:
            got.append(jax.devices())
        except Exception as e:  # noqa: BLE001 - re-raised on the main thread
            err.append(e)

    t = threading.Thread(target=work, daemon=True, name="lsk-device-probe")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise TimeoutError(
            f"no JAX devices after {timeout_s:.0f}s — the accelerator "
            "tunnel may be down or held by another client (it is "
            "single-client). Workarounds: run on CPU with "
            "`env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu`, or raise "
            "LSK_DEVICE_TIMEOUT_S.")
    if err:
        raise err[0]
    return got[0]


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host lifecycle init (no-op on a single host).

    Mirrors ``MPI_Init`` in the reference (unorderedDataVariant.cu:107); on
    TPU pods the runtime usually autodetects everything, so explicit args
    are only needed off-TPU. On the CPU backend (the multi-node-without-a-
    cluster fixture) cross-process collectives ride gloo.
    """
    if num_processes is not None and num_processes > 1:
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:  # lsk: allow[except-swallow] compat probe:
                pass  # older jax has no gloo option; collectives still default
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    elif os.environ.get("COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def get_mesh(num_shards: int | None = None) -> Mesh:
    """1-D device mesh over the first ``num_shards`` devices (default: all).

    The mesh axis plays the role of the MPI communicator: axis index == rank,
    axis size == world size.
    """
    devices = acquire_devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devices)} devices "
            f"are visible (set XLA_FLAGS=--xla_force_host_platform_device_count "
            f"for CPU testing)")
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


def shard_axis_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS]


def my_mesh_positions(mesh: Mesh) -> list[int]:
    """Mesh positions whose devices this process hosts (ascending, so the
    concatenated local block matches global index order).

    Validates — identically on EVERY host, before any collective — that each
    launched process owns at least one mesh position. When the requested
    shard count is smaller than the pod's device count, ``get_mesh`` takes a
    device prefix and can exclude every device of some process; that host
    would then feed an empty block to ``make_array_from_process_local_data``
    while the others block forever inside the collective — a silent
    distributed hang. Raising the same error everywhere turns it into a
    clean failure. Shared by the batch multi-host CLIs (cli/multihost.py)
    and the multi-host serving engine (serve/engine.py)."""
    mesh_devs = list(mesh.devices.ravel())
    owners = {d.process_index for d in mesh_devs}
    missing = sorted(set(range(jax.process_count())) - owners)
    if missing:
        raise RuntimeError(
            f"mesh of {len(mesh_devs)} device(s) excludes all devices of "
            f"process(es) {missing} of {jax.process_count()}; every launched "
            "process must own at least one mesh position — increase --shards "
            "(or the partition-file count) or launch fewer hosts")
    my_pos = [i for i, d in enumerate(mesh_devs)
              if d.process_index == jax.process_index()]
    assert my_pos == sorted(my_pos)
    return my_pos


def pvary(x):
    """Mark a replicated value as device-varying along AXIS.

    JAX's varying-manual-axes typing requires scan/while carries inside
    shard_map to keep a consistent varying type; freshly-initialized
    constants (e.g. empty candidate heaps) start replicated and must be cast
    before entering a loop whose body mixes them with sharded data.
    Idempotent: leaves already varying along AXIS pass through unchanged.
    On older jax (no ``lax.pcast``) there is no varying-manual-axes type
    system to satisfy, so this is the identity (utils/compat.py).
    """
    if not hasattr(jax.lax, "pcast"):
        return x

    def cast(a):
        vma = getattr(jax.typeof(a), "vma", frozenset())
        if AXIS in vma:
            return a
        return jax.lax.pcast(a, (AXIS,), to="varying")

    return jax.tree.map(cast, x)
